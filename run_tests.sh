#!/bin/bash
# CPU test runner. Unsetting PALLAS_AXON_POOL_IPS skips the site-level TPU
# plugin registration (which claims the exclusive device grant and can block
# behind any other live JAX process); tests run on an 8-device virtual CPU
# mesh regardless (tests/conftest.py).
cd "$(dirname "$0")"
# Gate 1: the JAX-aware static-analysis rules (DP101-DP106) over the package
# and tools — pure ast/tokenize logic, never initializes a jax backend,
# fails on any finding.
python -m dorpatch_tpu.analysis dorpatch_tpu tools || exit $?
echo "static analysis: OK"
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m pytest tests/ -q "$@" \
  || exit $?
# Smoke: the offline telemetry report CLI must render the checked-in fixture
# results dir end-to-end (tests/test_report.py covers the content; this
# covers the `python -m` entry point itself).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python -m dorpatch_tpu.observe.report tests/fixtures/report_run \
  > /dev/null || exit $?
echo "report CLI smoke: OK"
