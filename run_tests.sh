#!/bin/bash
# CPU test runner. Unsetting PALLAS_AXON_POOL_IPS skips the site-level TPU
# plugin registration (which claims the exclusive device grant and can block
# behind any other live JAX process); tests run on an 8-device virtual CPU
# mesh regardless (tests/conftest.py).
cd "$(dirname "$0")"
# Gate 1: the JAX-aware static-analysis rules (DP101-DP108) over the package
# and tools — pure ast/tokenize logic, never initializes a jax backend,
# fails on any finding.
python -m dorpatch_tpu.analysis dorpatch_tpu tools || exit $?
echo "static analysis: OK"
# Gate 1b: the concurrency tier (DP500-DP504) over the threaded packages —
# guarded-by lock discipline, lock-order cycles, blocking calls under locks,
# thread lifecycle, wall-clock liveness. Same stdlib-only engine; the
# dedicated mode keeps the deadlock audit loud even when the default gate's
# select set is narrowed.
python -m dorpatch_tpu.analysis --concurrency dorpatch_tpu tools || exit $?
echo "concurrency analysis (--concurrency): OK"
# Gate 2: the jaxpr-level program auditor (DP200-DP206) — abstractly traces
# every registered production jit entry point on CPU (attack block/sweep,
# defense predict tables, train init/step/eval, model init, serve buckets,
# sharded masked-fill on the 8-device virtual mesh). Trace-only: zero device
# FLOPs; the timeout is the wall-clock budget (enumeration + tracing runs in
# ~10 s, 120 s leaves room for a cold machine).
timeout -k 10 120 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m dorpatch_tpu.analysis --trace || exit $?
echo "program audit (--trace): OK"
# Gate 3: the program-baseline drift gate (DP300-DP304) — fingerprints +
# static cost vectors for every registered entry point, diffed against the
# checked-in analysis/baselines.json (same 8-device virtual mesh the
# baseline was generated under). Compiled-cost mode runs XLA's cost
# analysis per program (~90 s warm); 420 s is the cold-machine budget. An
# intentional program change regenerates the file in the same PR:
#   python -m dorpatch_tpu.analysis --baseline update
timeout -k 10 420 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m dorpatch_tpu.analysis --baseline check || exit $?
echo "program baseline (--baseline check): OK"
# Gate 4: the sharding & collectives auditor (DP600-DP603) — prices every
# explicit collective in every registered entry point (operand bytes x
# mesh-axis size), flags unpriceable collectives, accidental replication,
# boundary reshards, and any Pallas kernel a mesh program runs outside its
# shard_map wrapper (the shard-local proof). Trace-only, same 8-device
# virtual mesh as the trace gate.
timeout -k 10 120 env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python -m dorpatch_tpu.analysis --comms || exit $?
echo "comms audit (--comms): OK"
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m pytest tests/ -q "$@" \
  || exit $?
# Smoke: the offline telemetry report CLI must render the checked-in fixture
# results dir end-to-end (tests/test_report.py covers the content; this
# covers the `python -m` entry point itself).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python -m dorpatch_tpu.observe.report tests/fixtures/report_run \
  > /dev/null || exit $?
echo "report CLI smoke: OK"
# Smoke: the serving layer end-to-end — stand up the in-process
# certified-inference service (stub victim), fire the load generator at it,
# require every request to succeed with ZERO recompiles after warmup, and
# require the report CLI to render the serve section (latency percentiles,
# occupancy, reject rate) from the resulting events.jsonl.
SERVE_SMOKE=$(mktemp -d)
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/loadgen.py --requests 16 --stub-victim \
  --results-dir "$SERVE_SMOKE" --out "$SERVE_SMOKE/loadgen.json" \
  > /dev/null || exit $?
grep -q '"ok": 16' "$SERVE_SMOKE/loadgen.json" \
  || { echo "serve smoke: not all 16 requests ok:"; \
       cat "$SERVE_SMOKE/loadgen.json"; exit 1; }
grep -q '"zero_recompile": true' "$SERVE_SMOKE/loadgen.json" \
  || { echo "serve smoke: hot path retraced:"; \
       cat "$SERVE_SMOKE/loadgen.json"; exit 1; }
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python -m dorpatch_tpu.observe.report "$SERVE_SMOKE" \
  | grep -q -e "-- serve --" \
  || { echo "serve smoke: report missing serve section"; exit 1; }
rm -rf "$SERVE_SMOKE"
echo "serve loadgen smoke: OK"
# Smoke: pruned double-masking certification — the same seeded stub batch
# through the exhaustive oracle (--prune off) and the production two-phase
# schedule must yield bit-identical verdicts while the pruned run executes
# strictly fewer masked forwards (tools/certify_prune_smoke.py exits
# non-zero and lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/certify_prune_smoke.py \
  | grep -q '"parity": true' \
  || { echo "certify-prune smoke: parity/forward-count violation"; exit 1; }
echo "certify prune smoke: OK"
# Smoke: mask-aware incremental certification — the token-pruned ViT path
# must reproduce the PR 5 pruned-only verdicts on a seeded batch while
# executing strictly fewer forward-equivalents, and the conv masked-stem
# fold must be bit-exact (tools/certify_incr_smoke.py exits non-zero and
# lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/certify_incr_smoke.py \
  | grep -q '"parity": true' \
  || { echo "certify-incr smoke: parity/forward-equivalents violation"; exit 1; }
echo "certify incr smoke: OK"
# Smoke: mixed-precision certification — the same seeded batch certified at
# compute_dtype="float32" and "bfloat16" must yield identical verdicts
# (identical-or-escalated: near-boundary images re-run the f32 exhaustive
# program), and every defense.*.bf16.* entry in the checked-in program
# baseline bank must predict STRICTLY fewer HBM bytes than its f32 twin
# (tools/certify_bf16_smoke.py exits non-zero and lists the violations
# otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/certify_bf16_smoke.py \
  | grep -q '"parity": true' \
  || { echo "certify-bf16 smoke: parity/bytes violation"; exit 1; }
echo "certify bf16 smoke: OK"
# Smoke: the Pallas kernel tier — the same seeded batch through the
# engine-backed pruned certify with use_pallas="off" (pure XLA) and
# use_pallas="interpret" (the kernel bodies emulated on CPU; the lowered
# TPU path shares them) must agree per each kernel's exactness contract
# (stem/mixer bit-identical, token verdict parity), with ZERO recompiles
# on the kernel side under the armed watchdog (tools/kernel_smoke.py
# exits non-zero and lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/kernel_smoke.py \
  | grep -q '"parity": true' \
  || { echo "kernel smoke: kernel-tier parity/recompile violation"; exit 1; }
echo "kernel smoke: OK"
# Smoke: sharded pruned certification — the same seeded stub batch through
# the single-chip pruned oracle, the meshed exhaustive sweep, and the meshed
# two-phase pruned schedule (phase-2 worklists planned shard-locally,
# dispatched as [S * bucket] SPMD waves on a 4x2 virtual mesh) must yield
# bit-identical verdicts, count exactly the oracle's forwards, execute
# strictly fewer than exhaustive, and the report CLI must render the prune
# rate from the meshed run dir (tools/certify_mesh_smoke.py exits non-zero
# and lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/certify_mesh_smoke.py \
  | grep -q '"parity": true' \
  || { echo "certify-mesh smoke: parity/forward-count violation"; exit 1; }
echo "certify mesh smoke: OK"
# Smoke: fault-tolerant attack-sweep farm — submit a 4-job grid, SIGKILL a
# chaos worker mid-job after its carry snapshot lands, then drain with two
# healthy workers: every job must finish, the killed job must show
# attempts==2 / reclaims==1 and a checkpoint-resumed point whose final
# artifacts are bit-identical to an uninterrupted control run, and the
# fleet report must render the retry accounting (tools/farm_smoke.py exits
# non-zero and lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/farm_smoke.py \
  | grep -q '"ok": true' \
  || { echo "farm smoke: crash-resume violation"; exit 1; }
echo "farm smoke: OK"
# Smoke: the AOT executable store — build a store from a cold serve boot,
# then a strict warm boot must reach serving-ready with ZERO traces under
# the armed recompile watchdog and answer with verdicts identical to the
# cold service; a planted stale fingerprint must force exactly one
# compile-and-rewrite, and `python -m dorpatch_tpu.aot build` must refuse
# to write against a failing --baseline check (tools/aot_smoke.py exits
# non-zero and lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/aot_smoke.py \
  | grep -q '"ok": true' \
  || { echo "aot smoke: warm-boot/zero-trace violation"; exit 1; }
echo "aot smoke: OK"
# Smoke: supervised replica serving — a 2-replica service boots strictly
# from an AOT store, chaos wedges replica 0 mid-batch under load, and the
# failover contract must hold: every request answered ok exactly once with
# verdicts bit-identical to a 1-replica unfaulted control, the wedged
# replica quarantined and restarted through the store with ZERO traces
# under the armed watchdog, and the report rendering `-- replicas --`
# (tools/serve_chaos_smoke.py exits non-zero and lists the violations
# otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/serve_chaos_smoke.py \
  | grep -q '"ok": true' \
  || { echo "serve chaos smoke: failover/restart violation"; exit 1; }
echo "serve chaos smoke: OK"
# Smoke: the continuous re-certification platform — a control scheduler runs
# one full 2x2 (patch_budget x density) generation through real farm
# workers; a chaos scheduler is SIGKILLed mid-generation with a torn
# recert_state.json and its resume must complete the SAME generation with a
# baseline byte-identical to the control's; a planted regression must make
# `recert check` exit 1 naming the cell (DP400); serve must refuse
# serving-ready under --require-recert strict (typed RecertGateError,
# before any compile) while warn boots with the armed watchdog and
# GET /robustness answers 503 rendering the regressed cell
# (tools/recert_smoke.py exits non-zero and lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/recert_smoke.py \
  | grep -q '"ok": true' \
  || { echo "recert smoke: re-certification/gate violation"; exit 1; }
echo "recert smoke: OK"
# Smoke: the fleet metrics plane — a 2-replica service under closed-loop
# load (unfaulted AND with chaos wedging replica 0 mid-batch) must keep
# the client-side attempt counts and the server's serve_requests_total
# series equal BIT-FOR-BIT (exactly-once across failover re-dispatch),
# the Prometheus text exposition must round-trip to the same numbers,
# and `observe.report --fleet` must join the run dirs on trace ids with
# ZERO orphans and a consistent verdict (tools/metrics_smoke.py exits
# non-zero and lists the violations otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/metrics_smoke.py \
  | grep -q '"ok": true' \
  || { echo "metrics smoke: client/server reconciliation violation"; exit 1; }
echo "metrics smoke: OK"
# Smoke: the horizontal serve fleet — two serve SUBPROCESSES strict-boot
# from a shared AOT store behind the stdlib gateway; 24 closed-loop
# requests route with answers bit-identical to direct service calls while
# chaos SIGKILLs one backend mid-load (every request answered EXACTLY
# once via connection-level retry, the corpse health-ejected); a canary
# deploy from a second store version is poisoned with a DP400 robustness
# verdict and must roll back automatically (typed gateway.rollback event
# + restored stable weights); `observe.report --fleet` must reconcile the
# client==gateway==sum-of-backends counter chain with ZERO orphaned trace
# ids (tools/gateway_smoke.py exits non-zero and lists the violations
# otherwise).
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  python tools/gateway_smoke.py \
  | grep -q '"ok": true' \
  || { echo "gateway smoke: fleet routing/rollback violation"; exit 1; }
echo "gateway smoke: OK"
