#!/bin/bash
# CPU test runner. Unsetting PALLAS_AXON_POOL_IPS skips the site-level TPU
# plugin registration (which claims the exclusive device grant and can block
# behind any other live JAX process); tests run on an 8-device virtual CPU
# mesh regardless (tests/conftest.py).
cd "$(dirname "$0")"
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m pytest tests/ -q "$@"
