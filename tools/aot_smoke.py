#!/usr/bin/env python
"""AOT executable-store smoke: zero-trace warm boot end-to-end (CI gate,
`run_tests.sh`).

Five phases, one process, one throwaway store, one stub victim:

A. COLD — a service with no store boots (traces + compiles everything),
   answers a seeded batch; its verdicts are the parity reference.
B. BUILD — a fresh service in mode "auto" against the empty store misses
   everywhere, compiles, and populates one entry per serving program.
C. WARM — a fresh service in mode "strict" boots purely from the store
   with the recompile watchdog ARMED (`enforce_budgets=True` arms it
   before the warm boot runs): every program must hit, the total trace
   count must be 0 after boot AND after live traffic, and the verdicts on
   the same seeded batch must equal phase A's.
D. DRIFT — one manifest fingerprint is planted stale; an "auto" boot must
   miss exactly that program, recompile it, and REWRITE the entry back to
   the live fingerprint (never serve stale).
E. REFUSE — `python -m dorpatch_tpu.aot build` against a doctored
   baselines.json (one fingerprint flipped) must exit 1 and write nothing.

Prints ONE JSON line: {"metric": "aot_smoke", "ok": true, ...}; exits
non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.aot import build as aot_build
    from dorpatch_tpu.aot.store import MANIFEST
    from dorpatch_tpu.config import AotConfig, DefenseConfig, ServeConfig

    # the serve smoke's stub victim shape: deterministic, jit-friendly,
    # classes depend on mean brightness so masking can flip verdicts.
    # A FRESH closure per service: jax.jit shares its trace cache across
    # wrappers of the same function object, so reusing one apply_fn would
    # leak the cold phase's trace counts into the warm service's
    # zero-trace accounting.
    num_classes, img = 5, 32

    def make_apply():
        def apply_fn(params, x):
            s = x.mean(axis=(1, 2, 3))
            return jax.nn.one_hot((s * 7.0).astype(jnp.int32) % num_classes,
                                  num_classes)
        return apply_fn

    serve_cfg = ServeConfig(max_batch=4, bucket_sizes=(1, 4))
    defense_cfg = DefenseConfig(ratios=(0.1,), chunk_size=64)
    rng = np.random.default_rng(0)
    images = rng.uniform(0.0, 1.0, (6, img, img, 3)).astype(np.float32)

    from dorpatch_tpu.serve.service import CertifiedInferenceService

    def make(aot_cfg):
        return CertifiedInferenceService(
            make_apply(), None, num_classes, img, serve_cfg=serve_cfg,
            defense_cfg=defense_cfg, aot_cfg=aot_cfg)

    def drive(svc):
        out = []
        for im in images:
            r = svc.predict(im, deadline_ms=60000)
            if r.status != "ok":
                raise AssertionError(f"predict failed: {r!r}")
            out.append((r.prediction, r.certified, r.clean_prediction))
        return out

    failures = []
    stats = {"metric": "aot_smoke"}
    store_dir = tempfile.mkdtemp(prefix="aot-smoke-store-")
    refuse_dir = tempfile.mkdtemp(prefix="aot-smoke-refuse-")
    doctored = tempfile.NamedTemporaryFile(
        mode="w", suffix=".json", delete=False)
    try:
        # ---- A: cold reference ----
        cold = make(None)
        cold.start()
        n_programs = len(cold.trace_entrypoints())
        want = drive(cold)
        cold_traces = sum(cold.trace_counts().values())
        cold.stop()
        stats["programs"] = n_programs
        stats["cold_trace_count"] = cold_traces
        if cold_traces <= 0:
            failures.append("cold service reports zero traces — the "
                            "trace accounting this smoke relies on is dead")

        # ---- B: populate the store ----
        builder = make(AotConfig(cache_dir=store_dir, mode="auto"))
        builder.start()
        bstats = builder._aot_stats or {}
        builder.stop()
        stats["build"] = {"hits": bstats.get("hits"),
                          "misses": bstats.get("misses"),
                          "builds": bstats.get("builds")}
        if bstats.get("builds") != n_programs:
            failures.append(
                f"build pass wrote {bstats.get('builds')} entries, expected "
                f"{n_programs} (one per serving program)")

        # ---- C: strict warm boot under the armed watchdog ----
        warm = make(AotConfig(cache_dir=store_dir, mode="strict"))
        warm.start()   # AotBootError here IS the failure: strict miss
        wstats = warm._aot_stats or {}
        boot_traces = sum(warm.trace_counts().values())
        got = drive(warm)
        traffic_traces = sum(warm.trace_counts().values())
        warm.stop()
        stats["warm"] = {"hits": wstats.get("hits"),
                         "misses": wstats.get("misses"),
                         "boot_trace_count": boot_traces,
                         "traffic_trace_count": traffic_traces}
        if wstats.get("hits") != n_programs or wstats.get("misses", 1) != 0:
            failures.append(
                f"strict warm boot: {wstats.get('hits')} hits / "
                f"{wstats.get('misses')} misses, expected {n_programs}/0")
        if boot_traces != 0:
            failures.append(
                f"warm boot traced {boot_traces} program(s) — the "
                f"zero-trace contract is broken at startup")
        if traffic_traces != 0:
            failures.append(
                f"warm traffic traced {traffic_traces} program(s) under "
                f"the armed watchdog")
        if got != want:
            failures.append(f"verdict parity broke: cold {want} "
                            f"vs warm {got}")

        # ---- D: planted fingerprint drift -> exactly one rebuild ----
        mpath = os.path.join(store_dir, MANIFEST)
        with open(mpath) as fh:
            manifest = json.load(fh)
        victim_name = sorted(manifest["entries"])[0]
        live_fp = manifest["entries"][victim_name]["fingerprint"]
        manifest["entries"][victim_name]["fingerprint"] = "0" * 16
        with open(mpath, "w") as fh:
            json.dump(manifest, fh)
        drift = make(AotConfig(cache_dir=store_dir, mode="auto"))
        drift.start()
        dstats = drift._aot_stats or {}
        drift.stop()
        stats["drift"] = {"victim": victim_name,
                          "misses": dstats.get("misses"),
                          "builds": dstats.get("builds"),
                          "miss_reasons": dstats.get("miss_reasons")}
        if dstats.get("misses") != 1 or dstats.get("builds") != 1:
            failures.append(
                f"planted drift on {victim_name}: {dstats.get('misses')} "
                f"miss(es) / {dstats.get('builds')} build(s), expected 1/1")
        with open(mpath) as fh:
            rewritten = json.load(fh)["entries"][victim_name]["fingerprint"]
        if rewritten != live_fp:
            failures.append(
                f"drifted entry {victim_name} was not rewritten to the "
                f"live fingerprint ({rewritten!r} != {live_fp!r})")

        # ---- E: aot build refuses on a failing --baseline check ----
        from dorpatch_tpu.analysis.baseline import baseline_path

        with open(baseline_path()) as fh:
            baseline = json.load(fh)
        name = sorted(baseline["entries"])[0]
        baseline["entries"][name]["fingerprint"] = "0" * 16
        json.dump(baseline, doctored)
        doctored.close()
        rc = aot_build.main(["build", "--store", refuse_dir,
                             "--baseline-file", doctored.name])
        wrote = os.path.exists(os.path.join(refuse_dir, MANIFEST))
        stats["refuse"] = {"rc": rc, "wrote_manifest": wrote}
        if rc != 1:
            failures.append(f"aot build against a drifted baseline "
                            f"returned rc={rc}, expected 1 (refusal)")
        if wrote:
            failures.append("aot build wrote a manifest despite refusing")
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)
        shutil.rmtree(refuse_dir, ignore_errors=True)
        try:
            os.unlink(doctored.name)
        except OSError:
            pass

    stats["ok"] = not failures
    stats["failures"] = failures
    print(json.dumps(stats))
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
