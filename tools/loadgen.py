#!/usr/bin/env python
"""Open/closed-loop load generator for the certified-inference service.

Drives `dorpatch_tpu.serve` and prints ONE BENCH-style JSON line (stdout):
throughput, latency percentiles, status mix, batch occupancy, and —
in-process — whether the zero-recompile contract held (per-program trace
counts identical before and after traffic).

Modes:
- **closed loop** (default): `--concurrency` workers each keep exactly one
  request in flight — classic latency-vs-throughput operating point. A 503
  `overloaded` reject is retried in place (same worker, same slot) after
  the shared capped-exponential backoff (`dorpatch_tpu.backoff`), up to
  `--max-retries`; the JSON line reports how many retries that took.
- **open loop**: requests arrive at `--rate` per second regardless of
  completions — the overload probe; expect typed `overloaded` rejects once
  the arrival rate outruns the service, never unbounded queueing.

Targets:
- default: an IN-PROCESS service (no sockets), built over a stub victim
  (`--stub-victim`, cheap brightness classifier — the CI smoke) or the
  configured real model. `--results-dir` keeps its telemetry so
  `python -m dorpatch_tpu.observe.report <dir>` renders the serve section.
- `--url http://host:port`: an already-running HTTP front-end
  (`python -m dorpatch_tpu.serve`); this process then never initializes an
  accelerator backend (pure sockets + the host-only percentile helper).
- `--url ... --fleet`: the target is a **gateway**
  (`python -m dorpatch_tpu.gateway`) fronting N serve processes. The JSON
  line gains a `fleet` section with per-backend attribution (which backend
  answered each request, read from the `gateway` envelope the gateway
  stamps into every response), gateway-side connection retries, and
  whether a rolling-deploy rollback happened during the run (gateway
  `/stats` diff). `--expect-metrics` then reconciles against the
  gateway's `gateway_requests_total` instead of `serve_requests_total` —
  the gateway is the process that owes the client an exactly-once answer;
  `observe.report --fleet` covers the gateway↔backend leg.

Every ATTEMPT (each predict call, so an overloaded reject that gets
retried counts once per try — exactly how the server counts it) lands in
a client-side `observe.MetricRegistry` counter `loadgen_requests_total`.
`--expect-metrics` then reconciles that counter against the server's
`serve_requests_total` series — in-process by reading the service
registry, over `--url` by scraping `GET /metrics` before and after the
run and diffing — and exits non-zero on any per-status mismatch. With
`--results-dir` the client registry is dumped to `metrics_client.json`
there so `observe.report --fleet` can cross-check runs after the fact.

Examples:
  python tools/loadgen.py --requests 16 --stub-victim --results-dir /tmp/s
  python tools/loadgen.py --requests 200 --mode open --rate 100 \
      --url http://127.0.0.1:8700 --expect-metrics
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_images(n: int, img_size: int, seed: int) -> np.ndarray:
    """Deterministic smooth-ish random images, HWC float32 in [0, 1]."""
    rng = np.random.default_rng(seed)
    base = rng.uniform(0.0, 1.0, (n, 4, 4, 3)).astype(np.float32)
    return np.clip(np.kron(base, np.ones((1, img_size // 4, img_size // 4, 1),
                                         np.float32)), 0.0, 1.0)


def _http_predict(url: str, image: np.ndarray, deadline_ms: float) -> dict:
    import urllib.error
    import urllib.request

    body = json.dumps({"image": image.tolist(),
                       "deadline_ms": deadline_ms}).encode("utf-8")
    req = urllib.request.Request(
        url.rstrip("/") + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=deadline_ms / 1e3 + 60) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:  # typed rejects ride error codes
        try:
            return json.loads(e.read())
        except ValueError:
            return {"status": "error", "reason": f"http {e.code}"}
    except (urllib.error.URLError, OSError) as e:
        return {"status": "error", "reason": repr(e)}


def _scrape_server_counts(url: str,
                          counter: str = "serve_requests_total") -> dict:
    """`counter` by status from a live `GET /metrics` (a serve process's
    `serve_requests_total`, or the gateway's `gateway_requests_total`)."""
    import urllib.request

    from dorpatch_tpu.observe import parse_exposition

    with urllib.request.urlopen(url.rstrip("/") + "/metrics", timeout=30) as r:
        parsed = parse_exposition(r.read().decode("utf-8"))
    out: dict = {}
    for key, value in (parsed.get(counter) or {}).items():
        for k, v in key:
            if k == "status":
                out[v] = out.get(v, 0.0) + value
    return out


def _scrape_gateway_rollbacks(url: str) -> int:
    """`rollbacks` counter from the gateway's `GET /stats`."""
    import urllib.request

    with urllib.request.urlopen(url.rstrip("/") + "/stats", timeout=30) as r:
        stats = json.loads(r.read())
    return int(stats.get("rollbacks", 0))


def _reconcile(client_by_status: dict, server_by_status: dict) -> dict:
    """Per-status exact cross-check: client attempts vs server answers."""
    rows, ok = [], True
    for s in sorted(set(client_by_status) | set(server_by_status)):
        c = int(round(float(client_by_status.get(s, 0))))
        v = int(round(float(server_by_status.get(s, 0))))
        rows.append({"status": s, "client": c, "server": v, "ok": c == v})
        ok = ok and c == v
    return {"ok": ok, "by_status": rows}


def _build_inprocess_service(args):
    """In-process target; imports jax lazily so --url runs stay host-only."""
    from dorpatch_tpu.config import DefenseConfig, ExperimentConfig, ServeConfig

    serve_cfg = ServeConfig(max_batch=args.max_batch,
                            max_queue_depth=args.queue_depth,
                            deadline_ms=args.deadline_ms)
    defense_cfg = DefenseConfig(ratios=tuple(args.ratios))
    from dorpatch_tpu.serve import CertifiedInferenceService

    if args.stub_victim:
        import jax
        import jax.numpy as jnp

        def apply_fn(params, x):
            # brightness-bucket classifier: occlusion-sensitive, no weights
            s = x.mean(axis=(1, 2, 3))
            return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

        return CertifiedInferenceService(
            apply_fn, None, num_classes=5, img_size=args.img_size,
            serve_cfg=serve_cfg, defense_cfg=defense_cfg,
            result_dir=args.results_dir or None,
            run_cfg=ExperimentConfig(dataset="cifar10", img_size=args.img_size,
                                     serve=serve_cfg, defense=defense_cfg))
    cfg = ExperimentConfig(dataset="cifar10", base_arch=args.arch,
                           img_size=args.img_size, serve=serve_cfg,
                           defense=defense_cfg, synthetic_data=True)
    return CertifiedInferenceService.from_config(
        cfg, result_dir=args.results_dir or None)


def run_load(send, images: np.ndarray, args, metrics=None) -> dict:
    """Fire the workload; returns per-request (status, latency_s) tuples
    aggregated into the report dict. When `metrics` (a client-side
    MetricRegistry) is given, every attempt increments
    `loadgen_requests_total{status=...}` — one inc per predict call, the
    same granularity the server's `serve_requests_total` uses."""
    results = []
    retry = {"total": 0, "requests_retried": 0, "exhausted": 0}
    # --fleet: per-backend attribution from the `gateway` envelope the
    # gateway stamps into every answer (terminal answers only — an
    # overloaded reject retried in place re-attributes on the next try)
    fleet = {"by_backend": {}, "gateway_retries": 0}
    res_lock = threading.Lock()
    m_attempts = (metrics.counter(
        "loadgen_requests_total",
        help="client-side attempts by terminal status (one per predict "
             "call, retries counted individually)")
        if metrics is not None else None)
    # closed loop only: an open-loop run MEASURES the overload response, so
    # retrying there would rewrite the arrival process it exists to impose
    retries = args.max_retries if args.mode == "closed" else 0

    def fire(i: int) -> None:
        from dorpatch_tpu.backoff import retry_delay

        t0 = time.perf_counter()
        attempt = 0
        while True:
            resp = send(images[i % len(images)], args.deadline_ms)
            status = (resp.get("status", "error") if isinstance(resp, dict)
                      else resp.status)
            if m_attempts is not None:
                m_attempts.inc(status=str(status))
            if status != "overloaded" or attempt >= retries:
                break
            attempt += 1
            time.sleep(retry_delay(f"loadgen-{i}", attempt,
                                   base=args.retry_base, cap=args.retry_cap))
        dt = time.perf_counter() - t0
        gw = (resp.get("gateway") if isinstance(resp, dict) else None) or {}
        with res_lock:
            results.append((status, dt))
            if attempt:
                retry["total"] += attempt
                retry["requests_retried"] += 1
                if status == "overloaded":
                    retry["exhausted"] += 1
            if getattr(args, "fleet", False):
                backend = gw.get("backend") or "(gateway)"
                fleet["by_backend"][backend] = (
                    fleet["by_backend"].get(backend, 0) + 1)
                fleet["gateway_retries"] += int(gw.get("retries", 0))

    t_start = time.perf_counter()
    if args.mode == "closed":
        nxt = {"i": 0}

        def worker() -> None:
            while True:
                with res_lock:
                    i = nxt["i"]
                    if i >= args.requests:
                        return
                    nxt["i"] = i + 1
                fire(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(args.concurrency)]
        for t in threads:
            t.start()
    else:
        # open loop: scheduled arrivals at --rate req/sec. Threads spawn
        # LAZILY at each request's arrival instant (live thread count =
        # in-flight requests, not --requests), so a big run doesn't burn
        # a stack per future request or measure scheduler churn
        threads = []
        for i in range(args.requests):
            delay = t_start + i / args.rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=fire, args=(i,), daemon=True)
            t.start()
            threads.append(t)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    by_status = {}
    for status, _ in results:
        by_status[status] = by_status.get(status, 0) + 1
    ok = sorted(dt for status, dt in results if status == "ok")

    def pct(q):
        # the shared nearest-rank formula: this line, the service's /stats,
        # and the report CLI must agree on the same samples
        from dorpatch_tpu.observe import nearest_rank_percentile

        v = nearest_rank_percentile(ok, q)
        return None if v is None else round(v * 1e3, 3)

    total = len(results)
    report = {
        "metric": "serve_load",
        "mode": args.mode,
        "requests": total,
        "wall_seconds": round(wall, 3),
        "by_status": dict(sorted(by_status.items())),
        "throughput_rps": round(by_status.get("ok", 0) / wall, 3)
        if wall else 0.0,
        "latency_ms": {"p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
                       "count": len(ok)},
        "reject_rate": round(by_status.get("overloaded", 0) / total, 4)
        if total else 0.0,
        "retries": dict(retry),
    }
    if getattr(args, "fleet", False):
        report["fleet"] = {
            "by_backend": dict(sorted(fleet["by_backend"].items())),
            "gateway_retries": fleet["gateway_retries"],
        }
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="load generator for the certified-inference service")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--mode", choices=["closed", "open"], default="closed")
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop in-flight requests")
    p.add_argument("--rate", type=float, default=50.0,
                   help="open-loop arrival rate (req/sec)")
    p.add_argument("--deadline-ms", type=float, default=5000.0)
    p.add_argument("--max-retries", type=int, default=4,
                   help="closed loop: retry an `overloaded` reject this "
                        "many times (0 disables); open loop never retries")
    p.add_argument("--retry-base", type=float, default=0.05,
                   help="first-retry backoff seconds (doubles per attempt)")
    p.add_argument("--retry-cap", type=float, default=2.0,
                   help="backoff ceiling seconds")
    p.add_argument("--url", default="",
                   help="target a running HTTP front-end instead of an "
                        "in-process service")
    p.add_argument("--fleet", action="store_true",
                   help="--url targets a gateway (python -m "
                        "dorpatch_tpu.gateway): report per-backend "
                        "attribution + rollbacks, reconcile "
                        "--expect-metrics against gateway_requests_total")
    p.add_argument("--stub-victim", action="store_true",
                   help="serve a weightless brightness classifier (fast "
                        "CI smoke) instead of a real model")
    p.add_argument("--arch", default="resnet18")
    p.add_argument("--img-size", type=int, default=32)
    p.add_argument("--ratios", type=float, nargs="+", default=[0.1],
                   help="defense bank patch ratios (in-process target)")
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--queue-depth", type=int, default=32)
    p.add_argument("--results-dir", default="",
                   help="keep the in-process service's telemetry here "
                        "(run.json + events.jsonl for the report CLI)")
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--expect-metrics", action="store_true",
                   help="reconcile client-side attempt counts against the "
                        "server's serve_requests_total series exactly; "
                        "exit 1 on any per-status mismatch")
    p.add_argument("--out", default="", help="also write the JSON here")
    args = p.parse_args(argv)

    from dorpatch_tpu.observe import MetricRegistry, labeled_values

    if args.fleet and not args.url:
        p.error("--fleet requires --url (a running gateway)")

    images = make_images(min(args.requests, 64), args.img_size, args.seed)
    client_metrics = MetricRegistry()
    server_counts = None

    if args.url:
        # against a gateway the exactly-once contract the client can check
        # is the gateway's own admission counter; the gateway↔backend leg
        # belongs to `observe.report --fleet`
        counter = ("gateway_requests_total" if args.fleet
                   else "serve_requests_total")
        server_before = (_scrape_server_counts(args.url, counter)
                         if args.expect_metrics else {})
        rollbacks_before = (_scrape_gateway_rollbacks(args.url)
                            if args.fleet else 0)
        report = run_load(
            lambda img, dl: _http_predict(args.url, img, dl), images, args,
            metrics=client_metrics)
        report["target"] = args.url
        if args.fleet:
            report["fleet"]["rollbacks_observed"] = (
                _scrape_gateway_rollbacks(args.url) - rollbacks_before)
        if args.expect_metrics:
            server_after = _scrape_server_counts(args.url, counter)
            server_counts = {
                s: server_after.get(s, 0.0) - server_before.get(s, 0.0)
                for s in set(server_after) | set(server_before)}
    else:
        service = _build_inprocess_service(args)
        with service:
            before = service.trace_counts()
            report = run_load(
                lambda img, dl: service.predict(img, deadline_ms=dl).to_dict(),
                images, args, metrics=client_metrics)
            after = service.trace_counts()
            stats = service.stats()
            if args.expect_metrics:
                server_counts = labeled_values(
                    service.metrics.snapshot(), "serve_requests_total",
                    "status")
        report["target"] = "in-process"
        report["occupancy"] = stats["occupancy"]
        report["trace_counts"] = after
        report["zero_recompile"] = before == after

    exit_code = 0
    if args.expect_metrics:
        client_counts = labeled_values(
            client_metrics.snapshot(), "loadgen_requests_total", "status")
        check = _reconcile(client_counts, server_counts or {})
        report["metrics_check"] = check
        if not check["ok"]:
            exit_code = 1
    if args.results_dir:
        os.makedirs(args.results_dir, exist_ok=True)
        client_metrics.dump(
            os.path.join(args.results_dir, "metrics_client.json"))
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    return exit_code


if __name__ == "__main__":
    raise SystemExit(main())
