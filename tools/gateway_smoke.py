#!/usr/bin/env python
"""Fleet gateway smoke: exactly-once routing across real serve processes
(CI gate, `run_tests.sh`).

One parent process (this script) and real `serve` SUBPROCESSES — the
cross-process shape the gateway exists for. The same file doubles as the
backend launcher (`--serve-backend`): a stub-victim certified-inference
service + HTTP front-end on an ephemeral port, announcing its bound port
through a ready-file and draining cleanly on SIGTERM.

Phases:

A. FLEET BOOT — two AOT stores are populated in-parent, then two serve
   backends STRICT-boot from store v1 (strict = provably warm: any miss
   refuses boot instead of compiling) with an `ok` recert verdict behind
   `GET /robustness`. A jax-free in-process gateway probes them healthy.
B. PARITY + CHAOS KILL — 24 closed-loop requests ride POST /predict
   through the gateway while chaos `kill_backend` SIGKILLs backend 2
   mid-load (metrics flushed first — the flush-before-kill contract).
   Every answer must match a direct parent-side service call bit-for-bit
   (label + certified), every request is answered EXACTLY ONCE (the
   router retries connection failures on the survivor, never an admitted
   request), and the gateway ejects the corpse via health probes.
C. CANARY ROLLBACK — a third backend strict-boots from store v2 and
   rolls out via `RollingDeploy`; chaos `poison_canary` plants a DP400
   finding in its robustness verdict, which must roll the fleet back
   automatically (typed `gateway.rollback` event + counter, stable
   weights restored) while the fleet keeps serving.
D. FLEET REPORT — `observe.report --fleet` over client + gateway + all
   three backend dirs must reconcile the three-way counter chain
   (client == gateway == sum of backends, the killed backend's
   unresolved batch counted NOWHERE) with ZERO orphaned trace ids, and
   render the rollback trail.

Prints ONE JSON line: {"metric": "gateway_smoke", "ok": true, ...};
exits non-zero on any violation.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_CLASSES, IMG = 5, 32
OK_VERDICT = {"status": "ok", "generation": 1, "worst_margin": 0.25,
              "findings_by_rule": {}, "cells": {}}


def _make_apply():
    """Deterministic weightless brightness classifier (imports jax —
    backend/parity paths only; the gateway itself never does)."""
    import jax
    import jax.numpy as jnp

    def apply_fn(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7.0).astype(jnp.int32) % NUM_CLASSES,
                              NUM_CLASSES)
    return apply_fn


def _build_service(result_dir: str, aot_store: str, aot_mode: str,
                   recert_dir: str, chaos: str):
    from dorpatch_tpu.config import (AotConfig, DefenseConfig, RecertConfig,
                                     ServeConfig)
    from dorpatch_tpu.serve.service import CertifiedInferenceService

    # replicas=1 on purpose: with one worker loop the kill_backend flush
    # can never race another replica's counter increments, so the victim's
    # on-disk books are exactly its answered requests
    serve_cfg = ServeConfig(max_batch=4, bucket_sizes=(1, 2, 4),
                            deadline_ms=15000.0, replicas=1, chaos=chaos)
    return CertifiedInferenceService(
        _make_apply(), None, NUM_CLASSES, IMG,
        serve_cfg=serve_cfg,
        defense_cfg=DefenseConfig(ratios=(0.1,), chunk_size=64),
        result_dir=result_dir or None,
        aot_cfg=(AotConfig(cache_dir=aot_store, mode=aot_mode)
                 if aot_store else None),
        recert_cfg=(RecertConfig(dir=recert_dir, require="warn")
                    if recert_dir else None))


# ------------------------------------------------- backend launcher mode


def serve_backend_main(args) -> int:
    from dorpatch_tpu.serve.http import HttpFrontend

    svc = _build_service(args.result_dir, args.aot_store, args.aot_mode,
                         args.recert_dir, args.chaos)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    with svc, HttpFrontend(svc, "127.0.0.1", 0) as fe:
        ready = {"ready": True, "port": fe.port, "pid": os.getpid(),
                 "aot": (svc.stats().get("aot"))}
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(ready, fh)
        os.replace(tmp, args.ready_file)  # atomic: parent never reads half
        while not stop.is_set():
            stop.wait(0.5)
    return 0


# ------------------------------------------------- parent-side helpers


def _spawn_backend(result_dir: str, aot_store: str, recert_dir: str,
                   chaos: str = ""):
    """Launch one backend subprocess; returns (proc, ready_file, logpath)."""
    os.makedirs(result_dir, exist_ok=True)
    ready_file = os.path.join(result_dir, "ready.json")
    logpath = os.path.join(result_dir, "backend.log")
    cmd = [sys.executable, os.path.abspath(__file__), "--serve-backend",
           "--result-dir", result_dir, "--ready-file", ready_file,
           "--aot-store", aot_store, "--aot-mode", "strict",
           "--recert-dir", recert_dir]
    if chaos:
        cmd += ["--chaos", chaos]
    log = open(logpath, "w")
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            env=os.environ.copy())
    return proc, ready_file, logpath


def _await_ready(proc, ready_file: str, logpath: str,
                 timeout_s: float = 600.0) -> dict:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(ready_file):
            with open(ready_file) as fh:
                return json.load(fh)
        if proc.poll() is not None:
            break
        time.sleep(0.2)
    try:
        with open(logpath) as fh:
            tail = fh.read()[-2000:]
    except OSError:
        tail = "(no log)"
    raise RuntimeError(
        f"backend never became ready (exit={proc.poll()}): ...{tail}")


def _get_json(url: str, timeout: float = 30.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post_predict(url: str, payload: dict, timeout: float = 120.0) -> dict:
    body = json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        url.rstrip("/") + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:  # typed rejects ride error codes
        try:
            return json.loads(e.read())
        except ValueError:
            return {"status": "error", "reason": f"http {e.code}"}


def _stop_backend(proc, timeout_s: float = 120.0) -> int:
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
    return proc.returncode


# ------------------------------------------------- the smoke


def run_smoke() -> int:
    import numpy as np

    from dorpatch_tpu.config import GatewayConfig
    from dorpatch_tpu.gateway import Gateway, GatewayFrontend, RollingDeploy
    from dorpatch_tpu.gateway.membership import backend_name
    from dorpatch_tpu.observe import MetricRegistry, labeled_values
    from dorpatch_tpu.observe import report as report_mod

    failures = []
    stats = {"metric": "gateway_smoke"}
    root = tempfile.mkdtemp(prefix="gateway-smoke-")
    d = {name: os.path.join(root, name)
         for name in ("backend1", "backend2", "canary", "gateway", "client",
                      "store_v1", "store_v2", "recert")}
    for path in d.values():
        os.makedirs(path, exist_ok=True)
    with open(os.path.join(d["recert"], "recert_verdict.json"), "w") as fh:
        json.dump(OK_VERDICT, fh)

    procs = []
    try:
        # ---- A: two AOT store versions, then a strict-booted fleet ----
        for store in (d["store_v1"], d["store_v2"]):
            svc = _build_service("", store, "auto", "", "")
            with svc:
                pass  # warm boot populates the store; nothing served
        p1, rf1, lg1 = _spawn_backend(d["backend1"], d["store_v1"],
                                      d["recert"])
        p2, rf2, lg2 = _spawn_backend(d["backend2"], d["store_v1"],
                                      d["recert"], chaos="kill_backend")
        procs += [p1, p2]
        r1 = _await_ready(p1, rf1, lg1)
        r2 = _await_ready(p2, rf2, lg2)
        urls = [f"http://127.0.0.1:{r['port']}" for r in (r1, r2)]
        names = [backend_name(u) for u in urls]
        stats["backends"] = {names[0]: {"aot": bool(r1.get("aot"))},
                             names[1]: {"aot": bool(r2.get("aot")),
                                        "chaos": "kill_backend"}}

        cfg = GatewayConfig(
            backends=tuple(urls), probe_interval_s=0.3, probe_jitter=0.1,
            fail_threshold=2, ok_threshold=1, inflight_cap=32,
            dispatch_retries=2, canary_steps=(0.5, 1.0), canary_hold_s=0.4,
            chaos="poison_canary")
        gateway = Gateway(cfg, result_dir=d["gateway"])
        client = MetricRegistry()
        m_attempts = client.counter(
            "loadgen_requests_total",
            help="client-side attempts by terminal status")
        rng = np.random.default_rng(7)
        images = rng.uniform(0.0, 1.0, (12, IMG, IMG, 3)).astype(np.float32)

        with gateway, GatewayFrontend(gateway, port=0) as fe:
            gw_url = f"http://127.0.0.1:{fe.port}"
            deadline = time.time() + 120
            while time.time() < deadline:
                if gateway.healthz()["routable"] == 2:
                    break
                time.sleep(0.1)
            else:
                failures.append("fleet never probed healthy: "
                                f"{gateway.healthz()}")

            # ---- B: parity + chaos kill mid-load ----
            # ground truth from a DIRECT service call (no gateway, no
            # result_dir so its books stay out of the fleet join)
            parity_svc = _build_service("", "", "off", "", "")
            with parity_svc:
                expected = [parity_svc.predict(img, deadline_ms=15000.0)
                            .to_dict() for img in images]
            # closed loop, concurrency 1: when the chaos kill fires,
            # every previously-answered request has fully round-tripped,
            # so the victim's flushed books are exactly its answers
            by_backend, retried, parity_bad, statuses = {}, 0, 0, []
            n_requests = 24
            for i in range(n_requests):
                want = expected[i % len(images)]
                got = _post_predict(gw_url, {
                    "image": images[i % len(images)].tolist(),
                    "deadline_ms": 15000.0, "trace_id": f"gws-{i}"})
                status = str(got.get("status", "error"))
                m_attempts.inc(status=status)
                statuses.append(status)
                env = got.get("gateway") or {}
                who = env.get("backend") or "(gateway)"
                by_backend[who] = by_backend.get(who, 0) + 1
                retried += 1 if env.get("retries") else 0
                if status == "ok" and (
                        got.get("label") != want.get("label")
                        or got.get("certified") != want.get("certified")):
                    parity_bad += 1
            stats["load"] = {"by_backend": by_backend, "retried": retried,
                             "statuses": sorted(set(statuses))}
            if statuses != ["ok"] * n_requests:
                failures.append(f"fleet load lost/failed requests: "
                                f"{statuses}")
            if parity_bad:
                failures.append(f"{parity_bad}/{n_requests} gateway answers "
                                "diverge from direct service calls")
            marker = os.path.join(d["backend2"], "chaos_kill_backend.fired")
            if not os.path.exists(marker):
                failures.append("chaos kill_backend never fired — backend 2 "
                                "survived the whole load")
            if retried < 1:
                failures.append("no request was ever re-dispatched — the "
                                "kill did not land mid-load")
            if by_backend.get(names[0], 0) < 1:
                failures.append(f"survivor {names[0]} answered nothing: "
                                f"{by_backend}")
            try:
                p2.wait(timeout=60)
            except subprocess.TimeoutExpired:
                failures.append("killed backend still running after load")
            deadline = time.time() + 60
            while time.time() < deadline:
                b2 = gateway.registry.get(names[1])
                if b2 is not None and b2.snapshot()["state"] == "ejected":
                    break
                time.sleep(0.1)
            else:
                failures.append("gateway never ejected the killed backend")

            # ---- C: canary deploy, poisoned verdict, auto-rollback ----
            pc, rfc, lgc = _spawn_backend(d["canary"], d["store_v2"],
                                          d["recert"])
            procs.append(pc)
            rc = _await_ready(pc, rfc, lgc)
            canary_url = f"http://127.0.0.1:{rc['port']}"
            canary = backend_name(canary_url)
            gateway.add_backend(canary_url)  # weight 0 until the deploy
            outcome = RollingDeploy(gateway, [canary]).run(warm_timeout_s=60)
            stats["deploy"] = {"outcome": outcome["outcome"],
                               "reason": outcome.get("reason", "")}
            if outcome["outcome"] != "rolled_back":
                failures.append(f"poisoned canary was not rolled back: "
                                f"{outcome}")
            elif "DP400" not in outcome["reason"]:
                failures.append(f"rollback reason is not the planted DP400: "
                                f"{outcome['reason']!r}")
            if not os.path.exists(os.path.join(
                    d["gateway"], "chaos_poison_canary.fired")):
                failures.append("poison_canary fault never fired")
            if int(gateway.metrics.value("gateway_rollbacks_total")) != 1:
                failures.append("gateway_rollbacks_total != 1 after the "
                                "rollback")
            snaps = {s["name"]: s for s in
                     (b.snapshot() for b in gateway.registry.backends())}
            if snaps[canary]["state"] != "draining" \
                    or snaps[canary]["weight"] != 0.0:
                failures.append(f"canary not drained: {snaps[canary]}")
            if snaps[names[0]]["weight"] != 1.0:
                failures.append(f"stable weight not restored: "
                                f"{snaps[names[0]]}")
            # the fleet keeps serving after the rollback — on stable only
            for i in range(4):
                got = _post_predict(gw_url, {
                    "image": images[i].tolist(), "deadline_ms": 15000.0,
                    "trace_id": f"gws-post-{i}"})
                status = str(got.get("status", "error"))
                m_attempts.inc(status=status)
                if status != "ok":
                    failures.append(f"post-rollback request failed: {got}")
                elif (got.get("gateway") or {}).get("backend") == canary:
                    failures.append("post-rollback traffic reached the "
                                    "drained canary")

        # gateway stopped (books dumped); drain the live backends cleanly
        for proc in (p1, pc):
            code = _stop_backend(proc)
            if code != 0:
                failures.append(f"backend exited {code} on SIGTERM")
        client.dump(os.path.join(d["client"], "metrics_client.json"))

        # ---- D: the three-way fleet reconciliation ----
        fleet_dirs = [d["client"], d["gateway"], d["backend1"],
                      d["backend2"], d["canary"]]
        fleet = report_mod.summarize_fleet_dirs(fleet_dirs)
        stats["fleet"] = {
            "orphans": fleet["traces"]["orphans"],
            "consistent": fleet["consistent"],
            "checks": fleet["checks"],
            "gateway": fleet["gateway"]["by_status"],
            "by_backend": fleet["gateway"]["by_backend"],
            "rollbacks": fleet["gateway"]["rollbacks"],
        }
        client_counts = {k: int(v) for k, v in labeled_values(
            client.snapshot(), "loadgen_requests_total", "status").items()}
        if fleet["traces"]["orphans"]:
            failures.append(f"fleet join left orphaned trace ids: "
                            f"{fleet['traces']['orphans'][:4]}")
        if not fleet["consistent"]:
            failures.append(f"fleet cross-check inconsistent: "
                            f"{fleet['checks']}")
        if fleet["gateway"]["by_status"] != client_counts:
            failures.append(
                f"gateway books {fleet['gateway']['by_status']} != client "
                f"books {client_counts}")
        if fleet["gateway"]["rollbacks"] != 1:
            failures.append("fleet report does not carry the rollback")
        if len(fleet["gateway"]["by_backend"]) != 2:
            failures.append(f"expected answers from exactly 2 backends: "
                            f"{fleet['gateway']['by_backend']}")
        rendered = report_mod.format_fleet_dirs(fleet)
        for needle in ("-- cross-process --", "consistent: yes",
                       "orphaned traces: 0", "gateway rollbacks: 1",
                       "gateway responses by backend:"):
            if needle not in rendered:
                failures.append(f"fleet report missing {needle!r}")
    except Exception as e:  # noqa: BLE001 — a smoke must report, not crash
        failures.append(f"smoke crashed: {type(e).__name__}: {e}")
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(root, ignore_errors=True)

    stats["ok"] = not failures
    stats["failures"] = failures
    print(json.dumps(stats))
    return 0 if not failures else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="fleet gateway smoke (parent) / backend launcher")
    p.add_argument("--serve-backend", action="store_true",
                   help="internal: run as one serve backend subprocess")
    p.add_argument("--result-dir", default="")
    p.add_argument("--ready-file", default="")
    p.add_argument("--aot-store", default="")
    p.add_argument("--aot-mode", default="off")
    p.add_argument("--recert-dir", default="")
    p.add_argument("--chaos", default="")
    args = p.parse_args(argv)
    if args.serve_backend:
        return serve_backend_main(args)
    return run_smoke()


if __name__ == "__main__":
    raise SystemExit(main())
