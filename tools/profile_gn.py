#!/usr/bin/env python
"""Bound the GroupNorm+ReLU cost in the RN50-BiT forward/backward on-chip.

PERF.md attributes the steady-state step almost entirely to the victim
fwd+bwd at ~41% MFU and names "GroupNorm/elementwise bandwidth between the
convs" as the residual. Before writing a fused Pallas GN kernel, measure the
actual headroom: time the same scan-threaded fwd / fwd+bwd programs
(tools/profile_scan.py methodology) for

  gn       — the real model (GroupNormRelu: f32 stats, bf16 out)
  identity — GroupNormRelu monkeypatched to plain ReLU (no stats, no
             normalize, no f32 round-trip)

The gn→identity delta is the *upper bound* on what any GN fusion can
recover (a real kernel still reads/writes the slab once). If the delta is
small, the forward is conv-bound and the kernel isn't worth building.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp

from dorpatch_tpu import utils
from dorpatch_tpu.models import resnetv2

utils.enable_compilation_cache()  # tunnel recompiles cost minutes
# announced so callers (chip_validation) can refuse to bank a silent
# jax-CPU fallback as an on-chip measurement
print(f"backend: {jax.default_backend()}", flush=True)


def timed_scan(name, fn, args, k, flops_per_iter=None, reps=2):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    per_iter = (time.perf_counter() - t0) / (reps * k)
    tfs = (f"  {flops_per_iter / per_iter / 1e12:7.2f} TFLOP/s"
           if flops_per_iter else "")
    print(f"{name:42s} {per_iter * 1e3:9.1f} ms/iter  (compile {compile_s:.0f}s){tfs}",
          flush=True)
    return per_iter


class _FlaxNorm(resnetv2.GroupNormRelu):
    """Force the flax GroupNorm path (the pre-round-3 baseline; "auto" now
    resolves to the Pallas kernel on single-device TPU backends)."""

    @resnetv2.nn.compact
    def __call__(self, x):  # noqa: D102
        dt = x.dtype
        x = resnetv2.nn.GroupNorm(
            num_groups=self.num_groups, epsilon=1e-5,
            dtype=resnetv2.jnp.float32, name="GroupNorm_0")(x)
        return resnetv2.nn.relu(x).astype(dt)


class _IdentityNorm(resnetv2.GroupNormRelu):
    """ReLU only: removes GN stats/normalize and the f32 round-trip."""

    @resnetv2.nn.compact
    def __call__(self, x):  # noqa: D102
        return resnetv2.nn.relu(x)


class _FusedNorm(resnetv2.GroupNormRelu):
    """The fused Pallas custom-VJP kernel (`ops/fused_gn.py`)."""

    @resnetv2.nn.compact
    def __call__(self, x):  # noqa: D102
        from dorpatch_tpu.ops import fused_gn

        scale, bias = resnetv2._GNParams(x.shape[-1], name="GroupNorm_0")()
        return fused_gn.gn_relu(x, scale, bias, self.num_groups, impl="pallas")


def build(img: int, n: int, k: int):
    # NOTE: the caller selects the variant by monkeypatching
    # resnetv2.GroupNormRelu, and the patch must stay active while the
    # returned fns trace (first call).
    model = resnetv2.resnetv2_50x1(num_classes=1000)
    params = model.init(jax.random.PRNGKey(0),  # noqa: DP104 — standalone profiling harness, fixed seed is deliberate
                        jnp.zeros((1, img, img, 3), jnp.bfloat16))
    params = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a, params)

    @jax.jit  # noqa: DP105 — harness times compile itself
    def fwd_scan(x0):
        def body(x, _):
            logits = model.apply(params, x)
            return x + logits.mean().astype(x.dtype) * 1e-9, None
        return jax.lax.scan(body, x0, None, length=k)[0]

    @jax.jit  # noqa: DP105 — harness times compile itself
    def fwdbwd_scan(x0):
        def body(x, _):
            g = jax.grad(
                lambda xx: model.apply(params, xx).astype(jnp.float32).mean()
            )(x)
            return jnp.clip(x - 0.01 * jnp.sign(g), 0, 1), None
        return jax.lax.scan(body, x0, None, length=k)[0]

    return fwd_scan, fwdbwd_scan


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=256, help="masked-image batch")
    p.add_argument("--img", type=int, default=224)
    p.add_argument("--k", type=int, default=8)
    p.add_argument("--variants", default="gn,identity")
    args = p.parse_args()
    n, img, k = args.n, args.img, args.k

    print(f"devices: {jax.devices()}  n={n} img={img} k={k}", flush=True)
    xb = jax.random.uniform(jax.random.PRNGKey(1), (n, img, img, 3),  # noqa: DP104 — profiling harness, fixed seed
                            jnp.bfloat16)
    gflops = n * 8.0e9  # XLA cost-model fwd FLOPs/img @224 (PERF.md)

    orig = resnetv2.GroupNormRelu
    for variant in args.variants.split(","):
        resnetv2.GroupNormRelu = {
            "gn": _FlaxNorm, "identity": _IdentityNorm,
            "fused": _FusedNorm}[variant]
        try:
            fwd, fwdbwd = build(img, n, k)
            timed_scan(f"[{variant}] fwd-only scan", fwd, (xb,), k, gflops)
            timed_scan(f"[{variant}] fwd+bwd scan", fwdbwd, (xb,), k,
                       3 * gflops)
        finally:
            resnetv2.GroupNormRelu = orig


if __name__ == "__main__":
    main()
