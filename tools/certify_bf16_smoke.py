#!/usr/bin/env python
"""bf16-certification smoke: the mixed-precision bank vs the f32 bank on a
seeded batch (CI gate, `run_tests.sh`).

Two checks:

- **verdict law** — the same seeded batch certified at
  `DefenseConfig.compute_dtype="float32"` and `"bfloat16"` must produce
  identical verdicts, image by image. The bf16 sweep's contract is
  identical-or-escalated: any image whose evaluated margins land within
  `incremental_margin` of the argmax boundary re-certifies through the f32
  exhaustive program, so a surviving mismatch is a real precision bug, not
  noise the margin was supposed to absorb.
- **bytes invariant** — every `defense.*.bf16.*` entry in the checked-in
  program baseline bank must predict STRICTLY fewer HBM bytes
  (`cost.est_bytes`) than its f32 twin (same name minus the `.bf16` tag).
  A bf16 program pricing at or above f32 means a silent upcast snuck a
  full-precision slab back in (the DP208 class).

Prints ONE JSON line: {"metric": "certify_bf16_smoke", "parity": true,
"escalated": ..., "bf16_entries": ..., "bytes_ratio": ...}; exits non-zero
on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import PatchCleanser
    from dorpatch_tpu.models.small import CifarResNet18

    img, n_classes, ratio = 32, 3, 0.1
    spec = masks_lib.geometry(img, ratio)
    rng = np.random.default_rng(1234)
    imgs = rng.uniform(0.0, 1.0, (3, img, img, 3)).astype(np.float32)
    imgs[0] = 0.5                 # gray: provably first-round unanimous
    imgs[1, :6, :6, :] = 1.0      # bright corner: disagreement inducer
    x = jnp.asarray(imgs)

    failures = []
    stats = {"metric": "certify_bf16_smoke", "images": int(x.shape[0])}

    conv = CifarResNet18(num_classes=n_classes)
    params = conv.init(jax.random.PRNGKey(6),  # noqa: DP104 fixed smoke seed
                       jnp.zeros((1, img, img, 3)))

    def apply_fn(p, xx):
        return conv.apply(p, (xx - 0.5) / 0.5)

    def build(dtype):
        return PatchCleanser(
            apply_fn, spec,
            DefenseConfig(ratios=(ratio,), prune="exact",
                          compute_dtype=dtype))

    f32 = build("float32")
    b16 = build("bfloat16")
    want = f32.robust_predict(params, x, n_classes, bucket_sizes=(1, 4))
    got = b16.robust_predict(params, x, n_classes, bucket_sizes=(1, 4))
    for i, (w, g) in enumerate(zip(want, got)):
        if (w.prediction, w.certification) != (g.prediction,
                                               g.certification):
            failures.append(f"bf16 image {i}: verdict "
                            f"({w.prediction}, {w.certification}) != "
                            f"({g.prediction}, {g.certification})")
    mm = np.asarray(b16.last_min_margin)
    escalated = int((mm < b16.config.incremental_margin).sum())
    stats.update({"escalated": escalated,
                  "min_margin": round(float(mm.min()), 4)})

    # ---- baseline bytes invariant ----
    base = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "dorpatch_tpu", "analysis",
        "baselines.json")
    entries = json.load(open(base)).get("entries", {})
    bf16_bytes = f32_bytes = 0.0
    n_bf16 = 0
    for name, e in sorted(entries.items()):
        if ".bf16" not in name:
            continue
        n_bf16 += 1
        twin = entries.get(name.replace(".bf16", ""))
        if twin is None:
            failures.append(f"baseline entry {name} has no f32 twin")
            continue
        by = float(e["cost"]["est_bytes"])
        twin_by = float(twin["cost"]["est_bytes"])
        bf16_bytes += by
        f32_bytes += twin_by
        if not by < twin_by:
            failures.append(
                f"baseline entry {name}: est_bytes {by:.0f} not strictly "
                f"below f32 twin {twin_by:.0f}")
    if n_bf16 == 0:
        failures.append("no defense.*.bf16.* entries in the baseline bank")
    stats.update({"bf16_entries": n_bf16,
                  "bytes_ratio": round(bf16_bytes / f32_bytes, 4)
                  if f32_bytes else None})

    stats.update({"parity": not failures, "failures": failures})
    print(json.dumps(stats))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
