#!/usr/bin/env python
"""Certify-prune smoke: pruned vs exhaustive double-masking on a seeded
stub batch (CI gate, `run_tests.sh`).

Runs the same mixed batch — one provably-unanimous gray image plus seeded
random images — through `defense.robust_predict` with `prune="off"` (the
exhaustive 666-forward oracle) and `prune="exact"` (the production
two-phase schedule), then asserts:

- verdict parity: (prediction, certification) bit-identical per image,
  and the first-round tables equal;
- every double-masked entry the pruned path DID evaluate matches the
  exhaustive table;
- the pruned path executed strictly fewer masked forwards in total.

Prints ONE JSON line: {"metric": "certify_prune_smoke", "parity": true,
"forwards": N, "forwards_exhaustive": N, "prune_rate": r, ...}; exits
non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    import jax.numpy as jnp

    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import PatchCleanser

    img, n_classes = 32, 2

    def stub(params, x):
        # weightless trigger detector: class 1 iff the 4x4 region at
        # (20:24, 20:24) is mostly bright — only masks occluding the whole
        # trigger flip it, so those masks form a small, genuine
        # first-round minority (the pruned second round's target shape)
        score = x[:, 20:24, 20:24, :].mean(axis=(1, 2, 3))
        return jnp.stack([0.7 - score, score - 0.7], axis=-1)

    rng = np.random.default_rng(1234)
    imgs = np.full((6, img, img, 3), 0.2, np.float32)
    imgs += rng.uniform(0.0, 0.05, imgs.shape).astype(np.float32)
    imgs[0] = 0.5  # gray: masking with the gray fill is an identity ->
    #                provably first-round unanimous (and certified)
    imgs[3, 20:24, 20:24, :] = 1.0  # planted triggers: first-round
    imgs[4, 20:24, 20:24, :] = 1.0  # disagreement -> pruned second round
    x = jnp.asarray(imgs)

    spec = masks_lib.geometry(img, 0.1)
    oracle = PatchCleanser(stub, spec,
                           DefenseConfig(ratios=(0.1,), prune="off"))
    pruned = PatchCleanser(stub, spec,
                           DefenseConfig(ratios=(0.1,), prune="exact"))
    want = oracle.robust_predict(None, x, n_classes)
    got = pruned.robust_predict(None, x, n_classes, bucket_sizes=(1, 8))

    failures = []
    for i, (w, g) in enumerate(zip(want, got)):
        if (w.prediction, w.certification) != (g.prediction,
                                               g.certification):
            failures.append(f"image {i}: verdict "
                            f"({w.prediction}, {w.certification}) != "
                            f"({g.prediction}, {g.certification})")
        if not np.array_equal(w.preds_1, g.preds_1):
            failures.append(f"image {i}: first-round tables differ")
        evaluated = g.preds_2 >= 0
        if not np.array_equal(w.preds_2[evaluated], g.preds_2[evaluated]):
            failures.append(f"image {i}: evaluated second-round entries "
                            "differ from the exhaustive table")

    fwd = sum(r.forwards for r in got)
    exhaustive = sum(r.forwards for r in want)
    if not fwd < exhaustive:
        failures.append(f"no pruning: executed {fwd} vs "
                        f"exhaustive {exhaustive}")
    if not any((r.preds_1 == r.preds_1[0]).all() for r in got):
        failures.append("smoke batch lost its unanimous image")

    print(json.dumps({
        "metric": "certify_prune_smoke",
        "parity": not failures,
        "images": len(got),
        "forwards": int(fwd),
        "forwards_exhaustive": int(exhaustive),
        "prune_rate": round(1.0 - fwd / exhaustive, 4),
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
