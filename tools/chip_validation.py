#!/usr/bin/env python
"""One-shot on-chip validation sequence for the round-3/4 performance work.

Runs, in order, each as an isolated child process (one JAX process at a
time — the tunnel's device grant is exclusive):

  1. fused-GN microbench           tools/profile_gn.py --variants gn,fused
  2. attack bench, auto GN         python bench.py            (fused kernel)
  3. attack bench, flax GN         BENCH_GN=flax python bench.py   (A/B)
  4. certification bench           BENCH_MODE=certify python bench.py
  5. EOT=128 remat, full policy    BENCH_REMAT=1 BENCH_REMAT_POLICY=full
  6. EOT=128 remat, conv policy    BENCH_REMAT=1 BENCH_REMAT_POLICY=conv
  7. victim training               python -m dorpatch_tpu.train (r04 ask #5)
  8. trained-victim flagship       cli --data-source procedural against the
                                   step-7 checkpoint, full 2-stage protocol
                                   + 4-radius certification

Results land in artifacts/chip_validation_r05.json as they complete, so a
tunnel outage mid-sequence loses nothing. Usage:

  python tools/chip_validation.py [--only 1,2,...] [--out PATH]

Every step has a hard deadline; a wedged step is recorded and skipped.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# One absolute victim dir shared by step 7 (--out), step 8 (--model_dir) and
# the step-8 checkpoint guard, so an out-of-repo invocation can't train into
# one tree while the guard checks (or step 8 consumes) another.
VICTIM_DIR = os.path.join(ROOT, "artifacts", "victim_r05")


def run(cmd, env_extra, timeout_s):
    # strip ambient BENCH_* so stray operator exports cannot silently turn
    # an A/B step into two identical configs; each step pins what it needs
    env = {k: v for k, v in os.environ.items() if not k.startswith("BENCH_")}
    if os.path.basename(cmd[1] if len(cmd) > 1 else "") == "bench.py":
        # bench's internal wall budget must undercut OUR deadline, or a
        # wedged accelerator eats the step before bench prints its JSON
        # row (the r03 rc=124 failure shape, one level up)
        env["BENCH_TOTAL_BUDGET"] = str(max(120, timeout_s - 120))
    env.update(env_extra)
    t0 = time.time()

    def _cpu_marker(full: str) -> bool:
        # scanned over the FULL stdout before truncation: a verbose child
        # (the step-8 flagship echoes ~18 KB of metrics) prints its
        # `backend:` line once at the start, long before the retained tail
        return ("backend: cpu" in full) or ("'backend': 'cpu'" in full)

    try:
        proc = subprocess.run(
            cmd, env=env, cwd=ROOT, capture_output=True, text=True,
            timeout=timeout_s)
        return {"rc": proc.returncode, "seconds": round(time.time() - t0, 1),
                "cpu_backend": _cpu_marker(proc.stdout or ""),
                "stdout": proc.stdout[-4000:], "stderr": proc.stderr[-4000:]}
    except subprocess.TimeoutExpired as e:
        def _full(b):
            if b is None:
                return ""
            return b.decode(errors="replace") if isinstance(b, bytes) else b
        # keep whatever the child printed before the deadline: it is the
        # only way to tell "hung claiming the device" from "hung in compile"
        return {"rc": None, "seconds": round(time.time() - t0, 1),
                "cpu_backend": _cpu_marker(_full(e.stdout)),
                "stdout": _full(e.stdout)[-4000:],
                "stderr": _full(e.stderr)[-4000:],
                "error": f"timeout after {timeout_s}s"}


def probe_tunnel(timeout_s: int = 180) -> bool:
    """One cheap child: can jax initialize a non-cpu backend right now?

    A dead axon tunnel makes `jax.devices()` HANG (not fail fast), so the
    timeout is the signal. Called only after a step times out, to tell
    "this step wedged" from "the tunnel is gone" — the latter means every
    remaining step would burn its full deadline for nothing (the r03
    failure shape, 6h of timeouts)."""
    r = run([sys.executable, "-c",
             "import jax; ds = jax.devices(); "
             "assert ds[0].platform != 'cpu', ds; print('tunnel-ok')"],
            {}, timeout_s)
    return r.get("rc") == 0 and "tunnel-ok" in r.get("stdout", "")


def parse_bench(res):
    if res.get("rc") == 0:
        for line in reversed(res.get("stdout", "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    row = json.loads(line)
                except Exception:
                    continue
                # bench delivers rc=0 error rows by design ("benchmark
                # could not run"): that is a failed step here, not a
                # result to bank — resume must retry it
                if row.get("error"):
                    return None
                return row
    return None


def is_on_chip_result(parsed) -> bool:
    """True if a stored parsed result is worth skipping on resume.

    A CPU-fallback bench row (fallback/comparable markers) is a liveness
    artifact, not the on-chip measurement this sequence exists to capture:
    resuming once the tunnel holds must re-run such steps, or the
    unattended watcher would bank fallback rows as finished steps."""
    if parsed is None:
        return False
    if isinstance(parsed, dict) and (
            parsed.get("fallback") or parsed.get("comparable") is False
            or parsed.get("backend") == "cpu"):
        # backend=="cpu": the jax child silently landed on the CPU backend
        # WITHOUT the orchestrator's fallback path (plugin registered but
        # device gone) — an unmarked row, same non-measurement
        return False
    return True


def ran_on_cpu(res) -> bool:
    """True if the child announced a jax-CPU backend — a silent fallback
    that must not be banked as an on-chip result (profile_gn and the
    pipeline print `backend: <name>`; train.py reports `'backend': '<name>'`
    in its saved-report dict). `run()` scans the FULL child stdout before
    truncating to the 4 KB tail and records `cpu_backend`; the tail scan
    remains as the fallback for results recorded by older runs."""
    if "cpu_backend" in res:
        return bool(res["cpu_backend"])
    out = res.get("stdout", "")
    return ("backend: cpu" in out) or ("'backend': 'cpu'" in out)


def parse_profile_gn(res):
    if res.get("rc") != 0:
        return None  # partial rows from a crashed child are not a success
    if ran_on_cpu(res):
        return None  # CPU-fallback microbench is not an on-chip measurement
    rows = {}
    for line in res.get("stdout", "").splitlines():
        m = re.match(r"\[(\w+)\] (fwd-only|fwd\+bwd) scan\s+([\d.]+) ms/iter",
                     line)
        if m:
            rows[f"{m.group(1)}_{m.group(2).replace('+', '_')}"] = float(
                m.group(3))
    return rows or None


STEPS = {
    "1_gn_microbench": lambda t: (
        parse_profile_gn,
        run([sys.executable, "tools/profile_gn.py", "--variants", "gn,fused"],
            {}, t)),
    "2_attack_auto_gn": lambda t: (
        parse_bench, run([sys.executable, "bench.py"], {}, t)),
    "3_attack_flax_gn": lambda t: (
        parse_bench, run([sys.executable, "bench.py"], {"BENCH_GN": "flax"}, t)),
    "4_certify": lambda t: (
        parse_bench,
        run([sys.executable, "bench.py"], {"BENCH_MODE": "certify"}, t)),
    "5_eot128_remat_full": lambda t: (
        parse_bench,
        run([sys.executable, "bench.py"],
            {"BENCH_EOT": "128", "BENCH_BATCH": "4", "BENCH_REMAT": "1",
             "BENCH_REMAT_POLICY": "full"}, t)),
    "6_eot128_remat_conv": lambda t: (
        parse_bench,
        run([sys.executable, "bench.py"],
            {"BENCH_EOT": "128", "BENCH_BATCH": "4", "BENCH_REMAT": "1",
             "BENCH_REMAT_POLICY": "conv"}, t)),
    "7_train_victim": lambda t: (
        parse_train,
        run([sys.executable, "-m", "dorpatch_tpu.train",
             "--out", VICTIM_DIR, "--epochs", "12"], {}, t)),
    "8_flagship_trained": lambda t: (
        parse_flagship,
        run([sys.executable, "-m", "dorpatch_tpu.cli",
             "--data-source", "procedural", "--dataset", "cifar10",
             "--base_arch", "resnet18", "--img-size", "32", "-b", "8",
             "--num-batches", "2", "--sampling-size", "128",
             "--max-iterations", "600", "--compute-dtype", "bfloat16",
             "--model_dir", VICTIM_DIR,
             "--results-root", os.path.join(ROOT, "artifacts",
                                            "flagship_r05")], {}, t)),
}


def parse_train(res):
    """`train.py` prints `saved <path>; report={...}` on success —
    possibly behind observe.log's `[pN +T.Ts]` attribution prefix, so
    match the marker anywhere in the line, not at its start."""
    if res.get("rc") != 0 or ran_on_cpu(res):
        return None
    for line in reversed(res.get("stdout", "").splitlines()):
        if "saved " in line and "report=" in line:
            return {"line": line.strip()[:400]}
    return None


def parse_flagship(res):
    """The pipeline prints the reference-format report line last."""
    if res.get("rc") != 0 or ran_on_cpu(res):
        return None
    for line in reversed(res.get("stdout", "").splitlines()):
        if "certified_ASR@PC" in line:
            return {"report": line.strip()}
    return None


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--only", default="",
                   help="comma list of step prefixes (e.g. 1,2)")
    p.add_argument("--out",
                   default=os.path.join(ROOT, "artifacts",
                                        "chip_validation_r05.json"))
    p.add_argument("--timeout", type=int, default=2700,
                   help="per-step deadline (Mosaic compiles through the "
                        "tunnel can take many minutes)")
    p.add_argument("--redo", default="",
                   help="comma list of step prefixes to re-run even if the "
                        "existing --out already has a parsed result")
    args = p.parse_args()
    only = set(args.only.split(",")) if args.only else None
    redo = set(args.redo.split(",")) if args.redo else set()

    results = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                results = json.load(f)
        except Exception:
            print(f"warning: could not parse existing {args.out}; starting fresh",
                  flush=True)

    for name, step in STEPS.items():
        if only is not None and name.split("_")[0] not in only:
            continue
        if (name.split("_")[0] not in redo
                and is_on_chip_result((results.get(name) or {}).get("parsed"))):
            # true resume: a completed step's device time is not re-spent
            # (the loaded results already hold its parsed row)
            print(json.dumps({name: "already done (use --redo to re-run)"}),
                  flush=True)
            continue
        if name == "8_flagship_trained":
            # the flagship is only meaningful against the step-7 victim: a
            # failed/timed-out training must not burn 45 min of the
            # exclusive device grant against a missing checkpoint, nor
            # silently consume a stale VICTIM_DIR checkpoint from an
            # earlier round and mislabel the row as "trained-victim"
            trained = (results.get("7_train_victim") or {}).get("parsed")
            ckpt = os.path.join(VICTIM_DIR, "cifar10",
                                "cifar_resnet18_cutout2_128_cifar10.pth")
            if not trained or not os.path.exists(ckpt):
                results[name] = {"parsed": None, "rc": None, "seconds": 0,
                                 "error": "skipped: step 7 training did not "
                                          "complete (no checkpoint)"}
                os.makedirs(os.path.dirname(args.out), exist_ok=True)
                tmp = args.out + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(results, f, indent=1)
                os.replace(tmp, args.out)
                print(json.dumps({name: results[name]["error"]}), flush=True)
                continue
        print(f"== {name}", flush=True)
        parse, res = step(args.timeout)
        parsed = parse(res)
        results[name] = {"parsed": parsed,
                         "rc": res.get("rc"),
                         "seconds": res.get("seconds"),
                         "error": res.get("error")}
        if parsed is None:
            results[name]["stdout_tail"] = res.get("stdout", "")[-1500:]
            results[name]["stderr_tail"] = res.get("stderr", "")[-1500:]
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(results, f, indent=1)
        os.replace(tmp, args.out)  # atomic: an interrupt never truncates
        print(json.dumps({name: results[name].get("parsed")}), flush=True)
        if res.get("error", "").startswith("timeout") and not probe_tunnel():
            # Circuit breaker: a step deadline plus a failed 3-min probe
            # means the tunnel is gone, and every remaining step would eat
            # its full deadline for nothing. Stop resumably instead; the
            # skip-completed logic above makes the re-run cheap.
            print(f"tunnel down after {name}: stopping (resume with the "
                  f"same --out once tools/tpu_probe.sh reports TPU_UP)",
                  flush=True)
            return 3

    print(f"results -> {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
