#!/usr/bin/env python
"""Trained-victim flagship parity: jax-tpu backend vs the torch oracle.

The BASELINE.json acceptance criterion is "certified-ASR of the jax-tpu
backend matches the torch backend on fixed seeds/images" (reference protocol:
`/root/reference/main.py:84,150-151,186-187`). This tool produces that
evidence in two parts:

1. **oracle-certify** (exact): the jax run's patch artifacts
   (`adv_mask_*/adv_pattern_*/targets_*`, torch-NCHW interchange format) are
   copied into a FRESH results tree — deliberately WITHOUT the `adv_PC_*`
   record cache, which would short-circuit the torch defense into re-scoring
   jax's own certification records — and the torch backend certifies them
   with the torch victim + torch PatchCleanser. Same images, same patches —
   any certified-ASR gap is backend skew (victim logits or verdict logic),
   bounded by the checkpoint converter's 1e-4 logits tolerance.
2. **oracle-attack** (independent, optional --attack): the torch backend
   re-runs the whole two-stage attack from scratch in its own results_root
   on the same seeds/images. Numbers differ by sampling noise; this compares
   protocol-level efficacy, not numerics.

Run AFTER tools/chip_validation.py step 8 (which leaves the jax flagship
summary + patch artifacts under artifacts/flagship_r05). CPU-only by
construction: re-exec's with the no-accelerator env so it can run alongside
a live TPU job without touching the device grant (the torch oracle and this
comparison never need jax devices).

Usage:
  python tools/parity_flagship.py [--attack] [--jax-root TREE]
  # report default: <jax-root>_PARITY.json (derived, per-tree)
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)  # runnable from any CWD, like the other tools


def flagship_config(results_root: str, backend: str,
                    model_dir: str = "", config_path: str = ""):
    """The torch-oracle config for the flagship run being scored.

    `config_path` is the config.json sitting in the SAME result dir as the
    summary.json the caller chose (never globbed independently — jax_root
    can hold several runs, and pairing a summary with another run's config
    would silently break the same-seeds-same-images premise). When present
    (written by the pipelines since r05), the oracle reconstructs THAT
    config, whatever scale the run used (full step-8 or a CPU-scaled
    hedge). Fallback: the hardcoded chip_validation step-8 flags, for trees
    predating the record."""
    import dataclasses

    from dorpatch_tpu.config import (AttackConfig, ExperimentConfig,
                                     config_from_dict)

    recorded = None
    if config_path and os.path.exists(config_path):
        with open(config_path) as f:
            recorded = config_from_dict(json.load(f))
    if recorded is not None:
        return dataclasses.replace(
            recorded,
            backend=backend,
            results_root=results_root,
            model_dir=model_dir or recorded.model_dir,
            # the torch oracle is fp32; bf16 is a jax-path knob
            attack=dataclasses.replace(recorded.attack,
                                       compute_dtype="float32"),
        )
    return ExperimentConfig(
        dataset="cifar10",
        base_arch="resnet18",
        img_size=32,
        batch_size=8,
        num_batches=2,
        data_source="procedural",
        model_dir=model_dir or os.path.join(ROOT, "artifacts", "victim_r05"),
        results_root=results_root,
        backend=backend,
        attack=AttackConfig(sampling_size=128, max_iterations=600,
                            compute_dtype="float32"),
    )


def derived_roots(jax_root: str) -> tuple:
    """(oracle_root, torch_root) for a given jax flagship tree. Derived —
    not shared constants — so parity runs for different trees (conv vs vit
    family legs) never rmtree each other's staged evidence."""
    base = os.path.normpath(jax_root)
    return base + "_oracle", base + "_torch"


def stage_oracle_root(jax_root: str, oracle_root: str) -> int:
    """Copy patch + target artifacts (NOT the adv_PC_* certification cache)
    from the jax flagship tree into a fresh tree for the torch oracle.
    Returns the number of files staged."""
    import shutil

    # fresh tree every run: a stale adv_PC_* from a previous parity run
    # would short-circuit exactly the recomputation this leg exists for
    if os.path.isdir(oracle_root):
        shutil.rmtree(oracle_root)
    n = 0
    for src in glob.glob(os.path.join(jax_root, "**", "*.pt"),
                         recursive=True):
        name = os.path.basename(src)
        if name.startswith("adv_PC_"):
            continue  # the whole point: torch must recompute certification
        dst = os.path.join(oracle_root, os.path.relpath(src, jax_root))
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copy2(src, dst)
        n += 1
    return n


def load_jax_summary(results_root: str):
    """The step-8 run's committed summary.json (written by pipeline.py)."""
    hits = glob.glob(os.path.join(results_root, "**", "summary.json"),
                     recursive=True)
    if not hits:
        return None, None
    with open(hits[0]) as f:
        return json.load(f), hits[0]


def parity_rows(jax_m: dict, torch_m: dict) -> list:
    rows = []
    # both backends filter to their own correctly-classified images; a
    # borderline logit flipping across the 1e-4 converter tolerance would
    # change the evaluated set — surface the counts so a reader can tell
    rows.append({"metric": "evaluated_images",
                 "jax": jax_m.get("evaluated_images"),
                 "torch": torch_m.get("evaluated_images"),
                 "delta": (jax_m.get("evaluated_images", 0)
                           - torch_m.get("evaluated_images", 0))})
    for key in ("clean_accuracy", "robust_accuracy"):
        rows.append({"metric": key, "jax": jax_m[key], "torch": torch_m[key],
                     "delta": round(jax_m[key] - torch_m[key], 4)})
    radii = ("1.5%", "3%", "6%", "12%")
    for key in ("acc_pc", "certified_acc_pc", "certified_asr_pc"):
        for r, jv, tv in zip(radii, jax_m[key], torch_m[key]):
            # raw_delta feeds the parity gate (rounding to 4 decimals would
            # make any --tol below 5e-5 unenforceable); delta is for display
            rows.append({"metric": f"{key}@{r}", "jax": jv, "torch": tv,
                         "delta": round(jv - tv, 4),
                         "raw_delta": jv - tv})
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--jax-root",
                   default=os.path.join(ROOT, "artifacts", "flagship_r05"))
    p.add_argument("--model-dir", default="",
                   help="victim checkpoint dir; must be the SAME dir the "
                        "jax flagship used (default artifacts/victim_r05)")
    p.add_argument("--attack", action="store_true",
                   help="also run the independent torch attack (slow: the "
                        "full two-stage optimization on CPU)")
    p.add_argument("--out", default="",
                   help="report path (default: <jax-root>_PARITY.json, "
                        "derived so different trees never overwrite each "
                        "other's parity evidence)")
    p.add_argument("--tol", type=float, default=1e-6,
                   help="max |delta| in certified-ASR percentage points for "
                        "the oracle-certify leg to count as parity (same "
                        "patches, same images: exact agreement expected "
                        "unless a borderline logit flips)")
    args = p.parse_args(argv)
    if not args.out:
        args.out = os.path.normpath(args.jax_root) + "_PARITY.json"

    jax_m, jax_path = load_jax_summary(args.jax_root)
    if jax_m is None:
        print(f"no summary.json under {args.jax_root}: run "
              "tools/chip_validation.py step 8 first", file=sys.stderr)
        return 1

    from dorpatch_tpu.pipeline import run_experiment

    # Leg 1: torch oracle certifies the jax patches. Staged into a fresh
    # tree so the torch pipeline's cached-patch branch fires but its
    # PC-record cache misses (see stage_oracle_root). Roots are derived
    # from --jax-root so parity runs for different flagship trees (e.g.
    # the conv and vit family legs) never rmtree each other's staged
    # evidence.
    oracle_root, torch_root = derived_roots(args.jax_root)
    staged = stage_oracle_root(args.jax_root, oracle_root)
    if staged == 0:
        print(f"no patch artifacts under {args.jax_root}", file=sys.stderr)
        return 1
    jax_config_path = os.path.join(os.path.dirname(jax_path), "config.json")
    cert_cfg = flagship_config(oracle_root, "torch", args.model_dir,
                               config_path=jax_config_path)
    torch_cert = run_experiment(cert_cfg, verbose=True)

    out = {
        "victim": cert_cfg.model_dir,
        "jax_summary": jax_path,
        "oracle_certify": {
            "rows": parity_rows(jax_m, torch_cert),
            "torch_report": torch_cert.get("report"),
            "jax_report": jax_m.get("report"),
        },
    }
    cert_deltas = [abs(r["raw_delta"]) for r in out["oracle_certify"]["rows"]
                   if r["metric"].startswith("certified_asr")]
    out["oracle_certify"]["max_certified_asr_delta"] = max(cert_deltas)
    out["oracle_certify"]["parity"] = max(cert_deltas) <= args.tol

    # Leg 2 (optional): independent torch attack, own artifact tree.
    if args.attack:
        atk_cfg = flagship_config(
            torch_root, "torch", args.model_dir,
            config_path=jax_config_path)
        torch_atk = run_experiment(atk_cfg, verbose=True)
        out["oracle_attack"] = {
            "rows": parity_rows(jax_m, torch_atk),
            "torch_report": torch_atk.get("report"),
        }

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, default=float)
    print(json.dumps({"parity": out["oracle_certify"]["parity"],
                      "max_certified_asr_delta":
                          out["oracle_certify"]["max_certified_asr_delta"],
                      "out": args.out}))
    return 0


if __name__ == "__main__":
    # never touch the accelerator: the torch oracle runs alongside live TPU
    # jobs (chip_validation), so re-exec with the no-plugin CPU env before
    # any jax import can claim the device grant
    if os.environ.get("PALLAS_AXON_POOL_IPS") or (
            os.environ.get("JAX_PLATFORMS", "") != "cpu"):
        env = dict(os.environ)
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["JAX_PLATFORMS"] = "cpu"
        os.execve(sys.executable, [sys.executable] + sys.argv, env)
    sys.exit(main())
