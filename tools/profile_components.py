#!/usr/bin/env python
"""Attribute the single-chip attack-step time to its components.

Times, as separate jitted programs on the real chip:
  1. victim forward (bf16, EOT-sized batch)
  2. victim forward+backward w.r.t. input
  3. fused masked_fill (Pallas) fwd
  4. masked_fill fwd+bwd
  5. the full stage-1 attack step (1-step block)
and prints implied TFLOP/s per component so the gap has an address.

Usage: python tools/profile_components.py [--batch 8] [--eot 32]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp

from dorpatch_tpu import losses
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import AttackConfig
from dorpatch_tpu.models import get_model

RN50_FWD_GFLOPS = 4.3  # ResNetV2-50 @224 fwd, approx


def timed(name, fn, *args, reps=5, flops=None):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    tfs = f"  {flops / dt / 1e12:8.2f} TFLOP/s" if flops else ""
    print(f"{name:32s} {dt * 1e3:9.1f} ms/call  (compile {compile_s:.1f}s){tfs}",
          flush=True)
    return dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--eot", type=int, default=32)
    p.add_argument("--img", type=int, default=224)
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()
    b, s, img = args.batch, args.eot, args.img
    n = b * s

    print(f"devices: {jax.devices()}  batch={b} eot={s} img={img}", flush=True)
    victim = get_model("imagenet", "resnetv2", img_size=img)
    params16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
        victim.params)

    key = jax.random.PRNGKey(0)  # noqa: DP104 — standalone profiling harness, fixed seed is deliberate
    key, k_xb = jax.random.split(key)
    xb = jax.random.uniform(k_xb, (n, img, img, 3), jnp.bfloat16)

    fwd = jax.jit(lambda p_, x_: victim.apply(p_, x_))  # noqa: DP105 — harness times compile itself
    timed("model fwd (bf16)", fwd, params16, xb, reps=args.reps,
          flops=n * RN50_FWD_GFLOPS * 1e9)

    def loss_fn(x_):
        return victim.apply(params16, x_).astype(jnp.float32).mean()

    fwdbwd = jax.jit(jax.grad(loss_fn))  # noqa: DP105 — harness times compile itself
    timed("model fwd+bwd (bf16)", fwdbwd, xb, reps=args.reps,
          flops=n * 3 * RN50_FWD_GFLOPS * 1e9)

    def loss_fn_remat(x_):
        f = jax.checkpoint(lambda xx: victim.apply(params16, xx).astype(jnp.float32))
        return f(x_).mean()

    fwdbwd_r = jax.jit(jax.grad(loss_fn_remat))  # noqa: DP105 — harness times compile itself
    timed("model fwd+bwd remat", fwdbwd_r, xb, reps=args.reps,
          flops=n * 4 * RN50_FWD_GFLOPS * 1e9)

    # masked_fill
    cfg = AttackConfig(sampling_size=s)
    universe = jnp.asarray(masks_lib.dropout_universe(img, cfg.dropout, cfg.dropout_sizes))
    rects = universe[:s]
    key, k_x = jax.random.split(key)
    x = jax.random.uniform(k_x, (b, img, img, 3), jnp.float32)
    from dorpatch_tpu import ops

    mf = jax.jit(lambda x_, r_: ops.masked_fill(x_, r_, 0.5, "on"))  # noqa: DP105 — harness times compile itself
    bytes_mf = (b * img * img * 3 + b * s * img * img * 3) * 4
    timed(f"masked_fill pallas fwd ({bytes_mf / 1e6:.0f} MB)", mf, x, rects,
          reps=args.reps)

    mfg = jax.jit(jax.grad(lambda x_, r_: ops.masked_fill(x_, r_, 0.5, "on").sum(),  # noqa: DP105 — harness times compile itself
                           argnums=0))
    timed("masked_fill pallas fwd+bwd", mfg, x, rects, reps=args.reps)

    mfx = jax.jit(lambda x_, r_: ops.masked_fill(x_, r_, 0.5, "off"))  # noqa: DP105 — harness times compile itself
    timed("masked_fill XLA fwd", mfx, x, rects, reps=args.reps)

    # full attack step
    cfg = AttackConfig(sampling_size=s, compute_dtype="bfloat16")
    attack = DorPatch(victim.apply, victim.params, victim.num_classes, cfg)
    y = jnp.zeros((b,), jnp.int32)
    lv = jnp.mean(losses.local_variance(x)[0], axis=-1)
    state = attack._init_state(key, x, y, False, universe.shape[0])
    block1 = attack._get_block(1, img, 1)
    step_flops = n * 4 * RN50_FWD_GFLOPS * 1e9  # remat: fwd + (fwd+bwd)
    dt = timed("attack step (stage1, remat)", block1, state, x, lv, universe,
               reps=args.reps, flops=step_flops)
    print(f"\nattack images/sec (batch {b}): {b / dt:.2f}", flush=True)

    attack_nr = DorPatch(victim.apply, victim.params, victim.num_classes, cfg,
                         remat=False)
    block_nr = attack_nr._get_block(1, img, 1)
    timed("attack step (no remat)", block_nr, state, x, lv, universe,
          reps=args.reps, flops=n * 3 * RN50_FWD_GFLOPS * 1e9)


if __name__ == "__main__":
    main()
