#!/bin/bash
# Probe the axon TPU tunnel every 5 minutes; log transitions. Exits 0 the
# first time a non-cpu jax backend initializes. The python status is
# captured directly (no pipe: PIPESTATUS inside $() is lost to the parent
# shell), and the match is affirmative: a crashed probe's traceback tail
# contains no "cpu" either, so only an explicit platform= line counts.
LOG=/root/repo/artifacts/tpu_probe.log
mkdir -p /root/repo/artifacts
TMP=$(mktemp)
trap 'rm -f "$TMP"' EXIT
while true; do
  ts=$(date -u +%FT%TZ)
  timeout 240 python -c "import jax; ds = jax.devices(); print('platform=' + ds[0].platform, len(ds))" > "$TMP" 2>&1
  rc=$?
  out=$(grep "^platform=" "$TMP" | tail -1)
  echo "$ts rc=$rc $out" >> "$LOG"
  if [ "$rc" -eq 0 ] && [[ "$out" == platform=* ]] && [[ "$out" != *cpu* ]]; then
    echo "$ts TPU_UP" >> "$LOG"
    exit 0
  fi
  sleep 240
done
