#!/bin/bash
# Probe the axon TPU tunnel every 5 minutes; log transitions. Exits 0 the
# first time a non-cpu jax backend initializes. rc must be the python
# status (PIPESTATUS[0]), not the pipe tail's, and the match must be
# affirmative: a crashed probe's traceback tail contains no "cpu" either.
LOG=/root/repo/artifacts/tpu_probe.log
mkdir -p /root/repo/artifacts
while true; do
  ts=$(date -u +%FT%TZ)
  out=$(timeout 240 python -c "import jax; ds=jax.devices(); print('platform=' + ds[0].platform, len(ds))" 2>&1 | grep "^platform=" | tail -1)
  rc=${PIPESTATUS[0]}
  echo "$ts rc=$rc $out" >> "$LOG"
  if [ "$rc" -eq 0 ] && [[ "$out" == platform=* ]] && [[ "$out" != *cpu* ]]; then
    echo "$ts TPU_UP" >> "$LOG"
    exit 0
  fi
  sleep 240
done
