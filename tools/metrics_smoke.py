#!/usr/bin/env python
"""Metrics-plane smoke: exact client/server reconciliation (CI gate,
`run_tests.sh`).

Three phases, one process, stub victim only:

A. UNFAULTED — a 2-replica service answers a seeded closed-loop batch;
   the client counts every predict attempt by terminal status into its
   own registry. The service's `serve_requests_total` series must equal
   the client counts BIT-FOR-BIT, and the Prometheus text exposition
   (the `GET /metrics` body) must parse back to the same numbers.
B. CHAOS — same shape but chaos wedges replica 0 mid-batch with requests
   in flight. Failover re-dispatch must keep the books exact: every
   request answered ok exactly once, counters still reconciling
   bit-for-bit (nothing double-counted across the re-dispatch), and at
   least one `serve_failover_redispatched_total` increment proving the
   wedge landed.
C. FLEET — `observe.report --fleet` over both run dirs must join client
   and server snapshots, render the merged cross-process section, report
   ZERO orphaned trace ids, and judge the fleet consistent.

Prints ONE JSON line: {"metric": "metrics_smoke", "ok": true, ...};
exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.config import DefenseConfig, ServeConfig
    from dorpatch_tpu.observe import (MetricRegistry, labeled_values,
                                      parse_exposition)
    from dorpatch_tpu.observe import report as report_mod
    from dorpatch_tpu.serve.service import CertifiedInferenceService

    num_classes, img = 5, 32

    # fresh closure per service so jit trace caches never alias
    def make_apply():
        def apply_fn(params, x):
            s = x.mean(axis=(1, 2, 3))
            return jax.nn.one_hot((s * 7.0).astype(jnp.int32) % num_classes,
                                  num_classes)
        return apply_fn

    defense_cfg = DefenseConfig(ratios=(0.1,), chunk_size=64)
    rng = np.random.default_rng(7)
    images = rng.uniform(0.0, 1.0, (12, img, img, 3)).astype(np.float32)

    def drive(svc, client):
        """Closed-loop pass; every attempt lands in the CLIENT registry
        with the response's own terminal status — the numbers the server
        series must match exactly."""
        m = client.counter("loadgen_requests_total",
                           help="client-side attempts by terminal status")
        out = [None] * len(images)
        nxt = {"i": 0}
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    i = nxt["i"]
                    if i >= len(images):
                        return
                    nxt["i"] = i + 1
                r = svc.predict(images[i], deadline_ms=15000.0)
                m.inc(status=str(r.status))
                out[i] = r

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    def counts_of(registry, name):
        return {k: int(v) for k, v in labeled_values(
            registry.snapshot(), name, "status").items()}

    def exposition_counts(svc):
        """serve_requests_total by status as a /metrics scraper sees it."""
        parsed = parse_exposition(svc.metrics.render_text())
        out = {}
        for key, value in (parsed.get("serve_requests_total") or {}).items():
            for k, v in key:
                if k == "status":
                    out[v] = out.get(v, 0) + int(value)
        return out

    failures = []
    stats = {"metric": "metrics_smoke"}
    dirs = {name: tempfile.mkdtemp(prefix=f"metrics-smoke-{name}-")
            for name in ("plain", "chaos")}
    try:
        # ---- A: unfaulted 2-replica reconciliation ----
        client = MetricRegistry()
        svc = CertifiedInferenceService(
            make_apply(), None, num_classes, img,
            serve_cfg=ServeConfig(max_batch=4, bucket_sizes=(1, 2, 4),
                                  deadline_ms=15000.0, replicas=2),
            defense_cfg=defense_cfg, result_dir=dirs["plain"])
        with svc:
            got = drive(svc, client)
            statuses = [getattr(r, "status", "?") for r in got]
            server = counts_of(svc.metrics, "serve_requests_total")
            scraped = exposition_counts(svc)
        client_counts = counts_of(client, "loadgen_requests_total")
        client.dump(os.path.join(dirs["plain"], "metrics_client.json"))
        stats["plain"] = {"client": client_counts, "server": server}
        if statuses != ["ok"] * len(images):
            failures.append(f"unfaulted pass not all ok: {statuses}")
        if client_counts != server:
            failures.append(f"unfaulted counters diverge: client "
                            f"{client_counts} vs server {server}")
        if scraped != server:
            failures.append(f"text exposition does not round-trip: "
                            f"scraped {scraped} vs registry {server}")

        # ---- B: wedged replica — exactly-once books across failover ----
        client = MetricRegistry()
        svc = CertifiedInferenceService(
            make_apply(), None, num_classes, img,
            serve_cfg=ServeConfig(max_batch=4, bucket_sizes=(1, 2, 4),
                                  deadline_ms=15000.0, replicas=2,
                                  max_restarts=2, restart_backoff_base=0.2,
                                  restart_backoff_cap=1.0,
                                  replica_stale_s=0.6,
                                  chaos="wedge_dispatch"),
            defense_cfg=defense_cfg, result_dir=dirs["chaos"])
        with svc:
            # The wedge can only land when replica 0 picks up a batch, and
            # under single-core contention replica 1 can drain a whole pass
            # alone — the PR 17 flake. Deterministic harness: re-drive the
            # faulted leg until the O_EXCL fired-marker PROVES the fault
            # landed (each pass counts into the same client registry, so
            # the books stay exact), instead of hoping one pass wins the
            # scheduling race. Once the marker exists the wedged batch's
            # requests can only resolve through the supervisor's
            # re-dispatch, so drive() returning implies redispatched >= 1.
            marker = os.path.join(dirs["chaos"], "chaos_wedge_dispatch.fired")
            statuses, rounds, max_rounds = [], 0, 20
            while True:
                got = drive(svc, client)
                rounds += 1
                statuses.extend(getattr(r, "status", "?") for r in got)
                if os.path.exists(marker) or rounds >= max_rounds:
                    break
            if not os.path.exists(marker):
                failures.append(
                    f"chaos wedge_dispatch never fired in {rounds} passes "
                    f"({rounds * len(images)} requests) — replica 0 never "
                    f"picked up a batch")
            server = counts_of(svc.metrics, "serve_requests_total")
            redispatched = int(svc.metrics.value(
                "serve_failover_redispatched_total"))
            completed = svc.stats()["completed"]
            # let the supervisor finish quarantine+restart of the wedged
            # replica so stop() does not wait out the drain timeout
            deadline = time.time() + 90.0
            while time.time() < deadline:
                snap = {r["replica"]: r for r in svc.stats()["replicas"]}
                if (snap.get(0, {}).get("state") == "healthy"
                        and snap[0].get("generation", 0) >= 1):
                    break
                time.sleep(0.25)
        client_counts = counts_of(client, "loadgen_requests_total")
        client.dump(os.path.join(dirs["chaos"], "metrics_client.json"))
        stats["chaos"] = {"client": client_counts, "server": server,
                          "redispatched": redispatched,
                          "completed": completed, "rounds": rounds}
        expected_n = rounds * len(images)
        if statuses != ["ok"] * expected_n:
            failures.append(f"chaos pass lost/failed requests: {statuses}")
        if client_counts != server:
            failures.append(f"chaos counters diverge: client "
                            f"{client_counts} vs server {server} — failover "
                            f"double-counted or dropped a request")
        if redispatched < 1:
            failures.append("chaos never forced a failover re-dispatch — "
                            "the wedge did not land mid-batch")
        if completed != expected_n:
            failures.append(f"completed={completed} after {expected_n} "
                            f"requests — double-answered or lost")

        # ---- C: fleet join over both run dirs ----
        fleet = report_mod.summarize_fleet_dirs(list(dirs.values()))
        stats["fleet"] = {"orphans": fleet["traces"]["orphans"],
                          "consistent": fleet["consistent"]}
        if fleet["traces"]["orphans"]:
            failures.append(f"fleet join left orphaned trace ids: "
                            f"{fleet['traces']['orphans'][:4]}")
        if not fleet["consistent"]:
            failures.append(f"fleet cross-check inconsistent: "
                            f"{fleet['checks']}")
        rendered = report_mod.format_fleet_dirs(fleet)
        if "-- cross-process --" not in rendered:
            failures.append("fleet report does not render the merged "
                            "cross-process section")
        if "consistent: yes" not in rendered:
            failures.append("fleet report does not judge the run "
                            "consistent")
    finally:
        for d in dirs.values():
            shutil.rmtree(d, ignore_errors=True)

    stats["ok"] = not failures
    stats["failures"] = failures
    print(json.dumps(stats))
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
