#!/bin/bash
# Trained-victim protocol leg for the SECOND victim family (cifar_vit):
# train the 32px ViT on the procedural labeled task, run the full two-stage
# attack + 4-radius certification against it, then score torch-oracle
# certified-ASR parity — the same evidence chain as the cifar_resnet18
# hedge (tools/flagship_cpu_hedge.sh), proving the trained-victim parity
# acceptance is not conv-family-specific. CPU-scaled config (sampling 16,
# 200 iters), recorded in the run's config.json so the oracle scores the
# same scale.
set -u
cd "$(dirname "$0")/.."
LOG=artifacts/flagship_vit_leg.log
echo "$(date -u +%FT%TZ) vit-leg: training" >> "$LOG"
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m dorpatch_tpu.train \
  --arch cifar_vit --out artifacts/victim_vit_r05 --epochs 12 \
  --n-per-class 1000 --lr 1e-3 >> "$LOG" 2>&1
rc=$?
echo "$(date -u +%FT%TZ) vit-leg: train rc=$rc" >> "$LOG"
[ $rc -ne 0 ] && exit $rc
echo "$(date -u +%FT%TZ) vit-leg: attacking" >> "$LOG"
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m dorpatch_tpu.cli \
  --data-source procedural --dataset cifar10 --base_arch cifar_vit \
  --img-size 32 -b 4 --num-batches 2 --sampling-size 16 \
  --max-iterations 200 --model_dir artifacts/victim_vit_r05 \
  --results-root artifacts/flagship_vit_r05 >> "$LOG" 2>&1
rc=$?
echo "$(date -u +%FT%TZ) vit-leg: attack rc=$rc" >> "$LOG"
[ $rc -ne 0 ] && exit $rc
echo "$(date -u +%FT%TZ) vit-leg: torch-oracle parity" >> "$LOG"
python tools/parity_flagship.py --jax-root artifacts/flagship_vit_r05 \
  --model-dir artifacts/victim_vit_r05 --attack \
  --out artifacts/PARITY_vit_r05.json >> "$LOG" 2>&1
echo "$(date -u +%FT%TZ) vit-leg: parity rc=$?" >> "$LOG"
