#!/usr/bin/env python
"""Scan-threaded microbenchmarks: trustworthy per-iteration device timing.

Single-call timings through the remote tunnel are unreliable (identical-arg
calls appear memoized). Here every measured program is ONE jit containing a
`lax.scan` of K dependent iterations, so the device must execute all K and
per-iteration time = wall / K.

  1. fwd-only scan:    x -> logits -> fold a scalar back into x
  2. fwd+bwd scan:     signed-grad update of x through the victim
  3. fwd+bwd + masked_fill scan: the attack step's data path
  4. the real attack step block (stage 1)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import jax
import jax.numpy as jnp

from dorpatch_tpu import losses
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import AttackConfig
from dorpatch_tpu.models import get_model

RN50_FWD_GFLOPS = 4.3


def timed_scan(name, fn, args, k, flops_per_iter=None, reps=2):
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    per_iter = (time.perf_counter() - t0) / (reps * k)
    tfs = (f"  {flops_per_iter / per_iter / 1e12:7.2f} TFLOP/s"
           if flops_per_iter else "")
    print(f"{name:38s} {per_iter * 1e3:9.1f} ms/iter  (compile {compile_s:.0f}s){tfs}",
          flush=True)
    return per_iter


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--eot", type=int, default=32)
    p.add_argument("--img", type=int, default=224)
    p.add_argument("--k", type=int, default=8)
    p.add_argument("--only", default="", help="comma list: fwd,bwd,mf,step")
    args = p.parse_args()
    b, s, img, k = args.batch, args.eot, args.img, args.k
    n = b * s
    only = set(args.only.split(",")) if args.only else None

    print(f"devices: {jax.devices()}  batch={b} eot={s} img={img} k={k}", flush=True)
    victim = get_model("imagenet", "resnetv2", img_size=img)
    params16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
        victim.params)

    key = jax.random.PRNGKey(0)  # noqa: DP104 — standalone profiling harness, fixed seed is deliberate
    key, k_xb = jax.random.split(key)
    xb = jax.random.uniform(k_xb, (n, img, img, 3), jnp.bfloat16)

    if only is None or "fwd" in only:
        @jax.jit  # noqa: DP105 — harness times compile itself
        def fwd_scan(x0):
            def body(x, _):
                logits = victim.apply(params16, x)
                return x + logits.mean().astype(x.dtype) * 1e-9, None
            return jax.lax.scan(body, x0, None, length=k)[0]

        timed_scan("fwd-only scan", fwd_scan, (xb,), k,
                   n * RN50_FWD_GFLOPS * 1e9)

    if only is None or "bwd" in only:
        @jax.jit  # noqa: DP105 — harness times compile itself
        def fwdbwd_scan(x0):
            def body(x, _):
                g = jax.grad(
                    lambda xx: victim.apply(params16, xx).astype(jnp.float32).mean()
                )(x)
                return jnp.clip(x - 0.01 * jnp.sign(g), 0, 1), None
            return jax.lax.scan(body, x0, None, length=k)[0]

        timed_scan("fwd+bwd scan", fwdbwd_scan, (xb,), k,
                   n * 3 * RN50_FWD_GFLOPS * 1e9)

    cfg = AttackConfig(sampling_size=s, compute_dtype="bfloat16")
    universe = jnp.asarray(
        masks_lib.dropout_universe(img, cfg.dropout, cfg.dropout_sizes))
    key, k_x = jax.random.split(key)
    x = jax.random.uniform(k_x, (b, img, img, 3), jnp.float32)

    if only is None or "mf" in only:
        from dorpatch_tpu import ops

        @jax.jit  # noqa: DP105 — harness times compile itself
        def mf_scan(x0):
            def body(xc, i):
                rects = jax.lax.dynamic_slice_in_dim(universe, 0, s, 0)
                masked = ops.masked_fill(xc, rects, 0.5, "on")
                flat = masked.reshape((-1,) + xc.shape[1:]).astype(jnp.bfloat16)
                g = jax.grad(
                    lambda xx: victim.apply(
                        params16,
                        ops.masked_fill(xx, rects, 0.5, "on")
                        .reshape((-1,) + xx.shape[1:]).astype(jnp.bfloat16),
                    ).astype(jnp.float32).mean()
                )(xc)
                del flat
                return jnp.clip(xc - 0.01 * jnp.sign(g), 0, 1), None
            return jax.lax.scan(body, x0, None, length=k)[0]

        timed_scan("masked_fill+fwd+bwd scan (pallas)", mf_scan, (x,), k,
                   n * 3 * RN50_FWD_GFLOPS * 1e9)

    if only is None or "step" in only:
        attack = DorPatch(victim.apply, victim.params, victim.num_classes, cfg)
        y = jnp.zeros((b,), jnp.int32)
        lv = jnp.mean(losses.local_variance(x)[0], axis=-1)
        state = attack._init_state(key, x, y, False, universe.shape[0])
        block = attack._get_block(1, img, k)
        dt = timed_scan("attack step block (remat)", block,
                        (state, x, lv, universe), k,
                        n * 4 * RN50_FWD_GFLOPS * 1e9)
        print(f"attack images/sec: {b / dt:.2f}", flush=True)


if __name__ == "__main__":
    main()
