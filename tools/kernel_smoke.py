#!/usr/bin/env python
"""Pallas kernel-tier smoke: interpret-mode kernels vs the XLA tier
through the full pruned+incremental certify (CI gate, `run_tests.sh`).

The engines gate their kernels behind `DefenseConfig.use_pallas`; on the
CPU CI host "auto" resolves off, so this smoke pins the gate explicitly:
the SAME seeded batch through the same engine-backed schedule at
`use_pallas="off"` (pure XLA) and `use_pallas="interpret"` (the kernel
bodies emulated on CPU — the lowered TPU path shares them). One leg per
engine family:

- stem (CifarResNet18): the kernel shares `_delta_conv` with the fold —
  verdicts, first-round tables and every evaluated second-round entry
  must be BIT-identical.
- token (small ViT): the attention kernel is tolerance-contracted —
  verdict parity checked here (entry drift sits at f32 ULP scale, far
  under the margin gate; tests/test_kernel_tier.py asserts the tensor
  contract).
- mixer (small ResMLP): no kernel of its own — the gate must pass
  through as a no-op (bit-identical verdicts), guarding the plumbing.

The interpret side then proves the serving contract: after `warm_pruned`
at the smoke buckets, ragged traffic retraces NOTHING under the ARMED
recompile watchdog (`recompile_budget`).

Prints ONE JSON line: {"metric": "kernel_smoke", "parity": true, ...};
exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import UNEVALUATED, PatchCleanser
    from dorpatch_tpu.models.registry import incremental_engine
    from dorpatch_tpu.models.resmlp import ResMLP
    from dorpatch_tpu.models.small import CifarResNet18
    from dorpatch_tpu.models.vit import ViT

    img, n_classes, ratio = 32, 3, 0.1
    buckets = (1, 3)
    spec = masks_lib.geometry(img, ratio)
    rng = np.random.default_rng(1234)
    imgs = rng.uniform(0.0, 1.0, (3, img, img, 3)).astype(np.float32)
    imgs[0] = 0.5                 # gray: provably first-round unanimous
    imgs[1, :6, :6, :] = 1.0      # bright corner: disagreement inducer
    x = jnp.asarray(imgs)

    failures = []
    stats = {"metric": "kernel_smoke", "images": int(x.shape[0])}

    def build(apply_fn, engine, incremental, use_pallas):
        return PatchCleanser(
            apply_fn, spec,
            DefenseConfig(ratios=(ratio,), prune="exact",
                          incremental=incremental, use_pallas=use_pallas),
            incremental_engine=engine,
            recompile_budget=len(buckets) + 1)

    def leg(name, apply_fn, engine, incremental, params, exact):
        xla = build(apply_fn, engine, incremental, "off")
        kern = build(apply_fn, engine, incremental, "interpret")
        want = xla.robust_predict(params, x, n_classes, bucket_sizes=buckets)
        # warm the kernel side FIRST, then require traffic (including a
        # ragged 2-image batch) to retrace nothing with the watchdog armed
        kern.warm_pruned(params, buckets, num_classes=n_classes)
        warm_counts = kern.pruned_trace_counts()
        got = kern.robust_predict(params, x, n_classes, bucket_sizes=buckets)
        kern.robust_predict(params, x[:2], n_classes, bucket_sizes=buckets)
        if kern.pruned_trace_counts() != warm_counts:
            failures.append(f"{name}: kernel path retraced under the armed "
                            f"watchdog: {warm_counts} -> "
                            f"{kern.pruned_trace_counts()}")
        for i, (w, g) in enumerate(zip(want, got)):
            if (w.prediction, w.certification) != (g.prediction,
                                                   g.certification):
                failures.append(f"{name} image {i}: verdict "
                                f"({w.prediction}, {w.certification}) != "
                                f"({g.prediction}, {g.certification})")
            if exact:
                if not np.array_equal(w.preds_1, g.preds_1):
                    failures.append(f"{name} image {i}: first-round tables "
                                    "differ (bit-exact contract)")
                ev = g.preds_2 != UNEVALUATED
                if not np.array_equal(w.preds_2[ev], g.preds_2[ev]):
                    failures.append(f"{name} image {i}: evaluated "
                                    "second-round entries differ")
        stats[f"{name}_verdicts"] = [[int(g.prediction),
                                      bool(g.certification)] for g in got]

    # ---- stem leg (bit-exact kernel contract) ----
    conv = CifarResNet18(num_classes=n_classes)
    cparams = conv.init(jax.random.PRNGKey(6),  # noqa: DP104 fixed smoke seed
                        jnp.zeros((1, img, img, 3)))
    leg("stem", lambda p, xx: conv.apply(p, (xx - 0.5) / 0.5),
        incremental_engine("cifar_resnet18", conv, img), "stem",
        cparams, exact=True)

    # ---- token leg (margin-contracted attention kernel) ----
    vit = ViT(num_classes=n_classes, patch_size=4, dim=32, depth=2,
              num_heads=2, img_size=(img, img))
    vparams = vit.init(jax.random.PRNGKey(5),  # noqa: DP104 fixed smoke seed
                       jnp.zeros((1, img, img, 3)))
    leg("token", lambda p, xx: vit.apply(p, (xx - 0.5) / 0.5),
        incremental_engine("cifar_vit", vit, img), "token",
        vparams, exact=False)

    # ---- mixer leg (gate pass-through, no kernel) ----
    mlp = ResMLP(num_classes=n_classes, patch_size=4, dim=32, depth=2,
                 img_size=img)
    mparams = mlp.init(jax.random.PRNGKey(7),  # noqa: DP104 fixed smoke seed
                       jnp.zeros((1, img, img, 3)))
    leg("mixer", lambda p, xx: mlp.apply(p, (xx - 0.5) / 0.5),
        incremental_engine("cifar_resmlp", mlp, img), "mixer",
        mparams, exact=True)

    stats.update({"parity": not failures, "failures": failures})
    print(json.dumps(stats))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
