#!/usr/bin/env python
"""Pallas kernel-tier smoke: interpret-mode kernels vs the XLA tier
through the full pruned+incremental certify (CI gate, `run_tests.sh`).

The engines gate their kernels behind `DefenseConfig.use_pallas`; on the
CPU CI host "auto" resolves off, so this smoke pins the gate explicitly:
the SAME seeded batch through the same engine-backed schedule at
`use_pallas="off"` (pure XLA) and `use_pallas="interpret"` (the kernel
bodies emulated on CPU — the lowered TPU path shares them). One leg per
engine family:

- stem (CifarResNet18): the kernel shares `_delta_conv` with the fold —
  verdicts, first-round tables and every evaluated second-round entry
  must be BIT-identical.
- token (small ViT): the attention kernel is tolerance-contracted —
  verdict parity checked here (entry drift sits at f32 ULP scale, far
  under the margin gate; tests/test_kernel_tier.py asserts the tensor
  contract).
- mixer (small ResMLP): no kernel of its own — the gate must pass
  through as a no-op (bit-identical verdicts), guarding the plumbing.

The interpret side then proves the serving contract: after `warm_pruned`
at the smoke buckets, ragged traffic retraces NOTHING under the ARMED
recompile watchdog (`recompile_budget`).

A fourth, mesh leg (multi-device hosts; the gate forces 8 virtual CPU
devices) re-proves the off-vs-interpret contract for the MESHED phase-1
programs — the stem/token kernels inside their `shard_map` wrappers over
the data axis, the programs the DP603 shard-local audit certifies — and
requires a warm same-shape re-dispatch to retrace nothing.

Prints ONE JSON line: {"metric": "kernel_smoke", "parity": true, ...};
exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# before any jax import: the mesh leg needs 8 virtual CPU devices
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")


def main(argv=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import UNEVALUATED, PatchCleanser
    from dorpatch_tpu.models.registry import incremental_engine
    from dorpatch_tpu.models.resmlp import ResMLP
    from dorpatch_tpu.models.small import CifarResNet18
    from dorpatch_tpu.models.vit import ViT

    img, n_classes, ratio = 32, 3, 0.1
    buckets = (1, 3)
    spec = masks_lib.geometry(img, ratio)
    rng = np.random.default_rng(1234)
    imgs = rng.uniform(0.0, 1.0, (3, img, img, 3)).astype(np.float32)
    imgs[0] = 0.5                 # gray: provably first-round unanimous
    imgs[1, :6, :6, :] = 1.0      # bright corner: disagreement inducer
    x = jnp.asarray(imgs)

    failures = []
    stats = {"metric": "kernel_smoke", "images": int(x.shape[0])}

    def build(apply_fn, engine, incremental, use_pallas):
        return PatchCleanser(
            apply_fn, spec,
            DefenseConfig(ratios=(ratio,), prune="exact",
                          incremental=incremental, use_pallas=use_pallas),
            incremental_engine=engine,
            recompile_budget=len(buckets) + 1)

    def leg(name, apply_fn, engine, incremental, params, exact):
        xla = build(apply_fn, engine, incremental, "off")
        kern = build(apply_fn, engine, incremental, "interpret")
        want = xla.robust_predict(params, x, n_classes, bucket_sizes=buckets)
        # warm the kernel side FIRST, then require traffic (including a
        # ragged 2-image batch) to retrace nothing with the watchdog armed
        kern.warm_pruned(params, buckets, num_classes=n_classes)
        warm_counts = kern.pruned_trace_counts()
        got = kern.robust_predict(params, x, n_classes, bucket_sizes=buckets)
        kern.robust_predict(params, x[:2], n_classes, bucket_sizes=buckets)
        if kern.pruned_trace_counts() != warm_counts:
            failures.append(f"{name}: kernel path retraced under the armed "
                            f"watchdog: {warm_counts} -> "
                            f"{kern.pruned_trace_counts()}")
        for i, (w, g) in enumerate(zip(want, got)):
            if (w.prediction, w.certification) != (g.prediction,
                                                   g.certification):
                failures.append(f"{name} image {i}: verdict "
                                f"({w.prediction}, {w.certification}) != "
                                f"({g.prediction}, {g.certification})")
            if exact:
                if not np.array_equal(w.preds_1, g.preds_1):
                    failures.append(f"{name} image {i}: first-round tables "
                                    "differ (bit-exact contract)")
                ev = g.preds_2 != UNEVALUATED
                if not np.array_equal(w.preds_2[ev], g.preds_2[ev]):
                    failures.append(f"{name} image {i}: evaluated "
                                    "second-round entries differ")
        stats[f"{name}_verdicts"] = [[int(g.prediction),
                                      bool(g.certification)] for g in got]

    # ---- stem leg (bit-exact kernel contract) ----
    conv = CifarResNet18(num_classes=n_classes)
    cparams = conv.init(jax.random.PRNGKey(6),  # noqa: DP104 fixed smoke seed
                        jnp.zeros((1, img, img, 3)))
    leg("stem", lambda p, xx: conv.apply(p, (xx - 0.5) / 0.5),
        incremental_engine("cifar_resnet18", conv, img), "stem",
        cparams, exact=True)

    # ---- token leg (margin-contracted attention kernel) ----
    vit = ViT(num_classes=n_classes, patch_size=4, dim=32, depth=2,
              num_heads=2, img_size=(img, img))
    vparams = vit.init(jax.random.PRNGKey(5),  # noqa: DP104 fixed smoke seed
                       jnp.zeros((1, img, img, 3)))
    leg("token", lambda p, xx: vit.apply(p, (xx - 0.5) / 0.5),
        incremental_engine("cifar_vit", vit, img), "token",
        vparams, exact=False)

    # ---- mixer leg (gate pass-through, no kernel) ----
    mlp = ResMLP(num_classes=n_classes, patch_size=4, dim=32, depth=2,
                 img_size=img)
    mparams = mlp.init(jax.random.PRNGKey(7),  # noqa: DP104 fixed smoke seed
                       jnp.zeros((1, img, img, 3)))
    leg("mixer", lambda p, xx: mlp.apply(p, (xx - 0.5) / 0.5),
        incremental_engine("cifar_resmlp", mlp, img), "mixer",
        mparams, exact=True)

    # ---- mesh leg (the shard_map kernel wrappers; even multi-device
    # hosts — the test gate's 8-device virtual CPU mesh) ----
    # off-vs-interpret parity for the SAME meshed phase-1 programs the
    # DP603 shard-local audit certifies: the stem/token kernels trace
    # inside `fold_masked_stem_sharded` / `masked_kv_attention_sharded`
    # over the data axis, outputs must match each kernel's contract
    # against the kernel-off mesh path, and a warm same-shape re-dispatch
    # must retrace NOTHING.
    if jax.device_count() >= 2 and jax.device_count() % 2 == 0:
        from dorpatch_tpu.parallel import make_mesh

        mesh = make_mesh(2, jax.device_count() // 2)
        singles, doubles = masks_lib.mask_sets(spec)
        k = max(singles.shape[1], doubles.shape[1])
        rects = np.concatenate([masks_lib.pad_rects(singles, k),
                                masks_lib.pad_rects(doubles, k)], axis=0)
        xm = x[:2]  # batch 2 shards the size-2 data axis
        for name, engine, params in (
                ("stem", incremental_engine("cifar_resnet18", conv, img),
                 cparams),
                ("token", incremental_engine("cifar_vit", vit, img),
                 vparams)):
            def fam(mode, _e=engine):
                return _e.build_family(rects, singles.shape[0], 64, 0.5,
                                       use_pallas=mode, mesh=mesh)

            traces = []
            kern_phase1 = fam("interpret").phase1

            def counted(p, xx, _f=kern_phase1, _t=traces):
                _t.append(1)
                return _f(p, xx)

            run_on = jax.jit(counted)  # noqa: DP105 — smoke counts traces itself
            want = jax.jit(fam("off").phase1)(  # noqa: DP105 — smoke counts traces itself
                params, xm)
            got = run_on(params, xm)
            run_on(params, xm)  # warm re-dispatch: must not retrace
            if len(traces) != 1:
                failures.append(f"mesh {name}: kernel wrapper retraced on "
                                f"a warm same-shape dispatch "
                                f"({len(traces)} traces)")
            # the WRAPPER is bit-exact against the plain fold
            # (tests/test_kernel_tier.py pins that); at whole-program
            # scope the shard_map changes how XLA compiles the
            # SURROUNDING stem/trunk convs, so the family-level mesh
            # contract is verdict-grade: predictions bit-equal, margins
            # at f32 ULP scale (measured 1.3e-6 abs)
            for wl, gl in zip(jax.tree_util.tree_leaves(want),
                              jax.tree_util.tree_leaves(got)):
                wl, gl = np.asarray(wl), np.asarray(gl)
                if np.issubdtype(wl.dtype, np.integer):
                    if not np.array_equal(wl, gl):
                        failures.append(f"mesh {name}: phase-1 predictions "
                                        "differ")
                elif not np.allclose(wl, gl, atol=1e-5, rtol=1e-4):
                    failures.append(f"mesh {name}: phase-1 margins drift "
                                    "past f32 ULP scale")
            stats[f"mesh_{name}"] = "parity"
    else:
        stats["mesh"] = f"skipped ({jax.device_count()} device(s))"

    stats.update({"parity": not failures, "failures": failures})
    print(json.dumps(stats))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
