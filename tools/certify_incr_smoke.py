#!/usr/bin/env python
"""Incremental-certify smoke: engine-backed vs PR 5 pruned-only double
masking on a seeded batch (CI gate, `run_tests.sh`).

Three legs, one per engine family, at the production 36-mask geometry:

- token (small ViT victim): `DefenseConfig.incremental="token"` must yield
  the same verdicts as the pruned-only path on the seeded batch (the batch
  and the deterministic init make this reproducible; entry-level drift is
  tolerance-contracted, verdict-level checked here) while executing
  STRICTLY LOWER forward-equivalents — the fractional full-forward cost
  the token engine records per entry.
- mixer (small ResMLP victim): same contract as the token leg — the
  mixer engine's dirty-row tracking is tolerance-contracted per entry,
  verdict parity and strictly lower forward-equivalents checked here.
- stem (CifarResNet18 victim): the masked-stem fold is algebraically
  exact — verdicts and every evaluated second-round entry bit-identical.

Prints ONE JSON line: {"metric": "certify_incr_smoke", "parity": true,
"fe_token": ..., "fe_pruned_only": ..., ...}; exits non-zero on any
violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import UNEVALUATED, PatchCleanser
    from dorpatch_tpu.models.registry import incremental_engine
    from dorpatch_tpu.models.small import CifarResNet18
    from dorpatch_tpu.models.vit import ViT

    img, n_classes, ratio = 32, 3, 0.1
    spec = masks_lib.geometry(img, ratio)
    rng = np.random.default_rng(1234)
    imgs = rng.uniform(0.0, 1.0, (3, img, img, 3)).astype(np.float32)
    imgs[0] = 0.5                 # gray: provably first-round unanimous
    imgs[1, :6, :6, :] = 1.0      # bright corner: disagreement inducer
    x = jnp.asarray(imgs)

    failures = []
    stats = {"metric": "certify_incr_smoke", "images": int(x.shape[0])}

    def build(apply_fn, engine, incremental):
        return PatchCleanser(
            apply_fn, spec,
            DefenseConfig(ratios=(ratio,), prune="exact",
                          incremental=incremental),
            incremental_engine=engine if incremental != "off" else None)

    # ---- token leg (small ViT) ----
    vit = ViT(num_classes=n_classes, patch_size=4, dim=32, depth=2,
              num_heads=2, img_size=(img, img))
    # noqa-reason: the smoke's whole point is a pinned, reproducible victim
    vparams = vit.init(jax.random.PRNGKey(5),  # noqa: DP104 fixed smoke seed
                       jnp.zeros((1, img, img, 3)))

    def vapply(p, xx):
        return vit.apply(p, (xx - 0.5) / 0.5)

    vengine = incremental_engine("cifar_vit", vit, img)
    pruned = build(vapply, None, "off")
    token = build(vapply, vengine, "token")
    want = pruned.robust_predict(vparams, x, n_classes, bucket_sizes=(1, 4))
    got = token.robust_predict(vparams, x, n_classes, bucket_sizes=(1, 4))
    for i, (w, g) in enumerate(zip(want, got)):
        if (w.prediction, w.certification) != (g.prediction,
                                               g.certification):
            failures.append(f"token image {i}: verdict "
                            f"({w.prediction}, {w.certification}) != "
                            f"({g.prediction}, {g.certification})")
    fe_token = sum(r.forward_equivalents for r in got)
    fe_pruned = sum(r.forward_equivalents for r in want)
    if not fe_token < fe_pruned:
        failures.append(f"token path not cheaper: {fe_token} "
                        f"forward-equivalents vs pruned-only {fe_pruned}")
    stats.update({"fe_token": round(fe_token, 1),
                  "fe_pruned_only": round(fe_pruned, 1),
                  "fe_first_round_token": round(
                      token.first_round_forward_equivalents, 2)})

    # ---- mixer leg (small ResMLP) ----
    from dorpatch_tpu.models.resmlp import ResMLP

    mlp = ResMLP(num_classes=n_classes, patch_size=4, dim=32, depth=2,
                 img_size=img)
    # noqa-reason: the smoke's whole point is a pinned, reproducible victim
    mparams = mlp.init(jax.random.PRNGKey(7),  # noqa: DP104 fixed smoke seed
                       jnp.zeros((1, img, img, 3)))

    def mapply(p, xx):
        return mlp.apply(p, (xx - 0.5) / 0.5)

    mengine = incremental_engine("resmlp_24_distilled_224", mlp, img)
    mpruned = build(mapply, None, "off")
    mixer = build(mapply, mengine, "mixer")
    mwant = mpruned.robust_predict(mparams, x, n_classes, bucket_sizes=(1, 4))
    mgot = mixer.robust_predict(mparams, x, n_classes, bucket_sizes=(1, 4))
    for i, (w, g) in enumerate(zip(mwant, mgot)):
        if (w.prediction, w.certification) != (g.prediction,
                                               g.certification):
            failures.append(f"mixer image {i}: verdict "
                            f"({w.prediction}, {w.certification}) != "
                            f"({g.prediction}, {g.certification})")
    fe_mixer = sum(r.forward_equivalents for r in mgot)
    fe_mpruned = sum(r.forward_equivalents for r in mwant)
    if not fe_mixer < fe_mpruned:
        failures.append(f"mixer path not cheaper: {fe_mixer} "
                        f"forward-equivalents vs pruned-only {fe_mpruned}")
    stats.update({"fe_mixer": round(fe_mixer, 1),
                  "fe_mixer_pruned_only": round(fe_mpruned, 1)})

    # ---- stem leg (CifarResNet18, exact) ----
    conv = CifarResNet18(num_classes=n_classes)
    cparams = conv.init(jax.random.PRNGKey(6),  # noqa: DP104 fixed smoke seed
                        jnp.zeros((1, img, img, 3)))

    def capply(p, xx):
        return conv.apply(p, (xx - 0.5) / 0.5)

    cengine = incremental_engine("cifar_resnet18", conv, img)
    cpruned = build(capply, None, "off")
    cstem = build(capply, cengine, "stem")
    cwant = cpruned.robust_predict(cparams, x, n_classes, bucket_sizes=(1, 4))
    cgot = cstem.robust_predict(cparams, x, n_classes, bucket_sizes=(1, 4))
    for i, (w, g) in enumerate(zip(cwant, cgot)):
        if (w.prediction, w.certification) != (g.prediction,
                                               g.certification):
            failures.append(f"stem image {i}: verdict mismatch")
        if not np.array_equal(w.preds_1, g.preds_1):
            failures.append(f"stem image {i}: first-round tables differ")
        ev = g.preds_2 != UNEVALUATED
        if not np.array_equal(w.preds_2[ev], g.preds_2[ev]):
            failures.append(f"stem image {i}: evaluated second-round "
                            "entries differ")

    stats.update({"parity": not failures, "failures": failures})
    print(json.dumps(stats))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
