#!/usr/bin/env python
"""Farm smoke: SIGKILL crash-resume, end to end (CI gate, `run_tests.sh`).

The scenario the farm exists for, executed for real with separate worker
processes over a shared farm directory:

1. submit a 4-job attack-sweep grid (tiny synthetic cifar10/resnet18@32);
2. a chaos worker (`--chaos crash_block --crash-mode kill`) claims the
   first job and SIGKILLs itself at a seeded attack-block boundary — after
   the block's carry snapshot was saved, before the job could complete;
3. two healthy workers then drain the farm concurrently: one of them
   reclaims the dead worker's job via heartbeat-stale lease takeover and
   *resumes it from the checkpoint*;
4. a control `run_sweep` runs the killed job's grid point uninterrupted in
   this process.

Asserts: every job `done`, zero jobs lost; the killed job shows
attempts == 2, reclaims == 1, and a resumed point (steps not re-run from
zero); its final patch artifacts are bit-identical to the control run; and
the fleet report renders with the retry accounting.

Prints ONE JSON line: {"metric": "farm_smoke", "ok": true, ...}; exits
non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ATTACK = {"sampling_size": 4, "max_iterations": 4, "sweep_interval": 2,
          "switch_iteration": 2, "dropout": 1, "dropout_sizes": [0.06],
          "basic_unit": 4}
BASE = {"dataset": "cifar10", "base_arch": "resnet18", "img_size": 32,
        "batch_size": 2, "synthetic_data": True, "attack": ATTACK}
BUDGETS = [0.08, 0.1, 0.12, 0.15]
SWEEP = {"densities": [0.0], "structureds": [1e-3], "defense_ratio": 0.06}
LEASE_TTL = 5.0


def _work_cmd(farm_dir, worker_id, extra=()):
    return [sys.executable, "-m", "dorpatch_tpu.farm", "work", farm_dir,
            "--worker-id", worker_id, "--lease-ttl", str(LEASE_TTL),
            "--heartbeat-interval", "0.25", "--poll-interval", "0.25",
            "--backoff-base", "0.5", "--backoff-cap", "2.0",
            *extra]


def main(argv=None) -> int:
    workdir = tempfile.mkdtemp(prefix="farm_smoke_")
    farm_dir = os.path.join(workdir, "farm")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               # one shared XLA compile cache: the four processes (killer,
               # two drainers, this control run) compile each program once
               JAX_COMPILATION_CACHE_DIR=os.path.join(workdir, "xla_cache"))
    os.environ["JAX_COMPILATION_CACHE_DIR"] = env["JAX_COMPILATION_CACHE_DIR"]

    from dorpatch_tpu.farm.queue import JobQueue
    from dorpatch_tpu.farm.report import format_fleet_report, summarize_fleet

    jq = JobQueue(farm_dir)
    ids = jq.submit_spec({"base": BASE,
                          "axes": {"attack.patch_budget": BUDGETS},
                          "sweep": SWEEP, "max_attempts": 3})

    failures = []

    # ---- phase 1: the doomed worker (claims the first job, SIGKILLs) ----
    killer = subprocess.run(
        _work_cmd(farm_dir, "wKill",
                  ("--chaos", "crash_block", "--crash-mode", "kill",
                   "--max-jobs", "1")),
        env=env, capture_output=True, text=True, timeout=600)
    if killer.returncode != -signal.SIGKILL:
        failures.append(
            f"chaos worker exited {killer.returncode}, expected SIGKILL "
            f"(-9); stderr tail: {killer.stderr[-800:]}")
    killed = jq.read_job(ids[0])
    if killed["state"] != "running" or killed["attempts"] != 1:
        failures.append(
            "after SIGKILL the job should be orphaned mid-run "
            f"(state=running, attempts=1), got state={killed['state']} "
            f"attempts={killed['attempts']}")
    ck_root = os.path.join(jq.job_dir(ids[0]), "checkpoints", "carry_0")
    if not os.path.isdir(ck_root) or not os.listdir(ck_root):
        failures.append("no carry snapshot survived the SIGKILL — nothing "
                        "for the reclaimer to resume from")

    # ---- phase 2: two healthy workers drain the farm concurrently ----
    drainers = [subprocess.Popen(_work_cmd(farm_dir, w), env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
                for w in ("wA", "wB")]
    deadline = time.time() + 1200
    for proc in drainers:
        out, _ = proc.communicate(timeout=max(30, deadline - time.time()))
        if proc.returncode != 0:
            failures.append(f"drain worker exited {proc.returncode}; "
                            f"output tail: {out[-800:]}")

    counts = jq.counts()
    if counts["done"] != len(ids):
        failures.append(f"jobs lost: expected {len(ids)} done, got {counts}")
    killed = jq.read_job(ids[0])
    if killed.get("attempts") != 2:
        failures.append("killed job should show attempts == 2 (one life per "
                        f"worker), got {killed.get('attempts')}")
    if killed.get("reclaims", 0) != 1:
        failures.append(
            f"killed job should show reclaims == 1, got {killed.get('reclaims')}")
    result = killed.get("result", {})
    if result.get("resumed_points") != 1:
        failures.append("reclaimed job must resume from the carry snapshot, "
                        f"not restart: result={result}")

    # ---- phase 3: uninterrupted control run of the killed grid point ----
    from dorpatch_tpu.config import config_from_dict
    from dorpatch_tpu.sweep import run_sweep

    control_dir = os.path.join(workdir, "control")
    cfg = config_from_dict(dict(BASE))
    run_sweep(cfg, patch_budgets=(BUDGETS[0],),
              densities=tuple(SWEEP["densities"]),
              structureds=tuple(SWEEP["structureds"]),
              defense_ratio=SWEEP["defense_ratio"], verbose=False,
              result_dir=control_dir)

    import numpy as np

    result_dir = os.path.join(jq.job_dir(ids[0]), "results")
    for name in ("point_000_mask.npy", "point_000_pattern.npy"):
        got = np.load(os.path.join(result_dir, name))
        want = np.load(os.path.join(control_dir, name))
        if not np.array_equal(got, want):
            failures.append(f"{name}: crash-resumed artifact differs from "
                            "the uninterrupted control run")

    # ---- phase 4: the fleet report must render the accounting ----
    fleet = summarize_fleet(farm_dir)
    text = format_fleet_report(fleet)
    for needle in ("-- farm --", "-- jobs --", "-- robust accuracy --"):
        if needle not in text:
            failures.append(f"fleet report missing section {needle!r}")
    if fleet["retries"] < 1 or fleet["reclaims"] < 1:
        failures.append(f"fleet accounting lost the crash: retries="
                        f"{fleet['retries']} reclaims={fleet['reclaims']}")

    print(json.dumps({
        "metric": "farm_smoke",
        "ok": not failures,
        "jobs": len(ids),
        "done": counts["done"],
        "killed_job_attempts": killed.get("attempts"),
        "killed_job_reclaims": killed.get("reclaims"),
        "resumed_points": result.get("resumed_points"),
        "retries": fleet["retries"],
        "wasted_s": fleet["step_time"]["wasted_s"],
        "useful_s": fleet["step_time"]["useful_s"],
        "failures": failures,
    }, default=float))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        print(f"farm dir kept for debugging: {workdir}", file=sys.stderr)
        return 1
    shutil.rmtree(workdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
