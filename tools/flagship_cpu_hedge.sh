#!/bin/bash
# CPU hedge for the trained-victim flagship (VERDICT r04 next #3): when the
# tunnel is down, produce the full protocol evidence on CPU — trained
# victim -> two-stage attack on the procedural eval split -> 4-radius
# certification -> torch-oracle certified-ASR parity. Scaled config
# (sampling 16, 200 iters) so one CPU core finishes in hours, recorded in
# the run's config.json so the parity oracle scores the same scale.
set -u
cd "$(dirname "$0")/.."
LOG=artifacts/flagship_cpu_hedge.log
CKPT=artifacts/victim_r05_cpu/cifar10/cifar_resnet18_cutout2_128_cifar10.pth
echo "$(date -u +%FT%TZ) hedge: waiting for $CKPT" >> "$LOG"
for i in $(seq 1 720); do
  [ -f "$CKPT" ] && break
  sleep 60
done
if [ ! -f "$CKPT" ]; then
  echo "$(date -u +%FT%TZ) hedge: no checkpoint after 12h; giving up" >> "$LOG"
  exit 1
fi
echo "$(date -u +%FT%TZ) hedge: attacking" >> "$LOG"
env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m dorpatch_tpu.cli \
  --data-source procedural --dataset cifar10 --base_arch resnet18 \
  --img-size 32 -b 4 --num-batches 2 --sampling-size 16 \
  --max-iterations 200 --model_dir artifacts/victim_r05_cpu \
  --results-root artifacts/flagship_r05_cpu >> "$LOG" 2>&1
rc=$?
echo "$(date -u +%FT%TZ) hedge: attack rc=$rc" >> "$LOG"
[ $rc -ne 0 ] && exit $rc
echo "$(date -u +%FT%TZ) hedge: torch-oracle parity" >> "$LOG"
python tools/parity_flagship.py --jax-root artifacts/flagship_r05_cpu \
  --model-dir artifacts/victim_r05_cpu --attack \
  --out artifacts/PARITY_r05_cpu.json >> "$LOG" 2>&1
echo "$(date -u +%FT%TZ) hedge: parity rc=$?" >> "$LOG"
