#!/usr/bin/env python
"""Certify-mesh smoke: the sharded pruned certification path on an
8-device virtual CPU mesh (CI gate, `run_tests.sh`).

Runs the seeded stub batch of `certify_prune_smoke` through three
certifiers — the single-chip pruned oracle, the meshed exhaustive sweep,
and the meshed two-phase pruned schedule (phase 1 sharded over the data
axis, phase-2 worklists planned shard-locally and dispatched as
`[S * bucket]` SPMD waves; `defense._PrunedPending._schedule_mesh`) on a
(data=4, mask=2) mesh — then asserts:

- verdict parity: (prediction, certification) bit-identical across all
  three, first-round tables equal, and every double-masked entry the
  meshed pruned path DID evaluate matches the meshed exhaustive table;
- forwards accounting: the meshed pruned run counts exactly the
  single-chip pruned oracle's forwards, strictly fewer than exhaustive;
- the report CLI renders the prune rate from a run dir whose certify
  span carries the meshed run's forwards/forwards_exhaustive attrs.

Prints ONE JSON line: {"metric": "certify_mesh_smoke", "parity": true,
"mesh": "4x2", ...}; exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# before any jax import: the mesh needs 8 virtual CPU devices
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")


def main(argv=None) -> int:
    import numpy as np

    import jax.numpy as jnp

    from dorpatch_tpu import masks as masks_lib, observe, parallel
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import PatchCleanser

    img, n_classes = 32, 2

    def stub(params, x):
        # weightless trigger detector (certify_prune_smoke's): class 1 iff
        # the 4x4 region at (20:24, 20:24) is mostly bright — only masks
        # occluding the whole trigger flip it, so those masks form a small
        # genuine first-round minority (the pruned second round's target)
        score = x[:, 20:24, 20:24, :].mean(axis=(1, 2, 3))
        return jnp.stack([0.7 - score, score - 0.7], axis=-1)

    rng = np.random.default_rng(1234)
    imgs = np.full((6, img, img, 3), 0.2, np.float32)
    imgs += rng.uniform(0.0, 0.05, imgs.shape).astype(np.float32)
    imgs[0] = 0.5  # gray: provably first-round unanimous (and certified)
    imgs[3, 20:24, 20:24, :] = 1.0  # planted triggers: first-round
    imgs[4, 20:24, 20:24, :] = 1.0  # disagreement -> pruned second round
    x = jnp.asarray(imgs)

    spec = masks_lib.geometry(img, 0.1)
    oracle = PatchCleanser(stub, spec,
                           DefenseConfig(ratios=(0.1,), prune="exact"))
    mesh = parallel.make_mesh(4, 2)
    cfg = DefenseConfig(ratios=(0.1,), prune="exact")
    sharded = parallel.make_sharded_defenses(stub, img, mesh, cfg)[0]

    failures = []
    if sharded.resolved_prune() != "exact":
        failures.append("meshed certifier downgraded prune "
                        f"to {sharded.resolved_prune()!r}")
    xm = parallel.place_batch_auto(mesh, x)
    want = oracle.robust_predict(None, x, n_classes, bucket_sizes=(1, 8))
    got = sharded.robust_predict(None, xm, n_classes)
    exh = sharded.robust_predict(None, xm, n_classes, prune="off")

    for i, (w, g, e) in enumerate(zip(want, got, exh)):
        if not (w.prediction == g.prediction == e.prediction) or \
                not (w.certification == g.certification == e.certification):
            failures.append(
                f"image {i}: verdicts diverge — single-chip pruned "
                f"({w.prediction}, {w.certification}), meshed pruned "
                f"({g.prediction}, {g.certification}), meshed exhaustive "
                f"({e.prediction}, {e.certification})")
        if not (np.array_equal(w.preds_1, g.preds_1)
                and np.array_equal(np.asarray(e.preds_1), g.preds_1)):
            failures.append(f"image {i}: first-round tables differ")
        evaluated = g.preds_2 >= 0
        if not np.array_equal(np.asarray(e.preds_2)[evaluated],
                              g.preds_2[evaluated]):
            failures.append(f"image {i}: evaluated second-round entries "
                            "differ from the meshed exhaustive table")
        if w.forwards != g.forwards:
            failures.append(f"image {i}: meshed pruned counted "
                            f"{g.forwards} forwards, single-chip oracle "
                            f"{w.forwards}")

    fwd = sum(r.forwards for r in got)
    exhaustive = sum(r.forwards for r in exh)
    if not fwd < exhaustive:
        failures.append(f"no pruning on the mesh: executed {fwd} vs "
                        f"exhaustive {exhaustive}")

    # the report CLI must derive the prune rate from a meshed run's
    # certify span (the attrs pipeline.py records on both paths)
    run_dir = tempfile.mkdtemp(prefix="certify_mesh_smoke_")
    try:
        with observe.EventLog(os.path.join(run_dir, "events.jsonl"),
                              run_id="certify-mesh-smoke") as el:
            with el.span("run"):
                with el.span("certify", images=len(got)) as sp:
                    sp["forwards"] = int(fwd)
                    sp["forward_equivalents"] = float(sum(
                        r.forward_equivalents for r in got))
                    sp["forwards_exhaustive"] = int(exhaustive)
        rendered = subprocess.run(
            [sys.executable, "-m", "dorpatch_tpu.observe.report", run_dir],
            capture_output=True, text=True, timeout=120)
        if rendered.returncode != 0:
            failures.append("report CLI failed on the mesh run dir: "
                            + rendered.stderr[-500:])
        elif "prune rate" not in rendered.stdout:
            failures.append("report CLI did not render the prune rate "
                            "for the mesh run")
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)

    print(json.dumps({
        "metric": "certify_mesh_smoke",
        "parity": not failures,
        "mesh": "4x2",
        "images": len(got),
        "forwards": int(fwd),
        "forwards_exhaustive": int(exhaustive),
        "prune_rate": round(1.0 - fwd / exhaustive, 4),
        "failures": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
