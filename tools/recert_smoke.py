#!/usr/bin/env python
"""Recert smoke: crash-resumed generation, regression gate, canary boot
(CI gate, `run_tests.sh`).

The continuous re-certification story, executed for real with separate
scheduler processes over the tiny synthetic cifar10/resnet18@32 victim:

1. a control scheduler runs ONE full generation uninterrupted — 2x2
   (patch_budget x density) grid submitted to its private farm, drained by
   the in-process farm worker running the real attack+certify sweep,
   harvested and folded into a fresh robustness baseline;
2. a chaos scheduler runs the same spec with
   ``--chaos recert_kill_cycle,recert_torn_state``: the state file is torn
   mid-byte and the process SIGKILLs itself right after the grid is
   submitted — jobs live, nothing harvested, state file unreadable;
3. a plain re-run of the chaos dir must recover from the torn state,
   resume the SAME generation (never submit a second one), and commit a
   baseline BYTE-IDENTICAL to the control's;
4. a planted regression (baseline entry bumped past its tolerance) must
   make ``recert check`` exit 1 naming the cell (DP400);
5. serve boots against the now-failing verdict: ``--require-recert
   strict`` refuses serving-ready with the typed `RecertGateError` before
   any compile; ``warn`` boots (recompile watchdog armed), serves one
   certified predict, and `GET /robustness` answers 503 rendering the
   regressed cell.

Prints ONE JSON line: {"metric": "recert_smoke", "ok": true, ...}; exits
non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ATTACK = {"sampling_size": 4, "max_iterations": 4, "sweep_interval": 2,
          "switch_iteration": 2, "dropout": 1, "dropout_sizes": [0.06],
          "basic_unit": 4}
SPEC = {
    "base": {"dataset": "cifar10", "base_arch": "resnet18", "img_size": 32,
             "batch_size": 2, "synthetic_data": True, "attack": ATTACK},
    "axes": {"attack.patch_budget": [0.06, 0.12]},
    "sweep": {"densities": [0.0, 0.5], "structureds": [1e-3],
              "defense_ratio": 0.06},
    "max_attempts": 2,
}


def _run_cmd(recert_dir, baseline_file, spec_path, extra=()):
    return [sys.executable, "-m", "dorpatch_tpu.recert", "run", recert_dir,
            "--spec", spec_path, "--baseline-file", baseline_file,
            "--update-baseline", "--poll-interval", "0.1",
            "--lease-ttl", "30", "--worker-id", "recert-smoke", *extra]


def main(argv=None) -> int:
    workdir = tempfile.mkdtemp(prefix="recert_smoke_")
    spec_path = os.path.join(workdir, "spec.json")
    with open(spec_path, "w") as fh:
        json.dump(SPEC, fh)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               # one shared XLA compile cache across the scheduler
               # subprocesses and this process's serve boot
               JAX_COMPILATION_CACHE_DIR=os.path.join(workdir, "xla_cache"))
    os.environ["JAX_COMPILATION_CACHE_DIR"] = env["JAX_COMPILATION_CACHE_DIR"]

    failures = []
    t0 = time.time()

    # ---- phase 1: uninterrupted control generation ----
    control_dir = os.path.join(workdir, "control")
    control_rb = os.path.join(workdir, "control_baseline.json")
    control = subprocess.run(_run_cmd(control_dir, control_rb, spec_path),
                             env=env, capture_output=True, text=True,
                             timeout=1200)
    if control.returncode != 0:
        failures.append(f"control run exited {control.returncode}; stderr "
                        f"tail: {control.stderr[-800:]}")
    control_s = time.time() - t0
    control_bytes = (open(control_rb, "rb").read()
                     if os.path.exists(control_rb) else b"")
    if not control_bytes:
        failures.append("control run left no baseline file")

    # ---- phase 2: torn state + SIGKILL mid-generation ----
    chaos_dir = os.path.join(workdir, "chaos")
    chaos_rb = os.path.join(workdir, "chaos_baseline.json")
    killed = subprocess.run(
        _run_cmd(chaos_dir, chaos_rb, spec_path,
                 ("--chaos", "recert_kill_cycle,recert_torn_state",
                  "--crash-mode", "kill")),
        env=env, capture_output=True, text=True, timeout=600)
    if killed.returncode != -signal.SIGKILL:
        failures.append(
            f"chaos scheduler exited {killed.returncode}, expected SIGKILL "
            f"(-9); stderr tail: {killed.stderr[-800:]}")
    state_path = os.path.join(chaos_dir, "recert_state.json")
    try:
        json.load(open(state_path))
        failures.append("recert_torn_state left a parseable state file — "
                        "the torn-write path was not exercised")
    except (OSError, ValueError):
        pass  # torn, as injected
    if os.path.exists(chaos_rb):
        failures.append("SIGKILLed generation must not have touched the "
                        "baseline file (nothing was harvested)")

    # ---- phase 3: resume completes the SAME generation, bit-identical ----
    resume = subprocess.run(_run_cmd(chaos_dir, chaos_rb, spec_path),
                            env=env, capture_output=True, text=True,
                            timeout=1200)
    if resume.returncode != 0:
        failures.append(f"resume run exited {resume.returncode}; stderr "
                        f"tail: {resume.stderr[-800:]}")

    from dorpatch_tpu.recert.scheduler import RecertScheduler

    sched = RecertScheduler(chaos_dir, baseline_file=chaos_rb)
    st = sched.status()
    if st["generation"] != 1 or st["inflight"] is not None:
        failures.append("resume must finish generation 1, not start a new "
                        f"one: status={st}")
    chaos_bytes = (open(chaos_rb, "rb").read()
                   if os.path.exists(chaos_rb) else b"")
    if not chaos_bytes or chaos_bytes != control_bytes:
        failures.append(
            "crash-resumed baseline differs from the uninterrupted "
            f"control's ({len(chaos_bytes)} vs {len(control_bytes)} bytes)")
    verdict = st.get("verdict") or {}
    if verdict.get("status") != "ok":
        failures.append(f"resumed generation should verdict ok, got {verdict}")
    cells = len(json.loads(chaos_bytes or b"{}").get("entries", {}))
    if cells != 4:
        failures.append(f"expected 4 grid cells in the baseline, got {cells}")

    # ---- phase 4: planted regression -> check exits 1 naming the cell ----
    data = json.loads(chaos_bytes.decode("utf-8")) if chaos_bytes else {
        "entries": {}}
    planted = next(iter(sorted(data["entries"])), None)
    if planted is not None:
        # claim the defense used to do 30 points better than it measured:
        # the fresh measurement now reads as a regression past tolerance
        data["entries"][planted]["robust_accuracy"] += 30.0
        with open(chaos_rb, "w") as fh:
            json.dump(data, fh)
    check = subprocess.run(
        [sys.executable, "-m", "dorpatch_tpu.recert", "check", chaos_dir,
         "--baseline-file", chaos_rb],
        env=env, capture_output=True, text=True, timeout=600)
    if check.returncode != 1:
        failures.append(f"check with a planted regression exited "
                        f"{check.returncode}, expected 1; stderr tail: "
                        f"{check.stderr[-400:]}")
    if planted is None or "DP400" not in check.stdout \
            or planted not in check.stdout:
        failures.append("check finding must name DP400 and the regressed "
                        f"cell {planted!r}; stdout: {check.stdout[-400:]}")

    # ---- phase 5: serve boots against the failing verdict ----
    import numpy as np

    import jax
    import jax.numpy as jnp
    from dorpatch_tpu.config import DefenseConfig, RecertConfig, ServeConfig
    from dorpatch_tpu.recert.gate import RecertGateError
    from dorpatch_tpu.serve.http import HttpFrontend
    from dorpatch_tpu.serve.service import CertifiedInferenceService

    def stub_apply(params, x):
        s = x.mean(axis=(1, 2, 3))
        return jax.nn.one_hot((s * 7).astype(jnp.int32) % 5, 5)

    def make(require):
        return CertifiedInferenceService(
            stub_apply, None, num_classes=5, img_size=32,
            serve_cfg=ServeConfig(max_batch=2, bucket_sizes=(1, 2)),
            defense_cfg=DefenseConfig(ratios=(0.1,), chunk_size=64),
            recert_cfg=RecertConfig(dir=chaos_dir, require=require))

    strict_refused = False
    try:
        make("strict").start()
    except RecertGateError as e:
        strict_refused = True
        if "failing" not in str(e):
            failures.append(f"strict refusal should carry the verdict "
                            f"status: {e}")
    if not strict_refused:
        failures.append("--require-recert strict must refuse serving-ready "
                        "on a failing verdict (typed RecertGateError)")

    svc = make("warn").start()  # boots with the recompile watchdog armed
    frontend = HttpFrontend(svc, port=0).start()
    robustness_http = None
    try:
        resp = svc.predict(np.zeros((32, 32, 3), np.float32))
        if resp.status != "ok":
            failures.append(f"warn-mode service failed a predict: {resp}")
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{frontend.port}/robustness", timeout=30)
            failures.append("/robustness must answer 503 on a failing "
                            "verdict (canary-probe contract)")
        except urllib.error.HTTPError as e:
            robustness_http = e.code
            body = json.loads(e.read().decode("utf-8"))
            if e.code != 503 or body.get("status") != "failing":
                failures.append(f"/robustness: expected 503/failing, got "
                                f"{e.code}/{body.get('status')}")
            regressed = [k for k, c in (body.get("cells") or {}).items()
                         if c.get("status") == "regressed"]
            if planted not in regressed:
                failures.append("/robustness body must render the regressed "
                                f"cell {planted!r}; got {regressed}")
    finally:
        frontend.stop()
        svc.stop()

    print(json.dumps({
        "metric": "recert_smoke",
        "ok": not failures,
        "generation_s": round(control_s, 3),
        "cells": cells,
        "resume_generation": st.get("generation"),
        "baseline_bytes": len(control_bytes),
        "bit_identical": chaos_bytes == control_bytes,
        "check_rc": check.returncode,
        "strict_refused": strict_refused,
        "robustness_http": robustness_http,
        "failures": failures,
    }, default=float))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        print(f"recert dirs kept for debugging: {workdir}", file=sys.stderr)
        return 1
    shutil.rmtree(workdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
