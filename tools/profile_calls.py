#!/usr/bin/env python
"""Separate per-call dispatch overhead from per-step device compute.

Hypothesis from bench vs scan-profile discrepancy: calls with *fresh*
arguments pay a large constant per-call cost through the remote tunnel
(~20s), while repeat calls with identical args appear memoized. Threading
the state between calls defeats memoization, so:

  per_call(block_k) = overhead + k * step
  -> step = (per_call(block_8) - per_call(block_1)) / 7
  -> overhead = per_call(block_1) - step

Also times a trivial threaded jit (x <- x - 1e-6) and a threaded fwd+bwd to
see whether the overhead is attack-specific or universal.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import time

import jax
import jax.numpy as jnp

from dorpatch_tpu import losses
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import AttackConfig
from dorpatch_tpu.models import get_model


def main():
    b, s, img = 8, 32, 224
    print(f"devices: {jax.devices()}", flush=True)
    victim = get_model("imagenet", "resnetv2", img_size=img)

    key = jax.random.PRNGKey(0)  # noqa: DP104 — standalone profiling harness, fixed seed is deliberate

    # 1. trivial threaded jit
    xsmall = jax.random.uniform(key, (256, 256))
    triv = jax.jit(lambda a: a - 1e-6)  # noqa: DP105 — harness times compile itself
    xs = triv(xsmall)
    jax.block_until_ready(xs)
    t0 = time.perf_counter()
    for _ in range(10):
        xs = triv(xs)
    jax.block_until_ready(xs)
    print(f"trivial threaded jit: {(time.perf_counter()-t0)/10*1e3:.1f} ms/call",
          flush=True)

    # 2. threaded fwd+bwd on the EOT batch
    params16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
        victim.params)
    key, k_xb = jax.random.split(key)
    xb = jax.random.uniform(k_xb, (b * s, img, img, 3), jnp.bfloat16)

    @jax.jit  # noqa: DP105 — harness times compile itself
    def fb(x):
        g = jax.grad(lambda xx: victim.apply(params16, xx).astype(
            jnp.float32).mean())(x)
        return jnp.clip(x - 0.01 * jnp.sign(g), 0, 1)

    xb = fb(xb)
    jax.block_until_ready(xb)
    t0 = time.perf_counter()
    n = 4
    for _ in range(n):
        xb = fb(xb)
    jax.block_until_ready(xb)
    print(f"threaded fwd+bwd ({b*s} imgs): {(time.perf_counter()-t0)/n*1e3:.0f} ms/call",
          flush=True)

    # 3. attack blocks of 1 and 8 steps, threaded
    cfg = AttackConfig(sampling_size=s, compute_dtype="bfloat16")
    attack = DorPatch(victim.apply, victim.params, victim.num_classes, cfg,
                      remat=False)
    universe = jnp.asarray(
        masks_lib.dropout_universe(img, cfg.dropout, cfg.dropout_sizes))
    key, k_x = jax.random.split(key)
    x = jax.random.uniform(k_x, (b, img, img, 3))
    y = jnp.zeros((b,), jnp.int32)
    lv = jnp.mean(losses.local_variance(x)[0], axis=-1)
    state = attack._init_state(key, x, y, False, universe.shape[0])

    for k, reps in ((1, 4), (8, 2)):
        block = attack._get_block(1, img, k)
        t0 = time.perf_counter()
        state = block(state, x, lv, universe)
        jax.block_until_ready(state.adv_pattern)
        print(f"block{k} compile+first: {time.perf_counter()-t0:.1f}s", flush=True)
        t0 = time.perf_counter()
        for _ in range(reps):
            state = block(state, x, lv, universe)
        jax.block_until_ready(state.adv_pattern)
        per_call = (time.perf_counter() - t0) / reps
        print(f"block{k} threaded: {per_call:.2f} s/call", flush=True)


if __name__ == "__main__":
    main()
