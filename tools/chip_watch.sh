#!/bin/bash
# Wait for the axon tunnel (tpu_probe.sh exits 0 on TPU_UP), then run the
# resumable on-chip validation sequence — and RE-ARM on a circuit-breaker
# stop (rc=3: the tunnel died mid-sequence), so a flapping tunnel still
# completes all steps unattended. Completed steps are skipped on resume
# (CPU-fallback rows are not banked as completed — see
# chip_validation.is_on_chip_result). Any other exit code ends the watch.
cd "$(dirname "$0")/.."
LOG=artifacts/chip_validation_r05.log
while true; do
  bash tools/tpu_probe.sh || { echo "chip_watch: probe loop exited $?" >> "$LOG"; exit 1; }
  python tools/chip_validation.py >> "$LOG" 2>&1
  rc=$?
  echo "chip_watch: chip_validation exited rc=$rc" >> "$LOG"
  if [ "$rc" -ne 3 ]; then
    exit "$rc"
  fi
  echo "chip_watch: tunnel died mid-sequence; re-arming probe" >> "$LOG"
done
