#!/usr/bin/env python
"""Serve-side chaos smoke: supervised-pool failover end-to-end (CI gate,
`run_tests.sh`).

Three phases, one process, one throwaway AOT store, one stub victim:

A. CONTROL — a 1-replica unfaulted service (AOT mode "auto" against the
   empty store, so this pass also populates one entry per serving
   program) answers a seeded batch; its verdicts are the parity
   reference.
B. CHAOS — a 2-replica service boots strictly from the store (recompile
   watchdog ARMED, zero traces on every replica's bank) and serves the
   same batch under concurrent load while chaos wedges replica 0 mid-batch
   with requests in flight. Every admitted request must be answered ok
   exactly once (failover re-dispatch inside the original deadline —
   nothing lost, nothing double-answered) with verdicts bit-identical to
   phase A.
C. RECOVERY — the supervisor must classify the wedge, quarantine, and
   restart replica 0 through the AOT store: all hits, ZERO traces on the
   restarted bank under the still-armed watchdog. A second pass over the
   seeded batch must again match phase A, and the report CLI must render
   the `-- replicas --` lifecycle accounting.

Prints ONE JSON line: {"metric": "serve_chaos_smoke", "ok": true, ...};
exits non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.config import AotConfig, DefenseConfig, ServeConfig
    from dorpatch_tpu.observe import report as report_mod
    from dorpatch_tpu.serve.service import CertifiedInferenceService

    num_classes, img = 5, 32

    # fresh closure per service: jax.jit shares its trace cache across
    # wrappers of the same function object, so one shared apply_fn would
    # leak the control's traces into the chaos service's zero-trace books
    def make_apply():
        def apply_fn(params, x):
            s = x.mean(axis=(1, 2, 3))
            return jax.nn.one_hot((s * 7.0).astype(jnp.int32) % num_classes,
                                  num_classes)
        return apply_fn

    defense_cfg = DefenseConfig(ratios=(0.1,), chunk_size=64)
    rng = np.random.default_rng(0)
    images = rng.uniform(0.0, 1.0, (12, img, img, 3)).astype(np.float32)

    def drive(svc, deadline_ms=15000.0, concurrency=6):
        """Concurrent closed-loop pass over the seeded batch; every request
        must come back ok — a lost request surfaces here as a typed error
        or a deadline, never a hang (the client wait loop is bounded)."""
        out = [None] * len(images)
        nxt = {"i": 0}
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    i = nxt["i"]
                    if i >= len(images):
                        return
                    nxt["i"] = i + 1
                out[i] = svc.predict(images[i], deadline_ms=deadline_ms)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return out

    def verdicts(results):
        return [(r.prediction, r.certified, r.clean_prediction)
                for r in results]

    failures = []
    stats = {"metric": "serve_chaos_smoke"}
    store_dir = tempfile.mkdtemp(prefix="serve-chaos-store-")
    result_dir = tempfile.mkdtemp(prefix="serve-chaos-telemetry-")
    try:
        # ---- A: 1-replica unfaulted control (also populates the store) ----
        control = CertifiedInferenceService(
            make_apply(), None, num_classes, img,
            serve_cfg=ServeConfig(max_batch=4, bucket_sizes=(1, 2, 4),
                                  deadline_ms=15000.0, replicas=1),
            defense_cfg=defense_cfg,
            aot_cfg=AotConfig(cache_dir=store_dir, mode="auto"))
        control.start()
        n_programs = len(control.trace_entrypoints())
        ref = drive(control)
        bad = [r.status for r in ref if r.status != "ok"]
        control.stop()
        if bad:
            failures.append(f"control pass not all ok: {bad}")
            return _finish(stats, failures)  # no reference to compare against
        want = verdicts(ref)
        stats["programs"] = n_programs
        stats["control_completed"] = control.stats()["completed"]

        # ---- B: 2-replica strict warm boot + chaos under load ----
        svc = CertifiedInferenceService(
            make_apply(), None, num_classes, img,
            serve_cfg=ServeConfig(max_batch=4, bucket_sizes=(1, 2, 4),
                                  deadline_ms=15000.0, replicas=2,
                                  max_restarts=2, restart_backoff_base=0.2,
                                  restart_backoff_cap=1.0,
                                  replica_stale_s=0.6,
                                  chaos="wedge_dispatch"),
            defense_cfg=defense_cfg, result_dir=result_dir,
            aot_cfg=AotConfig(cache_dir=store_dir, mode="strict"))
        svc.start()  # AotBootError here IS a failure: strict miss
        boot_traces = [r["trace_counts"] for r in svc.stats()["replicas"]]
        stats["boot_trace_counts"] = boot_traces
        if any(t != 0 for t in boot_traces):
            failures.append(f"warm boot traced: per-replica {boot_traces}, "
                            f"expected all 0 (every program from the store)")

        got = drive(svc)
        statuses = [getattr(r, "status", "?") for r in got]
        if statuses != ["ok"] * len(images):
            failures.append(f"chaos pass lost/failed requests: {statuses}")
        elif verdicts(got) != want:
            failures.append("chaos-pass verdicts diverged from the "
                            "1-replica unfaulted control")
        st = svc.stats()
        stats["failover"] = st["failover"]
        stats["chaos_completed"] = st["completed"]
        if st["failover"]["redispatched"] < 1:
            failures.append("chaos never forced a failover re-dispatch — "
                            "the wedge did not land mid-batch")
        if st["completed"] != len(images):
            failures.append(
                f"completed={st['completed']} after {len(images)} requests "
                f"— a request was double-answered or lost")

        # ---- C: AOT-warm restart + post-recovery parity + report ----
        deadline = time.time() + 120.0
        snap = None
        while time.time() < deadline:
            snap = {r["replica"]: r for r in svc.stats()["replicas"]}
            if snap[0]["state"] == "healthy" and snap[0]["generation"] == 1:
                break
            time.sleep(0.25)
        stats["replica0"] = {k: snap[0][k] for k in
                            ("state", "generation", "restarts",
                             "trace_counts")} if snap else None
        if not snap or snap[0]["state"] != "healthy" \
                or snap[0]["generation"] != 1:
            failures.append(f"replica 0 never restarted: {snap}")
        elif snap[0]["trace_counts"] != 0:
            failures.append(
                f"restarted replica traced {snap[0]['trace_counts']} "
                f"program(s) — the AOT warm restart compiled instead of "
                f"loading under the armed watchdog")

        post = drive(svc)
        post_status = [getattr(r, "status", "?") for r in post]
        if post_status != ["ok"] * len(images):
            failures.append(f"post-recovery pass failed: {post_status}")
        elif verdicts(post) != want:
            failures.append("post-recovery verdicts diverged from control")
        total_traces = [r["trace_counts"] for r in svc.stats()["replicas"]]
        stats["final_trace_counts"] = total_traces
        if any(t != 0 for t in total_traces):
            failures.append(f"post-recovery traffic traced: {total_traces}")
        events = [e for e in _read_jsonl(
            os.path.join(result_dir, "events.jsonl"))]
        restart_evs = [e for e in events
                       if e.get("name") == "serve.replica.restart"]
        if not restart_evs or restart_evs[0].get("aot_hits") != n_programs:
            failures.append(
                f"restart event reports aot_hits="
                f"{restart_evs[0].get('aot_hits') if restart_evs else None},"
                f" expected {n_programs} (all programs from the store)")
        svc.stop()

        rendered = report_mod.format_report(report_mod.summarize(result_dir))
        if "-- replicas --" not in rendered:
            failures.append("report does not render the -- replicas -- "
                            "lifecycle section")
        if "1 restart(s)" not in rendered:
            failures.append("report replica section missing the restart "
                            "accounting")
    finally:
        shutil.rmtree(store_dir, ignore_errors=True)
        shutil.rmtree(result_dir, ignore_errors=True)

    return _finish(stats, failures)


def _read_jsonl(path):
    rows = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        continue
    except OSError:
        pass
    return rows


def _finish(stats, failures) -> int:
    stats["ok"] = not failures
    stats["failures"] = failures
    print(json.dumps(stats))
    return 0 if not failures else 1


if __name__ == "__main__":
    raise SystemExit(main())
