"""Configuration for the dorpatch-tpu framework.

One dataclass surfaces every knob of the reference pipeline, including the
constants that the reference hard-codes inside function bodies
(`/root/reference/attack.py:52-53,65,83,87-89`, `/root/reference/main.py:61,84`).
The config is also the persistence key for the results directory, mirroring the
reference's `generate_saving_path` contract (`/root/reference/utils.py:24-44`).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

# Single source of truth for the dropout/defense ratio schedule and the
# R-covering axis count (`/root/reference/attack.py:83`,
# `PatchCleanser.py:13`). These live HERE (not in masks.py, which
# re-exports them) so that importing the config layer — and with it the
# jax-free host-side processes, e.g. the fleet gateway — never drags in
# jax: masks.py depends on config, never the other way around.
DEFAULT_RATIOS: Tuple[float, ...] = (0.015, 0.03, 0.06, 0.12)
NUM_MASKS_PER_AXIS: int = 6

NUM_CLASSES = {"imagenet": 1000, "cifar10": 10, "cifar100": 100}


@dataclasses.dataclass(frozen=True)
class AttackConfig:
    """DorPatch optimizer hyper-parameters.

    Defaults replicate the reference (`/root/reference/attack.py:51-53` signature
    defaults plus in-body constants).
    """

    patch_budget: float = 0.12
    targeted: bool = False
    lr: float = 1e-2
    confidence: float = 1e-1
    clip_min: float = 0.0
    clip_max: float = 1.0
    max_iterations: int = 5000
    basic_unit: int = 7
    selection: str = "topk"
    dropout: int = 2               # 0: occlusion EOT off (identity mask), 1: single, 2: double masks
    sampling_size: int = 128       # EOT samples (occlusion masks) per step
    density: float = 1e-3          # density regularization coefficient
    structured: float = 1e-3       # structured (TV) loss coefficient
    eps: float = 4.0               # L2 budget for the patch delta
    mask_fill: float = 0.5         # occlusion gray fill (attack.py:206)
    dual: bool = False             # second independent occlusion layer per sample
    num_patch: int = -1            # bookkeeping only (results path), as in reference

    # In-body constants of the reference's generate():
    patience: int = 200                        # lr-decay patience (attack.py:65)
    coeff_group_lasso: float = 1e-5            # attack.py:87
    scale_up: float = 1.2                      # attack.py:88
    # scale_down = sqrt(scale_up**3), derived (attack.py:89)
    dropout_sizes: Tuple[float, ...] = DEFAULT_RATIOS  # attack.py:83
    success_threshold: float = 1e-1            # attack_success = loss_adv < 1e-1 (attack.py:255)
    switch_iteration: int = 500                # untargeted->targeted switch (attack.py:169)
    sweep_interval: int = 100                  # collect_failure cadence (attack.py:187)
    failure_sampling_start: int = 1000         # failure-biased sampling start (attack.py:193)
    lr_floor: float = 0.1 / 256.0              # lr clip floor (attack.py:307)
    lr_stop: float = 1e-3                      # all-lr early-stop threshold (attack.py:311)
    lr_decay: float = 0.1                      # patience decay factor (attack.py:306)
    loss_decay_margin: float = 1e-3            # improvement margin (attack.py:275)
    report_interval: int = 20                  # metrics cadence (attack.py:318)
    adapt_start: int = 200                     # stage-0 coeff adaptation start (attack.py:294)
    use_pallas: str = "auto"                   # fused mask-fill kernel: auto|on|off|interpret
    compute_dtype: str = "float32"             # EOT fwd+bwd precision: float32|bfloat16
                                               # (carry/losses stay float32 either way)
    remat: str = "auto"                        # rematerialize the EOT forward in the
                                               # backward: auto|on|off. "on" trades ~25%
                                               # step time for activation memory; "auto"
                                               # remats only when the masked batch
                                               # (images x sampling_size) exceeds
                                               # remat_threshold
    remat_threshold: int = 512                 # masked-batch size above which "auto" remats
                                               # (512 masked images @224 RN50 bf16 measured
                                               # to fit v5e HBM without remat — PERF.md)
    remat_policy: str = "full"                 # what the backward recomputes when remat is
                                               # active: "full" re-runs the whole forward
                                               # (stores only inputs; ~25-33% extra FLOPs);
                                               # "conv" saves conv outputs (tagged
                                               # `checkpoint_name("conv_out")` in StdConv)
                                               # and recomputes only the cheap normalize/
                                               # elementwise chains — activation memory ~=
                                               # the conv outputs (~19 MB/masked image for
                                               # RN50@224 bf16) for a few-percent tax;
                                               # "dots" saves matmul outputs (ViT/ResMLP)

    @property
    def scale_down(self) -> float:
        return float(self.scale_up ** 1.5)


@dataclasses.dataclass(frozen=True)
class DefenseConfig:
    """PatchCleanser double-masking defense (`/root/reference/main.py:61`)."""

    ratios: Tuple[float, ...] = DEFAULT_RATIOS
    n_patch: int = 1
    num_mask_per_axis: int = NUM_MASKS_PER_AXIS
    mask_fill: float = 0.5          # gray fill (PatchCleanser.py:100)
    chunk_size: int = 64            # certification sweep chunking (PatchCleanser.py:102)
    use_pallas: str = "auto"        # Pallas kernel tier (fused mask fill +
                                    # the engines' stem delta-conv and
                                    # masked-KV attention kernels):
                                    # auto|on|off|interpret. Meshed
                                    # certifiers pin the engine kernels off
                                    # (GSPMD path); mask fill keeps its own
                                    # shard_map kernel.
    prune: str = "exact"            # double-masking work scheduling:
                                    #  "off"       — the exhaustive 666-mask
                                    #    sweep in one program (parity oracle)
                                    #  "exact" (default) — two-phase pruning:
                                    #    first-round table, then only the
                                    #    second-round entries the verdict
                                    #    actually reads (minority rows for
                                    #    disagreeing images, the pair audit
                                    #    for unanimous ones). Verdicts are
                                    #    bit-identical to "off" by
                                    #    construction.
                                    #  "consensus" — like "exact" but
                                    #    first-round-unanimous images skip
                                    #    the O(M^2) pair audit (36 forwards
                                    #    total, ~18x); their certificate
                                    #    asserts round-1 consensus only and
                                    #    can exceed the exhaustive audit —
                                    #    opt-in, see README "Certification".
                                    # Runs on single-chip AND meshed
                                    # defenses: meshes plan phase-2
                                    # worklists shard-locally and dispatch
                                    # them as fixed [S * bucket] SPMD
                                    # waves (defense._schedule_mesh);
                                    # n_patch != 1 families downgrade to
                                    # "off" (one-time observe event).
    incremental: str = "auto"       # mask-aware incremental masked
                                    # forwards on the pruned certify path:
                                    #  "auto" (default) — per family:
                                    #    "token-exact" for ViT victims,
                                    #    "mixer-exact" for ResMLP victims
                                    #    (verdict contract preserved),
                                    #    "stem" for conv victims (exact by
                                    #    construction), "off" where no
                                    #    engine exists (stub apply_fns,
                                    #    n_patch!=1 certifiers,
                                    #    prune="off"). Meshed certifiers
                                    #    run it too, on the same
                                    #    shard-local schedule.
                                    #  "token" — token-pruned ViT forwards
                                    #    (clean KV cache + dirty-token
                                    #    recompute; per-mask cost scales
                                    #    with mask_tokens/T). Small bounded
                                    #    logit drift; verdict-level parity
                                    #    within the documented tolerance.
                                    #  "token-exact" — "token" plus
                                    #    escalation: any image whose read
                                    #    table entries sit within
                                    #    incremental_margin of the argmax
                                    #    boundary re-runs the exhaustive
                                    #    program, so VERDICTS stay
                                    #    bit-identical whenever drift stays
                                    #    below the margin.
                                    #  "mixer"/"mixer-exact" — the ResMLP
                                    #    twins of "token"/"token-exact":
                                    #    dirty-row tracking with the token
                                    #    mix's skinny [dirty, dirty] delta
                                    #    slice (models/resmlp.py), same
                                    #    margin/escalation contract.
                                    #  "stem" — conv families: the exact
                                    #    masked-stem fold for the 36-mask
                                    #    first round (ops/stem_fold.py).
                                    #  "off" — PR 5 behavior: full masked
                                    #    forwards for every scheduled entry.
    incremental_margin: float = 0.5 # "-exact" escalation threshold:
                                    # top-2 logit gap below which an
                                    # incremental entry is distrusted and
                                    # its image re-certified exhaustively.
                                    # Trained victims' measured drift sits
                                    # far below this; raise it toward inf
                                    # to force-escalate everything (the
                                    # parity-test configuration).
    compute_dtype: str = "float32"  # certify sweep precision:
                                    # float32|bfloat16. "bfloat16" builds
                                    # the bf16 program bank
                                    # (defense.*.bf16.*): params cast once
                                    # at family build, images cast at the
                                    # program boundary, preds/margins read
                                    # out in f32. Correctness rides the
                                    # margin-escalation contract — every
                                    # evaluated entry's top-2 margin is
                                    # tracked and any image within
                                    # incremental_margin of the argmax
                                    # boundary re-certifies through the
                                    # f32 exhaustive program (the same law
                                    # as "token-exact"), so bf16 never
                                    # weakens a verdict.


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Online certified-inference service (`dorpatch_tpu/serve/`).

    The micro-batcher admits requests into a bounded queue and flushes a
    batch when a full bucket's worth is pending (size trigger) or when the
    oldest request has spent `flush_fraction` of its latency budget
    (deadline trigger). Batches pad up to fixed bucket sizes
    (`data.batch_buckets(max_batch)`, e.g. 1/8/32) so the jitted
    defense/certify programs compile once per bucket at startup warmup and
    never retrace under live traffic — enforced via the PR 2 recompile
    watchdog budgets."""

    max_batch: int = 8              # largest micro-batch (top bucket size)
    bucket_sizes: Tuple[int, ...] = ()  # () = derive data.batch_buckets(max_batch)
    max_queue_depth: int = 64       # backpressure bound: submissions past
                                    # this many queued requests get a typed
                                    # Overloaded reject, never unbounded queueing
    deadline_ms: float = 2000.0     # default per-request latency budget
    flush_fraction: float = 0.5     # flush when this fraction of the oldest
                                    # queued request's budget is spent
    host: str = "127.0.0.1"
    port: int = 8700                # HTTP front-end bind port (0 = ephemeral)
    warmup: bool = True             # compile every bucket's programs at start
    # -- replica pool / failover (serve/pool.py) --
    replicas: int = 1               # worker loops sharing the queue, each
                                    # with its own jitted program bank
    max_restarts: int = 2           # quarantined-replica restarts before it
                                    # retires (0 = a failed replica is gone)
    restart_backoff_base: float = 0.5   # shared backoff.retry_delay knobs
    restart_backoff_cap: float = 30.0   # for replica restarts (seconds)
    replica_stale_s: float = 0.0    # missed-beat staleness threshold for
                                    # the supervisor; 0 = deadline_ms/1e3
    chaos: str = ""                 # serve-side fault injection (comma list
                                    # of dorpatch_tpu.chaos SERVE_FAULTS)


@dataclasses.dataclass(frozen=True)
class FarmConfig:
    """Attack-sweep farm knobs (`dorpatch_tpu/farm/`): how workers lease,
    retry, and (in chaos mode) sabotage jobs over a shared farm directory.

    `lease_ttl` is the reclaim latency after a worker dies: a lease is
    fresh while the owner's heartbeat file advanced within the TTL, so it
    must comfortably exceed `heartbeat_interval` AND the longest gap
    between block boundaries (lease renewal points) — a slow compile inside
    one block otherwise reads as a dead worker."""

    lease_ttl: float = 60.0         # heartbeat staleness after which a
                                    # worker's jobs are reclaimable
    heartbeat_interval: float = 1.0  # worker liveness beat cadence; beats
                                    # are the lease-expiry clock, so this
                                    # is deliberately faster than the
                                    # pipeline's telemetry default
    max_attempts: int = 3           # per-job cap across transient retries
                                    # and crash reclaims
    backoff_base: float = 2.0       # transient retry delay: base * 2^(n-1)
    backoff_cap: float = 300.0      # ... clipped here ...
    backoff_jitter: float = 0.25    # ... times (1 + jitter * u), u drawn
                                    # deterministically from the job id
    poll_interval: float = 1.0      # idle worker re-scan cadence
    chaos: str = ""                 # comma-joined fault injections for the
                                    # smoke/recovery tests ("" = off):
                                    # crash_block, ckpt_raise,
                                    # wedge_heartbeat, enospc_events


@dataclasses.dataclass(frozen=True)
class AotConfig:
    """AOT executable store (`dorpatch_tpu/aot/`): warm-boot serving from
    pre-compiled executables keyed by the baseline fingerprints.

    `mode` semantics:
      "off"    — (default) boot compiles in process, store untouched.
      "auto"   — load what hits; any miss (absent entry, fingerprint/
                 interface drift, topology change, corrupt blob) compiles
                 AND rewrites the store entry — never serves stale.
      "strict" — the deploy mode: any miss fails boot (`AotBootError`)
                 instead of compiling, so a fleet restart either comes up
                 warm or visibly refuses."""

    cache_dir: str = ""             # store directory ("" = AOT disabled)
    mode: str = "off"               # off|auto|strict


@dataclasses.dataclass(frozen=True)
class RecertConfig:
    """Continuous re-certification (`dorpatch_tpu/recert/`): the serve-boot
    robustness gate against the scheduler's published verdict.

    `require` semantics (mirrors AotConfig.mode):
      "off"    — (default) no gate; the snapshot is still loaded for
                 `GET /robustness` when `dir` is set.
      "warn"   — boot proceeds on any verdict (failing/stale/absent) and
                 carries the degraded status in `/robustness` + the boot
                 log — canary mode.
      "strict" — the deploy mode: the pool refuses serving-ready
                 (`RecertGateError`) unless the verdict exists and is
                 `ok` — never serve silently-uncertified."""

    dir: str = ""                   # recert dir holding recert_verdict.json
                                    # ("" = no robustness surface)
    baseline_file: str = ""         # baseline override ("" = the package's
                                    # recert/robustness_baseline.json)
    require: str = "off"            # off|warn|strict


@dataclasses.dataclass(frozen=True)
class GatewayConfig:
    """Horizontal serve fleet front-end (`dorpatch_tpu/gateway/`): a
    stdlib-only HTTP gateway routing `POST /predict` across N serve
    *processes* (each a `python -m dorpatch_tpu.serve`).

    Membership is probe-driven (`/healthz` + `/stats` + `/robustness` on a
    jittered interval): `fail_threshold` CONSECUTIVE probe failures eject a
    backend, `ok_threshold` consecutive successes re-admit it — the
    hysteresis that keeps a flapping backend out. Routing is
    power-of-two-choices over each backend's scraped occupancy/reject rate,
    retrying connection-level failures on the next backend (never
    re-dispatching a request the backend already answered). When every
    routable backend is saturated the gateway answers a typed `Overloaded`
    (503) instead of queueing."""

    backends: Tuple[str, ...] = ()  # backend base URLs (http://host:port)
    host: str = "127.0.0.1"
    port: int = 8800                # gateway bind port (0 = ephemeral)
    probe_interval_s: float = 1.0   # health-probe cadence per backend
    probe_jitter: float = 0.2       # multiplicative interval jitter (anti
                                    # thundering-herd across gateways)
    probe_timeout_s: float = 5.0    # per-probe socket timeout
    fail_threshold: int = 3         # consecutive probe failures -> ejected
    ok_threshold: int = 2           # consecutive probe successes -> healthy
                                    # (re-admission hysteresis)
    check_robustness: bool = True   # poll GET /robustness: a failing
                                    # verdict degrades (not ejects) the
                                    # backend — routable only when no
                                    # healthy backend remains
    inflight_cap: int = 32          # per-backend concurrent dispatches the
                                    # gateway allows before calling the
                                    # fleet saturated
    dispatch_retries: int = 1       # connection-failure retries, each on a
                                    # backend the request has not touched
    dispatch_timeout_s: float = 75.0  # per-dispatch socket timeout (never
                                    # retried: the backend may still answer)
    canary_steps: Tuple[float, ...] = (0.1, 0.5, 1.0)
                                    # rolling-deploy traffic fractions the
                                    # canary group is stepped through
    canary_hold_s: float = 2.0      # soak time per step before evaluating
                                    # the canary's robustness
    autoscale_window_s: float = 30.0   # sliding window for the signal-only
                                    # scale recommendations
    autoscale_high_occupancy: float = 0.8  # scale-up above this mean occupancy
    autoscale_low_occupancy: float = 0.2   # scale-down below (and no rejects)
    autoscale_high_reject: float = 0.01    # scale-up above this reject rate
    autoscale_cooldown_s: float = 60.0     # min gap between recommendations
    chaos: str = ""                 # gateway-side fault injection (comma
                                    # list of dorpatch_tpu.chaos
                                    # GATEWAY_FAULTS: wedge_probe,
                                    # poison_canary)


def config_to_dict(cfg: "ExperimentConfig") -> dict:
    """JSON-safe nested dict of the full experiment config (reproducibility
    record written beside summary.json by the pipelines)."""
    return dataclasses.asdict(cfg)


def config_from_dict(d: dict) -> "ExperimentConfig":
    """Inverse of `config_to_dict`. Unknown keys are rejected (a config
    written by a newer code version must not silently lose knobs); list
    values round-trip back to the tuples the dataclasses declare."""
    def build(cls, sub: dict):
        fields = {f.name: f for f in dataclasses.fields(cls)}
        unknown = set(sub) - set(fields)
        if unknown:
            raise ValueError(f"unknown {cls.__name__} keys: {sorted(unknown)}")
        kw = {}
        for k, v in sub.items():
            if isinstance(v, list):
                v = tuple(v)
            kw[k] = v
        return cls(**kw)

    d = dict(d)
    attack = build(AttackConfig, d.pop("attack", {}))
    defense = build(DefenseConfig, d.pop("defense", {}))
    serve = build(ServeConfig, d.pop("serve", {}))
    farm = build(FarmConfig, d.pop("farm", {}))
    aot = build(AotConfig, d.pop("aot", {}))
    recert = build(RecertConfig, d.pop("recert", {}))
    gateway = build(GatewayConfig, d.pop("gateway", {}))
    cfg = build(ExperimentConfig, d)
    return dataclasses.replace(cfg, attack=attack, defense=defense,
                               serve=serve, farm=farm, aot=aot,
                               recert=recert, gateway=gateway)


def resolved_data_source(cfg: "ExperimentConfig") -> str:
    """cfg.data_source with "auto" mapped through the synthetic_data flag.

    getattr default: configs pickled before the field existed (cached
    sweep/parity artifacts) resolve as "auto"."""
    source = getattr(cfg, "data_source", "auto")
    if source != "auto":
        if source not in ("disk", "synthetic", "procedural"):
            raise ValueError(f"unknown data_source {source!r}")
        return source
    return "synthetic" if cfg.synthetic_data else "disk"


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """End-to-end experiment: the reference's CLI surface (`/root/reference/main.py:8-41`)
    plus backend/mesh selection."""

    dataset: str = "imagenet"
    data_dir: str = "/home/data/data"
    model_dir: str = "pretrained_models/"
    base_arch: str = "resnetv2"
    attack_name: str = "DorPatch"
    batch_size: int = 1
    num_batches: int = 10           # hard cap of the reference driver (main.py:84)
    seed: int = 1234
    backend: str = "jax-tpu"        # {"torch", "jax-tpu"}
    device: str = "0"
    results_root: str = "results"
    synthetic_data: bool = False    # run without datasets on disk
    data_source: str = "auto"       # auto|disk|synthetic|procedural:
                                    # "procedural" = the learnable generated
                                    # task (data.procedural_arrays) with
                                    # genuine labels — the trained-victim
                                    # flagship's eval stream; "auto" maps
                                    # synthetic_data to synthetic/disk
    img_size: int = 224
    stream_depth: int = 2           # eval input streaming: background host
                                    # loader + double-buffered host->device
                                    # prefetch, this many batches ahead
                                    # (data.streaming_batches — the
                                    # production-224 input path). 0 =
                                    # synchronous in-loop loads.
    gn_impl: str = "auto"           # GroupNorm+ReLU impl for ResNetV2 victims
                                    # (models.resnetv2.GroupNormRelu): auto =
                                    # fused Pallas kernel on single-chip TPU,
                                    # flax elsewhere; force with flax|pallas

    # Mesh: data axis (images, DCN across slices) x mask axis (EOT samples, ICI).
    mesh_data: int = 1
    mesh_mask: int = 1

    # Observability (SURVEY.md §5): metrics_log is the master telemetry
    # switch — it gates the metrics JSONL *and* the run telemetry files
    # (events.jsonl spans, heartbeat_<proc>.jsonl) the offline report CLI
    # consumes (`observe/report.py`). run.json is always written (a results
    # dir must stay self-describing even with telemetry off).
    metrics_log: bool = True
    # Runtime sanitizers (analysis/sanitize.py): jax_debug_nans (fail at the
    # NaN-producing primitive), jax_log_compiles routed into observe events,
    # and the recompile-budget watchdog (each jitted entry point declares
    # its trace budget via timed_first_call; exceeding it fails the run).
    # Static rules (python -m dorpatch_tpu.analysis) catch what is provable
    # from source; this flag catches the rest live. Costs throughput —
    # debugging runs only.
    sanitize: bool = False
    trace_dir: str = ""
    heartbeat_interval: float = 5.0  # seconds between heartbeat beats
    hang_timeout: float = 0.0       # >0 arms the watchdog: abort (with every
                                    # process's last-known phase) instead of
                                    # hanging forever on a wedged collective.
                                    # Must exceed the longest single jitted
                                    # block INCLUDING its compile.

    # Mid-stage orbax checkpoints of the optimizer carry (crash recovery
    # finer than the reference's per-stage artifacts, SURVEY.md §5).
    carry_checkpoints: bool = False

    attack: AttackConfig = dataclasses.field(default_factory=AttackConfig)
    defense: DefenseConfig = dataclasses.field(default_factory=DefenseConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    farm: FarmConfig = dataclasses.field(default_factory=FarmConfig)
    aot: AotConfig = dataclasses.field(default_factory=AotConfig)
    recert: RecertConfig = dataclasses.field(default_factory=RecertConfig)
    gateway: GatewayConfig = dataclasses.field(default_factory=GatewayConfig)

    @property
    def num_classes(self) -> int:
        return NUM_CLASSES[self.dataset]
