"""Torch victim models with timm-compatible state_dicts (the parity oracle).

The reference loads its victims through `timm.create_model` + a PatchCleanser
checkpoint (`/root/reference/utils.py:47-63`). timm is not available in this
environment, so this module implements the same architectures natively in
torch with **state_dict keys matching timm**, which keeps the reference's
checkpoint files (`<model>_cutout2_128_<dataset>.pth`) loadable, and doubles
as the `--backend torch` oracle for numerical parity tests.

Implemented: resnetv2_50x1_bit_distilled (BiT ResNetV2-50x1). The timm
contract replicated here: StdConv2dSame with eps=1e-8 and dynamic TF SAME
padding; GroupNorm(32, eps=1e-5)+ReLU pre-activations; fixed stem
(ConstantPad2d(1,0) + VALID max-pool); preact projection shortcut; 1x1-conv
classifier head (`head.fc`).
"""

from __future__ import annotations

import math

import torch
import torch.nn as nn
import torch.nn.functional as F


def _same_pad(x: torch.Tensor, kernel: int, stride: int) -> torch.Tensor:
    """TF-style dynamic SAME padding (asymmetric: extra on the right/bottom)."""
    ih, iw = x.shape[-2:]
    pad_h = max((math.ceil(ih / stride) - 1) * stride + kernel - ih, 0)
    pad_w = max((math.ceil(iw / stride) - 1) * stride + kernel - iw, 0)
    return F.pad(x, (pad_w // 2, pad_w - pad_w // 2, pad_h // 2, pad_h - pad_h // 2))


class WSConv2d(nn.Conv2d):
    """Weight-standardized conv with SAME padding (timm StdConv2dSame)."""

    def __init__(self, in_ch, out_ch, kernel_size, stride=1, eps=1e-8):
        super().__init__(in_ch, out_ch, kernel_size, stride=stride, padding=0, bias=False)
        self.eps = eps

    def forward(self, x):
        w = self.weight
        mean = w.mean(dim=(1, 2, 3), keepdim=True)
        var = w.var(dim=(1, 2, 3), keepdim=True, unbiased=False)
        w = (w - mean) / torch.sqrt(var + self.eps)
        x = _same_pad(x, self.kernel_size[0], self.stride[0])
        return F.conv2d(x, w, None, self.stride)


class GNRelu(nn.GroupNorm):
    """GroupNorm+ReLU. Subclasses GroupNorm so state_dict keys are bare
    `<name>.weight` / `<name>.bias`, matching timm's GroupNormAct."""

    def __init__(self, channels, groups=32):
        super().__init__(groups, channels, eps=1e-5)

    def forward(self, x):
        return F.relu(super().forward(x))


class Bottleneck(nn.Module):
    def __init__(self, in_ch, out_ch, stride=1):
        super().__init__()
        mid = out_ch // 4
        self.norm1 = nn.GroupNorm(32, in_ch, eps=1e-5)
        self.conv1 = WSConv2d(in_ch, mid, 1)
        self.norm2 = nn.GroupNorm(32, mid, eps=1e-5)
        self.conv2 = WSConv2d(mid, mid, 3, stride)
        self.norm3 = nn.GroupNorm(32, mid, eps=1e-5)
        self.conv3 = WSConv2d(mid, out_ch, 1)
        if in_ch != out_ch or stride != 1:
            self.downsample = nn.Module()
            self.downsample.conv = WSConv2d(in_ch, out_ch, 1, stride)
        else:
            self.downsample = None

    def forward(self, x):
        pre = F.relu(self.norm1(x))
        shortcut = self.downsample.conv(pre) if self.downsample is not None else x
        y = self.conv1(pre)
        y = self.conv2(F.relu(self.norm2(y)))
        y = self.conv3(F.relu(self.norm3(y)))
        return y + shortcut


class ResNetV2Torch(nn.Module):
    """BiT ResNetV2, timm-compatible module tree / state_dict keys."""

    def __init__(self, num_classes=1000, layers=(3, 4, 6, 3), width=1):
        super().__init__()
        wf = width
        self.stem = nn.Module()
        self.stem.conv = WSConv2d(3, 64 * wf, 7, 2)

        self.stages = nn.ModuleList()
        in_ch, out_ch = 64 * wf, 256 * wf
        for si, depth in enumerate(layers):
            stage = nn.Module()
            blocks = nn.ModuleList()
            for bi in range(depth):
                stride = 2 if (bi == 0 and si > 0) else 1
                blocks.append(Bottleneck(in_ch, out_ch, stride))
                in_ch = out_ch
            stage.blocks = blocks
            self.stages.append(stage)
            out_ch *= 2

        self.norm = GNRelu(in_ch)
        self.head = nn.Module()
        self.head.fc = nn.Conv2d(in_ch, num_classes, 1, bias=True)

    def forward(self, x):
        x = self.stem.conv(x)
        x = F.max_pool2d(F.pad(x, (1, 1, 1, 1)), 3, 2)
        for stage in self.stages:
            for block in stage.blocks:
                x = block(x)
        x = self.norm(x)
        x = x.mean(dim=(2, 3), keepdim=True)
        x = self.head.fc(x)
        return x.flatten(1)


class ViTBlockTorch(nn.Module):
    def __init__(self, dim=768, heads=12, mlp_ratio=4):
        super().__init__()
        self.num_heads = heads
        self.norm1 = nn.LayerNorm(dim, eps=1e-6)
        self.attn = nn.Module()
        self.attn.qkv = nn.Linear(dim, dim * 3)
        self.attn.proj = nn.Linear(dim, dim)
        self.norm2 = nn.LayerNorm(dim, eps=1e-6)
        self.mlp = nn.Module()
        self.mlp.fc1 = nn.Linear(dim, dim * mlp_ratio)
        self.mlp.fc2 = nn.Linear(dim * mlp_ratio, dim)

    def forward(self, x):
        B, N, D = x.shape
        h = self.num_heads
        y = self.norm1(x)
        qkv = self.attn.qkv(y).reshape(B, N, 3, h, D // h).permute(2, 0, 3, 1, 4)
        q, k, v = qkv.unbind(0)
        att = (q @ k.transpose(-2, -1)) * (D // h) ** -0.5
        att = att.softmax(dim=-1)
        y = (att @ v).transpose(1, 2).reshape(B, N, D)
        x = x + self.attn.proj(y)
        y = self.mlp.fc2(F.gelu(self.mlp.fc1(self.norm2(x))))
        return x + y


class ViTTorch(nn.Module):
    """ViT-B/16, timm-compatible keys (cls_token, pos_embed, blocks.i.*)."""

    def __init__(self, num_classes=1000, dim=768, depth=12, heads=12, patch=16, img=224):
        super().__init__()
        self.patch_embed = nn.Module()
        self.patch_embed.proj = nn.Conv2d(3, dim, patch, patch)
        n_tokens = (img // patch) ** 2 + 1
        self.cls_token = nn.Parameter(torch.zeros(1, 1, dim))
        self.pos_embed = nn.Parameter(torch.randn(1, n_tokens, dim) * 0.02)
        self.blocks = nn.ModuleList([ViTBlockTorch(dim, heads) for _ in range(depth)])
        self.norm = nn.LayerNorm(dim, eps=1e-6)
        self.head = nn.Linear(dim, num_classes)

    def forward(self, x):
        B = x.shape[0]
        x = self.patch_embed.proj(x).flatten(2).transpose(1, 2)  # [B, 196, D]
        x = torch.cat([self.cls_token.expand(B, -1, -1), x], dim=1) + self.pos_embed
        for blk in self.blocks:
            x = blk(x)
        return self.head(self.norm(x)[:, 0])


class AffineTorch(nn.Module):
    """timm mlp_mixer `Affine`: alpha/beta stored [1, 1, D] (the exact
    shapes the PatchCleanser resmlp checkpoints carry — the converter
    flattens them to the flax [D] params)."""

    def __init__(self, dim):
        super().__init__()
        self.alpha = nn.Parameter(torch.ones(1, 1, dim))
        self.beta = nn.Parameter(torch.zeros(1, 1, dim))

    def forward(self, x):
        return self.alpha * x + self.beta


class ResMLPBlockTorch(nn.Module):
    def __init__(self, dim=384, seq_len=196, mlp_ratio=4, init_values=1e-5):
        super().__init__()
        self.norm1 = AffineTorch(dim)
        self.linear_tokens = nn.Linear(seq_len, seq_len)
        self.norm2 = AffineTorch(dim)
        self.mlp_channels = nn.Module()
        self.mlp_channels.fc1 = nn.Linear(dim, dim * mlp_ratio)
        self.mlp_channels.fc2 = nn.Linear(dim * mlp_ratio, dim)
        self.ls1 = nn.Parameter(init_values * torch.ones(dim))
        self.ls2 = nn.Parameter(init_values * torch.ones(dim))

    def forward(self, x):
        x = x + self.ls1 * self.linear_tokens(self.norm1(x).transpose(1, 2)).transpose(1, 2)
        y = self.mlp_channels.fc2(F.gelu(self.mlp_channels.fc1(self.norm2(x))))
        return x + self.ls2 * y


class ResMLPTorch(nn.Module):
    """ResMLP-24, timm mlp_mixer-compatible keys. NB the patch embed is
    named `stem` — timm's `MlpMixer` naming (unlike `VisionTransformer`'s
    `patch_embed`); r03 review caught the twin using the ViT name, which
    would have KeyError'd on a real checkpoint."""

    def __init__(self, num_classes=1000, dim=384, depth=24, patch=16, img=224):
        super().__init__()
        self.stem = nn.Module()
        self.stem.proj = nn.Conv2d(3, dim, patch, patch)
        seq_len = (img // patch) ** 2
        self.blocks = nn.ModuleList([ResMLPBlockTorch(dim, seq_len) for _ in range(depth)])
        self.norm = AffineTorch(dim)
        self.head = nn.Linear(dim, num_classes)

    def forward(self, x):
        x = self.stem.proj(x).flatten(2).transpose(1, 2)
        for blk in self.blocks:
            x = blk(x)
        return self.head(self.norm(x).mean(dim=1))


class CifarBasicBlockTorch(nn.Module):
    """Torch twin of `dorpatch_tpu.models.small.BasicBlock` (GroupNorm
    ResNet-18 block), for CPU-fallback benchmarking and small-model parity."""

    def __init__(self, in_ch, out_ch, stride=1):
        super().__init__()
        # eps=1e-6 matches the flax GroupNorm default used by the jax twin
        self.conv1 = nn.Conv2d(in_ch, out_ch, 3, stride, 1, bias=False)
        self.norm1 = nn.GroupNorm(8, out_ch, eps=1e-6)
        self.conv2 = nn.Conv2d(out_ch, out_ch, 3, 1, 1, bias=False)
        self.norm2 = nn.GroupNorm(8, out_ch, eps=1e-6)
        self.proj = None
        if in_ch != out_ch or stride != 1:
            self.proj = nn.Sequential(
                nn.Conv2d(in_ch, out_ch, 1, stride, bias=False),
                nn.GroupNorm(8, out_ch, eps=1e-6),
            )

    def forward(self, x):
        y = F.relu(self.norm1(self.conv1(x)))
        y = self.norm2(self.conv2(y))
        if self.proj is not None:
            x = self.proj(x)
        return F.relu(x + y)


class CifarResNet18Torch(nn.Module):
    """Torch twin of `dorpatch_tpu.models.small.CifarResNet18`."""

    def __init__(self, num_classes=10, stage_sizes=(2, 2, 2, 2)):
        super().__init__()
        self.stem = nn.Conv2d(3, 64, 3, 1, 1, bias=False)
        self.stem_norm = nn.GroupNorm(8, 64, eps=1e-6)
        blocks = []
        in_ch, features = 64, 64
        for si, depth in enumerate(stage_sizes):
            for bi in range(depth):
                stride = 2 if (bi == 0 and si > 0) else 1
                blocks.append(CifarBasicBlockTorch(in_ch, features, stride))
                in_ch = features
            features *= 2
        self.blocks = nn.Sequential(*blocks)
        self.head = nn.Linear(in_ch, num_classes)

    def forward(self, x):
        x = F.relu(self.stem_norm(self.stem(x)))
        x = self.blocks(x)
        return self.head(x.mean(dim=(2, 3)))


class Normalized(nn.Module):
    """[0,1]-input wrapper: normalize with mean/std 0.5 then run the net
    (reference `NormModel` + `get_normalize`, `/root/reference/utils.py:66-78`)."""

    def __init__(self, net):
        super().__init__()
        self.net = net

    def forward(self, x):
        return self.net((x - 0.5) / 0.5)


def create_torch_model(arch: str, num_classes: int) -> nn.Module:
    """Factory matching the reference's substring-based arch selection
    (`/root/reference/utils.py:51-58`)."""
    if arch in ("resnetv2", "resnetv2_50x1_bit_distilled"):
        return ResNetV2Torch(num_classes=num_classes)
    if arch in ("vit", "vit_base_patch16_224"):
        return ViTTorch(num_classes=num_classes)
    if arch in ("resmlp", "resmlp_24_distilled_224"):
        return ResMLPTorch(num_classes=num_classes)
    if arch in ("resnet18", "cifar_resnet18"):
        return CifarResNet18Torch(num_classes=num_classes)
    if arch == "cifar_vit":
        from dorpatch_tpu.models.vit import CIFAR_VIT

        return ViTTorch(num_classes=num_classes, dim=CIFAR_VIT["dim"],
                        depth=CIFAR_VIT["depth"], heads=CIFAR_VIT["num_heads"],
                        patch=CIFAR_VIT["patch_size"],
                        img=CIFAR_VIT["img_size"][0])
    raise NotImplementedError(f"torch backend arch: {arch}")
