"""The torch oracle backend: DorPatch attack + PatchCleanser defense in torch.

This is the executable stand-in for the reference pipeline
(`/root/reference/attack.py:51-406`, `/root/reference/defenses/PatchCleanser.py:62-118`)
— the `--backend torch` path that BASELINE.json's acceptance criterion
(certified-ASR parity of the jax backend vs the torch oracle on fixed
seeds/images) measures against. It is written to the same semantics as the
jax attack in `dorpatch_tpu.attack` — including that module's documented
deliberate repairs of the reference's latent bugs (true batched semantics,
per-image targeted flags, block-boundary sweeps/switch) — so the two
backends are comparable step-for-step, not just end-to-end.

Everything host-side is plain torch/numpy (the reference's style); the mask
geometry comes from `dorpatch_tpu.masks` (shared single source of truth) and
the double-masking verdict is evaluated with the shared
`defense.double_masking_verdict` decision logic so any backend difference is
isolated to model/attack numerics.

Layout: torch-native NCHW. Images `[B,3,H,W]`, patch masks `[B,1,H,W]`.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, NamedTuple, Optional, Sequence

import numpy as np
import torch
import torch.nn.functional as F

from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu.config import AttackConfig, DefenseConfig


# --------------------------------------------------------------- losses

def cw_margin(logits, labels, targeted, confidence: float = 0.0):
    """CW margin loss (`/root/reference/attack.py:10-23`), per-sample flag.

    logits `[N,C]`, labels `[N]`, targeted `[N]` bool. Same -1e4 label-slot
    masking as the jax twin (`losses.cw_margin_switchable`).
    """
    onehot = F.one_hot(labels, logits.shape[-1]).to(logits.dtype)
    real = (logits * onehot).sum(-1)
    other = ((1.0 - onehot) * logits - onehot * 1e4).amax(-1)
    margin = torch.where(targeted, other - real, real - other)
    return torch.clamp(confidence + margin, min=0.0)


def local_variance(x):
    """Directional absolute differences with one-sided gradients
    (`/root/reference/attack.py:33-39`; jax twin `losses.local_variance`):
    gradients reach only the shifted operand. x `[B,C,H,W]`."""
    sg = x.detach()
    diff_lr = (sg[..., :-1] - x[..., 1:]).abs()
    grad_lr = torch.cat([diff_lr, sg[..., -1:]], dim=-1)
    diff_ud = (sg[..., :-1, :] - x[..., 1:, :]).abs()
    grad_ud = torch.cat([diff_ud, sg[..., -1:, :]], dim=-2)
    return grad_lr + grad_ud, grad_lr, grad_ud


def min_var_weighted_variance(x):
    """TV weighted by the smaller directional gradient (`attack.py:41-45`)."""
    lv, grad_lr, grad_ud = local_variance(x)
    return lv * torch.where(grad_lr > grad_ud, grad_ud, grad_lr)


def structural_loss(adv_x, local_var_x):
    """Per-image structural loss (`attack.py:227-228`): channel-mean weighted
    TV normalized by the clean image's local variance. Returns `[B]`."""
    mv = min_var_weighted_variance(adv_x).mean(dim=1)  # [B,H,W]
    return (mv / (local_var_x + 1e-5)).mean(dim=(1, 2))


def window_sum(x, window: int):
    """Non-overlapping window sums `[B,1,H,W] -> [B,1,H/w,W/w]` (the
    reference's all-ones stride-w convs, `attack.py:72-80`)."""
    return F.avg_pool2d(x, window, window) * (window * window)


def group_lasso(adv_mask, basic_unit: int):
    g = window_sum(adv_mask**2, basic_unit)
    return basic_unit * g.sqrt().sum(dim=(1, 2, 3))


def density_loss(adv_mask, window: int):
    cells = window_sum(adv_mask, window)
    return cells.flatten(1).var(dim=1, unbiased=True)


def l2_project(mask, pattern, x, eps: float):
    """Soft L2 projection with detached norm (`/root/reference/utils.py:105-110`)."""
    delta = mask * (pattern - x)
    norm = delta.detach().flatten(1).norm(dim=1)
    scale = torch.clamp(eps / norm, max=1.0)
    return delta * scale[:, None, None, None]


def majority_incorrect_label(preds, y, num_classes: int):
    """Per-image mode of misclassified predictions (`attack.py:106-122`);
    images with no misclassified sample keep their label and report False
    (same repair as `attack.majority_incorrect_label`)."""
    incorrect = preds != y[:, None]
    counts = (F.one_hot(preds, num_classes) * incorrect[..., None]).sum(dim=1)
    has_any = incorrect.any(dim=1)
    mode = counts.argmax(dim=-1).to(y.dtype)  # smallest label on ties
    return torch.where(has_any, mode, y), has_any


def patch_selection(mask, patch_budget: float, basic_unit: int = 7):
    """Importance map -> hard top-k patch mask (`attack.py:363-382`);
    mirrors `attack.patch_selection`. mask `[B,1,H,W]` -> binary `[B,1,H,W]`."""
    b, _, h, w = mask.shape
    cells = window_sum(mask, basic_unit)[:, 0]  # [B,h',w']
    hp, wp = cells.shape[1:]
    flat = cells.reshape(b, -1)
    k = int(np.floor(h * w * patch_budget / basic_unit**2))
    vals, idxs = flat.topk(k, dim=1)
    sel = torch.zeros_like(flat)
    sel.scatter_(1, idxs, (vals > 0).to(mask.dtype))
    sel = sel.reshape(b, hp, wp)
    sel = sel.repeat_interleave(basic_unit, dim=1).repeat_interleave(basic_unit, dim=2)
    out = torch.zeros((b, h, w), dtype=mask.dtype)
    out[:, : sel.shape[1], : sel.shape[2]] = sel
    return out[:, None]


# ----------------------------------------------------- mask application

def rects_to_masks(rects: np.ndarray, img_size: int) -> torch.Tensor:
    """Rasterize rectangle sets `[N,K,4]` -> bool keep-masks `[N,H,W]`
    (True = kept; the convention of `masks.rasterize`, here in pure numpy:
    the torch backend must not execute jax ops — in production environments
    that would initialize, and claim, the accelerator backend)."""
    rects = np.asarray(rects, np.int32)
    rows = np.arange(img_size, dtype=np.int32)[:, None]
    cols = np.arange(img_size, dtype=np.int32)[None, :]
    r0 = rects[..., 0][..., None, None]
    r1 = rects[..., 1][..., None, None]
    c0 = rects[..., 2][..., None, None]
    c1 = rects[..., 3][..., None, None]
    occluded = (rows >= r0) & (rows < r1) & (cols >= c0) & (cols < c1)
    return torch.from_numpy(~occluded.any(axis=-3))


def apply_masks(imgs: torch.Tensor, keep: torch.Tensor, fill: float) -> torch.Tensor:
    """`[B,3,H,W] x [S,H,W] -> [B*S,3,H,W]` gray-filled occlusions
    (`attack.py:206`, `PatchCleanser.py:99-100`)."""
    m = keep[None, :, None].to(imgs.dtype)  # [1,S,1,H,W]
    out = imgs[:, None] * m + fill * (1.0 - m)
    return out.reshape((-1,) + imgs.shape[1:])


def masked_predictions(
    model, imgs: torch.Tensor, rects: np.ndarray, chunk_size: int, fill: float
) -> torch.Tensor:
    """Predictions under every mask: `[B,3,H,W] x [N,K,4] -> [B,N]` int64.
    Chunked like the reference's sweeps (`PatchCleanser.py:102-112`,
    `attack.py:384-406`)."""
    img_size = imgs.shape[-1]
    preds = []
    with torch.no_grad():
        for lo in range(0, rects.shape[0], chunk_size):
            keep = rects_to_masks(rects[lo: lo + chunk_size], img_size)
            logits = model(apply_masks(imgs, keep, fill))
            preds.append(logits.argmax(-1).reshape(imgs.shape[0], -1))
    return torch.cat(preds, dim=1)


# ------------------------------------------------------------- defense

class TorchPatchCleanser:
    """PatchCleanser double-masking certification on the torch model.

    Computes the [M]/[C(M,2)] prediction tables with the torch model, then
    hands them to the shared `defense.double_masking_verdict` (pure jnp on
    CPU) so the decision logic is byte-identical across backends."""

    def __init__(self, model, spec: masks_lib.MaskSpec, config: DefenseConfig):
        self.model = model
        self.spec = spec
        self.config = config
        singles, doubles = masks_lib.mask_sets(spec)
        self._num_singles = singles.shape[0]
        k = max(singles.shape[1], doubles.shape[1])
        self._rects = np.concatenate(
            [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)], axis=0
        )
        self.result = None

    def robust_predict(self, imgs: torch.Tensor, num_classes: int) -> List:
        from dorpatch_tpu.defense import (
            PatchCleanserRecord, double_masking_verdict_np)

        preds = masked_predictions(
            self.model, imgs, self._rects, self.config.chunk_size,
            self.config.mask_fill,
        ).numpy()
        p1 = preds[:, : self._num_singles]
        p2 = preds[:, self._num_singles:]
        pred, certified = double_masking_verdict_np(
            p1, p2, self._num_singles, num_classes)
        return [
            PatchCleanserRecord(int(pred[b]), bool(certified[b]), p1[b], p2[b])
            for b in range(imgs.shape[0])
        ]

    def collect(self, records: Sequence):
        from dorpatch_tpu.defense import PatchCleanserResult

        self.result = PatchCleanserResult(records)


def build_torch_defenses(model, img_size: int, config: DefenseConfig):
    """The 4-radius defense bank (`/root/reference/main.py:61`)."""
    return [
        TorchPatchCleanser(
            model,
            masks_lib.geometry(img_size, r, config.n_patch, config.num_mask_per_axis),
            config,
        )
        for r in config.ratios
    ]


# -------------------------------------------------------------- attack

class TorchAttackResult(NamedTuple):
    adv_mask: torch.Tensor     # [B,1,H,W]
    adv_pattern: torch.Tensor  # [B,3,H,W]
    y: np.ndarray              # [B] final labels (targets if switched)
    targeted: np.ndarray       # [B] bool per-image mode after switching
    stage0_mask: torch.Tensor
    stage0_pattern: torch.Tensor


class _State:
    """Host-side adaptive state — the torch analog of `attack.TrainState`."""

    def __init__(self, cfg: AttackConfig, b: int, universe_size: int,
                 y: torch.Tensor, targeted: torch.Tensor):
        self.lr = np.full((b,), cfg.lr)
        self.not_decay = np.zeros((b,), np.int64)
        self.loss_best = np.full((b,), np.inf)
        self.num_failure = universe_size + 1
        self.failed = np.zeros((universe_size,), bool)
        self.coeff_gl = float(cfg.coeff_group_lasso)
        self.coeff_struct = float(cfg.structured)
        self.y = y.clone()
        self.targeted = targeted.clone()
        self.best_mask = None
        self.best_pattern = None
        self.last_preds = None
        self.stopped = False
        self.step = 0


@dataclasses.dataclass
class TorchDorPatch:
    """Two-stage DorPatch attack driving a torch model — the oracle twin of
    `dorpatch_tpu.attack.DorPatch` (same config, same block/sweep/switch
    structure, same repairs)."""

    model: Callable[[torch.Tensor], torch.Tensor]
    num_classes: int
    config: AttackConfig = dataclasses.field(default_factory=AttackConfig)

    def _sample_indices(self, rng: np.random.Generator, failed: np.ndarray,
                        step: int):
        """Failure-biased EOT sampling (`attack.py:192-204`): up to half from
        the failure set after `failure_sampling_start`, the rest uniform from
        the universe, each draw without replacement."""
        cfg = self.config
        n_mask = failed.shape[0]
        s = min(cfg.sampling_size, n_mask)
        half = s // 2
        fail_ids = np.flatnonzero(failed)
        n_from_fail = (
            min(len(fail_ids), half) if step >= cfg.failure_sampling_start else 0
        )
        from_fail = np.zeros((s,), bool)
        idx = np.empty((s,), np.int64)
        if n_from_fail:
            idx[:n_from_fail] = rng.choice(fail_ids, n_from_fail, replace=False)
            from_fail[:n_from_fail] = True
        idx[n_from_fail:] = rng.choice(n_mask, s - n_from_fail, replace=False)
        return idx, from_fail

    def _loss(self, adv_mask, adv_pattern, x, local_var_x, keep, state, stage):
        cfg = self.config
        b = x.shape[0]
        s = keep.shape[0]
        delta = l2_project(adv_mask, adv_pattern, x, cfg.eps)
        adv_x = x + delta
        logits = self.model(apply_masks(adv_x, keep, cfg.mask_fill))
        y_rep = state.y.repeat_interleave(s)
        targeted_rep = state.targeted.repeat_interleave(s)
        loss_adv = cw_margin(logits, y_rep, targeted_rep, cfg.confidence).reshape(b, s)

        loss_struc = structural_loss(adv_x, local_var_x)
        loss = loss_adv.mean(dim=1)
        if cfg.structured != 0:
            loss = loss + state.coeff_struct * loss_struc
        gl = torch.zeros(b)
        dens = torch.zeros(b)
        if stage == 0:
            dens = density_loss(adv_mask, x.shape[-1] // 8)
            if cfg.density != 0:
                loss = loss + cfg.density * dens
            gl = group_lasso(adv_mask, cfg.basic_unit)
            loss = loss + state.coeff_gl * gl
        preds = logits.argmax(-1).reshape(b, s)
        return loss.sum(), dict(
            loss_adv=loss_adv.detach(), loss_struc=loss_struc.detach(),
            group_lasso=gl.detach(), preds=preds,
        )

    def _step(self, state: _State, adv_mask, adv_pattern, x, local_var_x,
              universe: np.ndarray, stage: int, rng: np.random.Generator,
              idx: Optional[np.ndarray] = None,
              from_fail: Optional[np.ndarray] = None,
              idx2: Optional[np.ndarray] = None):
        """One optimization step; returns updated (adv_mask, adv_pattern).
        `idx`/`from_fail`/`idx2` may be injected (tests drive both backends
        with the same EOT sample). Bookkeeping order matches
        `attack.DorPatch._step`."""
        cfg = self.config
        if idx is None:
            idx, from_fail = self._sample_indices(rng, state.failed, state.step)
        rects = universe[idx]
        if cfg.dual:
            # second independent occlusion layer (`/root/reference/
            # attack.py:208-218`), mirroring the jax twin: the union of both
            # rectangle sets as extra rows on the K axis; failure-set surgery
            # stays keyed on the first draw only
            if idx2 is None:
                idx2, _ = self._sample_indices(rng, state.failed, state.step)
            rects = np.concatenate([rects, universe[idx2]], axis=1)
        keep = rects_to_masks(rects, x.shape[-1])

        adv_mask = adv_mask.detach().requires_grad_(stage == 0)
        adv_pattern = adv_pattern.detach().requires_grad_(True)
        total, aux = self._loss(
            adv_mask, adv_pattern, x, local_var_x, keep, state, stage)
        total.backward()

        loss_adv = aux["loss_adv"].numpy()
        success_bs = loss_adv < cfg.success_threshold      # [B,S]
        mask_success = success_bs.all(axis=0)              # [S]

        # failure-set surgery (`attack.py:259-267`)
        state.failed[idx[from_fail & mask_success]] = False
        state.failed[idx[(~from_fail) & (~mask_success)]] = True
        n_failed = int(state.failed.sum())

        attack_success = bool(success_bs.all())
        certifiable = n_failed == 0

        loss_target = (aux["group_lasso"] if stage == 0 else aux["loss_struc"]).numpy()
        if n_failed < state.num_failure:
            state.loss_best = np.full_like(state.loss_best, np.inf)
        certify_better = n_failed <= state.num_failure
        loss_decay = certify_better & (
            (loss_target - state.loss_best) < -cfg.loss_decay_margin)

        if loss_decay.any():
            state.num_failure = n_failed
        state.loss_best = np.where(loss_decay, loss_target, state.loss_best)
        sel = torch.from_numpy(loss_decay)[:, None, None, None]
        if stage == 0:
            state.best_mask = torch.where(sel, adv_mask.detach(), state.best_mask)
        state.best_pattern = torch.where(sel, adv_pattern.detach(), state.best_pattern)
        state.not_decay = np.where(loss_decay, 0, state.not_decay + 1)

        # adaptive coefficients (`attack.py:294-303`)
        grow = attack_success and certifiable
        factor = cfg.scale_up if grow else 1.0 / cfg.scale_down
        if stage == 0 and state.step > cfg.adapt_start:
            state.coeff_gl *= factor
        else:
            state.coeff_struct *= factor

        # patience lr decay + early stop (`attack.py:292,305-316`); like the
        # reference, the stopping step applies no update
        early = state.not_decay > cfg.patience
        state.lr = np.where(early, state.lr * cfg.lr_decay, state.lr)
        state.lr = np.maximum(state.lr, cfg.lr_floor)
        state.not_decay = np.where(early, 0, state.not_decay)
        state.last_preds = aux["preds"]
        state.step += 1
        if bool((state.lr < cfg.lr_stop).all()):
            state.stopped = True
            return adv_mask.detach(), adv_pattern.detach()

        lr_b = torch.from_numpy(state.lr).float()[:, None, None, None]
        new_pattern = (adv_pattern.detach() - lr_b * adv_pattern.grad.sign()).clamp(
            cfg.clip_min, cfg.clip_max)
        if stage == 0:
            new_mask = (adv_mask.detach() - lr_b * adv_mask.grad.sign()).clamp(
                cfg.clip_min, cfg.clip_max)
        else:
            new_mask = adv_mask.detach()
        return new_mask, new_pattern

    def sweep_failures(self, adv_mask, adv_pattern, x, state: _State,
                       universe: np.ndarray) -> np.ndarray:
        """Full-universe failure sweep (`attack.py:384-406`)."""
        cfg = self.config
        with torch.no_grad():
            delta = l2_project(adv_mask, adv_pattern, x, cfg.eps)
            preds = masked_predictions(
                self.model, x + delta, universe,
                min(cfg.sampling_size, universe.shape[0]), cfg.mask_fill,
            ).numpy()
        hit = preds == state.y.numpy()[:, None]
        fail = np.where(state.targeted.numpy()[:, None], ~hit, hit)
        return fail.any(axis=0)

    def _run_stage(self, stage: int, state: _State, adv_mask, adv_pattern,
                   x, local_var_x, universe, rng):
        """Block/sweep/switch structure mirroring `attack.DorPatch._run_stage`:
        full sweep at every `sweep_interval` boundary, untargeted->targeted
        switch at the first boundary past `switch_iteration`."""
        cfg = self.config
        interval = cfg.sweep_interval
        total = cfg.max_iterations
        i = 0
        while i < total:
            state.failed = self.sweep_failures(
                adv_mask, adv_pattern, x, state, universe)
            n_steps = min(interval, total - i)
            for _ in range(n_steps):
                adv_mask, adv_pattern = self._step(
                    state, adv_mask, adv_pattern, x, local_var_x, universe,
                    stage, rng)
                if state.stopped:
                    break
            i += n_steps
            if (
                stage == 0
                and i >= cfg.switch_iteration
                and i - n_steps < cfg.switch_iteration
                and not bool(state.targeted.all())
            ):
                y_new, has_target = majority_incorrect_label(
                    state.last_preds, state.y, self.num_classes)
                switch = has_target & (~state.targeted)
                state.targeted = state.targeted | switch
                state.y = torch.where(switch, y_new, state.y)
                state.lr = np.full_like(state.lr, cfg.lr)
                state.loss_best = np.full_like(state.loss_best, np.inf)
                state.not_decay = np.zeros_like(state.not_decay)
                state.num_failure = universe.shape[0] + 1
            if state.stopped:
                break
        return adv_mask, adv_pattern

    def _finalize_best(self, state: _State, adv_mask, adv_pattern):
        never = torch.from_numpy(np.isinf(state.loss_best))[:, None, None, None]
        best_mask = torch.where(never, adv_mask, state.best_mask)
        best_pattern = torch.where(never, adv_pattern, state.best_pattern)
        return best_mask, best_pattern

    def generate(
        self,
        x: torch.Tensor,
        y: Optional[torch.Tensor] = None,
        targeted: bool = False,
        seed: int = 0,
        store=None,
        batch_id: int = 0,
    ) -> TorchAttackResult:
        """Run the full two-stage attack (`/root/reference/attack.py:51-361`);
        same store contract as the jax `DorPatch.generate`."""
        cfg = self.config
        b = x.shape[0]
        img_size = x.shape[-1]
        universe = masks_lib.dropout_universe(
            img_size, cfg.dropout, cfg.dropout_sizes)
        rng = np.random.default_rng(seed)
        gen = torch.Generator().manual_seed(seed)
        with torch.no_grad():
            if y is None:
                y = self.model(x).argmax(-1)
            local_var_x = local_variance(x)[0].mean(dim=1)

        targeted_vec = torch.full((b,), bool(targeted), dtype=torch.bool)
        y = y.to(torch.int64)

        def fresh_state():
            st = _State(cfg, b, universe.shape[0], y, targeted_vec)
            st.best_mask = torch.zeros((b, 1, img_size, img_size))
            st.best_pattern = torch.zeros((b, 3, img_size, img_size))
            return st

        # ---- stage 0: importance map (shared-parent-dir resumable) ----
        cached = store.load_stage0(batch_id) if store is not None else None
        if cached is not None:
            stage0_mask = torch.from_numpy(
                np.moveaxis(np.asarray(cached[0]), -1, 1).copy())
            stage0_pattern = torch.from_numpy(
                np.moveaxis(np.asarray(cached[1]), -1, 1).copy())
            state = fresh_state()
            coeff_struct_carry = float(cfg.structured)
        else:
            state = fresh_state()
            adv_mask = torch.rand((b, 1, img_size, img_size), generator=gen)
            adv_pattern = torch.rand((b, 3, img_size, img_size), generator=gen)
            adv_mask, adv_pattern = self._run_stage(
                0, state, adv_mask, adv_pattern, x, local_var_x, universe, rng)
            stage0_mask, stage0_pattern = self._finalize_best(
                state, adv_mask, adv_pattern)
            coeff_struct_carry = state.coeff_struct
            if store is not None:
                store.save_stage0(
                    batch_id,
                    np.moveaxis(stage0_mask.numpy(), 1, -1),
                    np.moveaxis(stage0_pattern.numpy(), 1, -1),
                )

        # ---- stage 1: pattern refinement on the frozen hard mask ----
        with torch.no_grad():
            delta = l2_project(stage0_mask, stage0_pattern, x, cfg.eps)
            adv_x = x + delta
            preds = self.model(adv_x).argmax(-1)
        targeted_vec = state.targeted.clone()
        newly = (~targeted_vec) & (preds != state.y)
        y_cur = torch.where(newly, preds, state.y)
        targeted_vec = targeted_vec | newly

        hard_mask = patch_selection(stage0_mask, cfg.patch_budget, cfg.basic_unit)
        state1 = _State(cfg, b, universe.shape[0], y_cur, targeted_vec)
        state1.best_mask = hard_mask.clone()
        state1.best_pattern = torch.zeros_like(adv_x)
        state1.coeff_struct = coeff_struct_carry
        adv_mask, adv_pattern = self._run_stage(
            1, state1, hard_mask, adv_x.clone(), x, local_var_x, universe, rng)
        best_mask, best_pattern = self._finalize_best(state1, adv_mask, adv_pattern)

        return TorchAttackResult(
            adv_mask=best_mask,
            adv_pattern=best_pattern,
            y=state1.y.numpy(),
            targeted=state1.targeted.numpy(),
            stage0_mask=stage0_mask,
            stage0_pattern=stage0_pattern,
        )
