"""Non-JAX backends: the torch oracle path (`--backend torch`)."""
