"""The `--backend torch` experiment driver: the reference pipeline
(`/root/reference/main.py:44-188`) executed with the torch oracle models.

Shares everything shareable with the jax pipeline — `ArtifactStore` (so
torch- and jax-produced artifacts interchange on disk), `data` batches,
`metrics`, mask geometry, and record types — and never executes a jax op
(in production environments any jnp op initializes, and claims, the
accelerator backend; the torch oracle must be runnable alongside it).
"""

from __future__ import annotations

import random
import time
from typing import Dict, List

import numpy as np
import torch

from dorpatch_tpu import metrics, observe
from dorpatch_tpu.artifacts import ArtifactStore, results_path, write_config_record
from dorpatch_tpu.backends.torch_attack import (
    TorchDorPatch,
    build_torch_defenses,
    l2_project,
)
from dorpatch_tpu.backends.torch_models import Normalized, create_torch_model
from dorpatch_tpu.config import ExperimentConfig
from dorpatch_tpu.config import resolved_data_source
from dorpatch_tpu.data import dataset_batches


def get_torch_victim(cfg: ExperimentConfig) -> torch.nn.Module:
    """Torch victim with the reference's checkpoint contract
    (`/root/reference/utils.py:47-63` + `NormModel`): load
    `<model_dir>/<dataset>/<timm>_cutout2_128_<dataset>.pth` when present,
    else keep the (seeded) random initialization."""
    import os

    from dorpatch_tpu.models.registry import checkpoint_path, resolve_arch

    torch.manual_seed(cfg.seed)
    net = create_torch_model(cfg.base_arch, cfg.num_classes)
    ckpt = checkpoint_path(cfg.model_dir, cfg.dataset, resolve_arch(cfg.base_arch))
    if os.path.exists(ckpt):
        obj = torch.load(ckpt, map_location="cpu", weights_only=True)
        if isinstance(obj, dict) and "state_dict" in obj:
            obj = obj["state_dict"]
        obj = {k.removeprefix("module."): v for k, v in obj.items()}
        net.load_state_dict(obj)
    return Normalized(net).eval()


def _nchw(x_np: np.ndarray) -> torch.Tensor:
    return torch.from_numpy(np.moveaxis(x_np, -1, 1).copy()).float()


def run_experiment_torch(cfg: ExperimentConfig, verbose: bool = True) -> Dict:
    """Torch twin of `pipeline.run_experiment`; returns the same metrics dict."""
    random.seed(cfg.seed)
    np.random.seed(cfg.seed)
    torch.manual_seed(cfg.seed)
    rng = np.random.default_rng(cfg.seed)

    model = get_torch_victim(cfg)
    store = ArtifactStore(results_path(cfg))
    write_config_record(cfg, store.result_dir)
    # run.json keeps the results dir self-describing on this backend too
    # (no jax environment blurb: this path must never touch jax)
    observe.write_run_manifest(
        store.result_dir, cfg, run_id=observe.new_run_id(),
        extra={"backend_impl": "torch", "backend": "torch-cpu",
               "torch": torch.__version__})
    defenses = build_torch_defenses(model, cfg.img_size, cfg.defense)
    attack = TorchDorPatch(model, cfg.num_classes, cfg.attack)

    preds_list: List[np.ndarray] = []
    y_list: List[np.ndarray] = []
    preds_adv_list: List[np.ndarray] = []
    target_list: List[np.ndarray] = []
    records: List[List] = []

    data_source = resolved_data_source(cfg)
    batches = dataset_batches(
        cfg.dataset, cfg.data_dir, cfg.batch_size, cfg.img_size, cfg.seed,
        source=data_source,
    )
    attack_seconds: List[float] = []
    generated_images = 0
    for i, (x_np, y_np) in enumerate(batches):
        if i == cfg.num_batches:  # reference batch cap (`main.py:84`)
            break
        t0 = time.time()
        x = _nchw(x_np)

        with torch.no_grad():
            preds = model(x).argmax(-1).numpy()
        if data_source == "synthetic":
            y_np = preds.copy()  # random labels -> score the model's own preds
        correct = preds == y_np
        if correct.sum() == 0:
            continue
        x = x[torch.from_numpy(correct)]
        y_np = y_np[correct]
        preds = preds[correct]

        cached = store.load_patch(i)
        if cached is not None:
            adv_mask = _nchw(cached[0])
            adv_pattern = _nchw(cached[1])
            if cfg.attack.targeted:
                # recorded target first; reference re-derivation fallback —
                # shared contract in ArtifactStore.resolve_targets
                def _rederive(s0):
                    with torch.no_grad():
                        delta0 = l2_project(
                            _nchw(s0[0]), _nchw(s0[1]), x, cfg.attack.eps)
                        return model(x + delta0).argmax(-1).numpy()

                target_list.append(store.resolve_targets(i, _rederive))
        else:
            y_attack = None
            if cfg.attack.targeted:
                y_attack = torch.from_numpy(
                    _random_targets(rng, y_np, cfg.num_classes))
            t_gen = time.time()
            result = attack.generate(
                x, y=y_attack, targeted=cfg.attack.targeted,
                seed=cfg.seed + i, store=store, batch_id=i,
            )
            attack_seconds.append(time.time() - t_gen)
            if cfg.attack.targeted:
                # the target the attack actually optimized (result.y), kept
                # consistent with what cached re-runs will score against
                target_list.append(np.asarray(result.y))
                store.save_targets(i, np.asarray(result.y))
            generated_images += int(x.shape[0])
            adv_mask, adv_pattern = result.adv_mask, result.adv_pattern
            store.save_patch(
                i,
                np.moveaxis(adv_mask.numpy(), 1, -1),
                np.moveaxis(adv_pattern.numpy(), 1, -1),
            )

        with torch.no_grad():
            delta = l2_project(adv_mask, adv_pattern, x, cfg.attack.eps)
            adv_x = x + delta

        recs = store.load_pc_records(i)
        if recs is not None and any(len(r) != len(defenses) for r in recs):
            recs = None
        if recs is None:
            per_defense = [
                d.robust_predict(adv_x, cfg.num_classes) for d in defenses
            ]
            recs = [list(r) for r in zip(*per_defense)]
            store.save_pc_records(i, recs)

        preds_list.append(preds)
        y_list.append(y_np)
        with torch.no_grad():
            preds_adv_list.append(model(adv_x).argmax(-1).numpy())
        records.extend(recs)
        if verbose:
            observe.log(
                f"batch {i}: {len(y_np)} imgs in {time.time() - t0:.1f}s")

    if not preds_list:
        empty = {"clean_accuracy": 0.0, "robust_accuracy": 0.0,
                 "acc_pc": [], "certified_acc_pc": [], "certified_asr_pc": [],
                 "evaluated_images": 0,
                 "report": "no correctly-classified images evaluated"}
        if verbose:
            observe.log(empty["report"])
        return empty
    preds_clean = np.concatenate(preds_list)
    y_all = np.concatenate(y_list)
    preds_adv = np.concatenate(preds_adv_list)
    targets = np.concatenate(target_list) if target_list else None

    for di, d in enumerate(defenses):
        d.collect([r[di] for r in records])
    m = metrics.compute_metrics(
        preds_clean, y_all, preds_adv, [d.result for d in defenses], targets)
    m["evaluated_images"] = int(len(y_all))
    if attack_seconds:
        m["attack_seconds"] = attack_seconds
        m["attack_images_per_sec"] = round(
            generated_images / sum(attack_seconds), 4)
    m["report"] = metrics.report_line(m)
    if verbose:
        observe.log(m["report"])
    return m


def _random_targets(rng: np.random.Generator, y: np.ndarray, n_classes: int) -> np.ndarray:
    """Random targets != label (same repair as the jax pipeline's
    `_random_targets`: re-sample clashes instead of asserting)."""
    t = rng.integers(0, n_classes, y.shape)
    while (t == y).any():
        clash = t == y
        t[clash] = rng.integers(0, n_classes, clash.sum())
    return t
