"""End-to-end experiment driver (the reference's `main()`,
`/root/reference/main.py:44-188`): per-batch attack with artifact resume,
PatchCleanser evaluation with record caching, and final metrics.

The jax path is the product; per-batch flow:
  filter correctly-classified -> resume or run DorPatch.generate ->
  L2-project the patch -> certify with the 4-radius defense bank ->
  accumulate records -> report.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from dorpatch_tpu import data, losses, metrics, observe, parallel, utils
from dorpatch_tpu.artifacts import ArtifactStore, results_path, write_config_record
from dorpatch_tpu.attack import DorPatch
from dorpatch_tpu.config import ExperimentConfig, resolved_data_source
from dorpatch_tpu.data import dataset_batches
from dorpatch_tpu.defense import build_defenses
from dorpatch_tpu.models import get_model


def _random_targets(rng: np.random.Generator, y: np.ndarray, n_classes: int) -> np.ndarray:
    """Random targets != label (the reference asserts and crashes on a clash,
    `main.py:122-123`; we re-sample instead)."""
    t = rng.integers(0, n_classes, y.shape)
    while (t == y).any():
        clash = t == y
        t[clash] = rng.integers(0, n_classes, clash.sum())
    return t


def run_experiment(cfg: ExperimentConfig, verbose: bool = True) -> Dict:
    """Run the full pipeline; returns the metrics dict (+ report line)."""
    if cfg.backend not in ("jax-tpu", "torch"):
        raise ValueError(f"unknown backend {cfg.backend!r}")
    if cfg.backend == "torch":
        from dorpatch_tpu.backends.torch_pipeline import run_experiment_torch

        return run_experiment_torch(cfg, verbose)

    multi = jax.process_count() > 1
    if multi:
        # SPMD driver (BASELINE config 5): every process runs this identical
        # host program on identical host values; per-image state is
        # replicated, the masked-image batch shards over the whole mesh, and
        # artifact IO is process-0-only with broadcast reads — see
        # parallel/multiproc.py for the design.
        if cfg.mesh_data * cfg.mesh_mask <= 1:
            raise ValueError(
                "multi-process run_experiment needs an explicit mesh: set "
                "mesh_data*mesh_mask to the global device count")
        if cfg.carry_checkpoints:
            raise ValueError(
                "carry_checkpoints snapshots are process-local and would "
                "diverge on resume; unsupported in multi-process runs")
    utils.set_global_seed(cfg.seed)       # host RNGs (`utils.py:16-21`)
    utils.select_device(cfg.device)       # `--device` flag (`utils.py:12-13`)
    utils.enable_compilation_cache()      # re-runs skip tunnel recompiles
    proc = jax.process_index()
    observe.set_process_index(proc)       # attributable multi-process logs
    is_main = (not multi) or parallel.multiproc.is_main()
    if verbose and is_main:
        # lets log consumers (chip_validation) tell a real accelerator run
        # from jax silently falling back to the CPU backend
        observe.log(f"backend: {jax.default_backend()} "
                    f"({len(jax.devices())} devices, "
                    f"{jax.process_count()} processes)")
    rng = np.random.default_rng(cfg.seed)
    store = ArtifactStore(results_path(cfg))
    if multi:
        store = parallel.multiproc.Process0Store(store)

    # Run telemetry (observe/): per-attempt run_id stamps every record so
    # resumed runs are groupable; run.json makes the results dir
    # self-describing; events.jsonl + heartbeat_<proc>.jsonl are per process
    # but share ONE attempt id (process 0's, broadcast) so the report CLI
    # groups the whole run as a single attempt.
    run_id = observe.new_run_id()
    if multi:
        run_id = parallel.multiproc.shared_run_id(run_id)
    if is_main:
        write_config_record(cfg, store.result_dir)
        observe.write_run_manifest(store.result_dir, cfg, run_id=run_id,
                                   extra=observe.jax_environment())
    elog = hb = watchdog = None
    if cfg.metrics_log:
        elog = observe.EventLog(
            os.path.join(store.result_dir, observe.events_filename(proc)),
            run_id=run_id, process_index=proc)
        hb = observe.Heartbeat(
            os.path.join(store.result_dir, observe.heartbeat_filename(proc)),
            get_phase=elog.current_path, interval=cfg.heartbeat_interval,
            process_index=proc, run_id=run_id)
        if cfg.hang_timeout > 0:
            watchdog = observe.Watchdog(store.result_dir, elog,
                                        cfg.hang_timeout)
    elif cfg.hang_timeout > 0:
        # the watchdog's progress signal IS the event log — be loud rather
        # than silently unprotected when telemetry is disabled
        observe.log(f"WARNING: --hang-timeout {cfg.hang_timeout:g} ignored: "
                    "telemetry is disabled (--no-metrics-log)",
                    file=sys.stderr)
    logger = observe.AttackMetricsLogger(
        path=os.path.join(store.result_dir, "metrics.jsonl")
        if (cfg.metrics_log and is_main) else None,
        echo_every=cfg.attack.report_interval if (verbose and is_main) else 0,
        run_id=run_id,
    )

    def _on_block(stage, step, info):
        logger.on_block_end(stage, step, info)
        if elog is not None:  # block wall time + device-memory sample
            elog.block_boundary(stage, step, info)

    with contextlib.ExitStack() as stack:
        if elog is not None:
            stack.enter_context(elog)
            stack.enter_context(observe.active(elog))
            stack.enter_context(hb)
            if watchdog is not None:
                stack.enter_context(watchdog)
        if cfg.sanitize:
            # runtime sanitizers (analysis/sanitize.py): debug_nans,
            # log_compiles -> events.jsonl, recompile-budget watchdog.
            # Entered after the EventLog activates so sanitizer events land
            # in the telemetry stream (no-op sinks when telemetry is off).
            from dorpatch_tpu.analysis.sanitize import Sanitizer

            stack.enter_context(Sanitizer())
        stack.enter_context(observe.trace(cfg.trace_dir))
        stack.enter_context(logger)
        stack.enter_context(
            observe.span("run", processes=int(jax.process_count())))

        with observe.span("setup"):
            victim = get_model(cfg.dataset, cfg.base_arch, cfg.model_dir,
                               cfg.img_size, gn_impl=cfg.gn_impl)
            # declared trace budget per jitted entry point: the correctness
            # filter makes the surviving batch dynamic, so distinct batch
            # sizes (1..batch_size) are the only legitimate shape buckets.
            # Enforced by the recompile watchdog under --sanitize.
            budget = int(cfg.batch_size)
            # certification runs bucketed (single-chip path): ragged batches
            # round up to data.batch_buckets sizes, so the 666-mask sweep
            # compiles once per bucket, not once per surviving batch size.
            # Meshed runs keep exact-batch sweeps: padding would re-lay-out
            # the sharded input and defeat the place_batch contract. (The
            # meshed pruned path still buckets its phase-2 worklists — at
            # its own [S * bucket] shard-local wave shapes, independent of
            # these image buckets; see defense._PrunedPending._schedule_mesh.)
            cert_buckets = None
            mesh = None
            if cfg.mesh_data * cfg.mesh_mask > 1:
                mesh = parallel.make_mesh(cfg.mesh_data, cfg.mesh_mask)
                defenses = parallel.make_sharded_defenses(
                    victim.apply, cfg.img_size, mesh, cfg.defense,
                    recompile_budget=budget,
                    incremental=victim.incremental)
                attack = parallel.make_sharded_attack(
                    victim.apply, victim.params, victim.num_classes,
                    cfg.attack, mesh, recompile_budget=budget)
            else:
                cert_buckets = data.batch_buckets(cfg.batch_size)
                defenses = build_defenses(victim.apply, cfg.img_size,
                                          cfg.defense,
                                          recompile_budget=len(cert_buckets),
                                          incremental=victim.incremental)
                attack = DorPatch(victim.apply, victim.params,
                                  victim.num_classes, cfg.attack,
                                  recompile_budget=budget)
            attack.on_block_end = _on_block

        preds_list: List[np.ndarray] = []
        y_list: List[np.ndarray] = []
        preds_adv_list: List[np.ndarray] = []
        target_list: List[np.ndarray] = []
        records: List[List] = []

        data_source = resolved_data_source(cfg)
        if cfg.stream_depth > 0 and not multi:
            # streaming input path: background chunked reads + double-
            # buffered device prefetch (data.streaming_batches). Multi-
            # process feeding keeps the synchronous path — its per-process
            # local shards go through place_replicated below, which needs
            # the host array.
            batches = data.streaming_batches(
                cfg.dataset, cfg.data_dir, cfg.batch_size, cfg.img_size,
                cfg.seed, source=data_source, depth=cfg.stream_depth,
                mesh=mesh)
        else:
            batches = dataset_batches(
                cfg.dataset, cfg.data_dir, cfg.batch_size, cfg.img_size,
                cfg.seed, source=data_source,
            )
        timer = observe.StepTimer()
        generated_images = 0
        batch_iter = enumerate(batches)
        while True:
            # data fetch in its own span so the batch spans plus this one
            # cover the whole loop's wall time (report coverage contract)
            with observe.span("data"):
                nxt = next(batch_iter, None)
            if nxt is None:
                break
            i, (x_np, y_np) = nxt
            if i == cfg.num_batches:  # the reference's hard batch cap (`main.py:84`)
                break
            t0 = time.time()
            logger.set_batch(i)
            with observe.span("batch", batch=i) as sp_batch:
                x = jnp.asarray(x_np)

                # keep only correctly-classified images (`main.py:91-99`)
                preds = np.asarray(
                    jnp.argmax(victim.apply(victim.params, x), -1))
                if data_source == "synthetic":
                    # synthetic labels are random, so the correctness filter
                    # would be degenerate: score against the model's own clean
                    # predictions instead. Procedural labels are genuine — the
                    # filter keeps its reference semantics (`main.py:91-99`).
                    y_np = preds.copy()
                correct = preds == y_np
                sp_batch["images"] = int(correct.sum())
                if correct.sum() == 0:
                    continue
                x = x[jnp.asarray(correct)]
                y_np = y_np[correct]
                preds = preds[correct]
                if mesh is not None:
                    if multi:
                        # per-image state replicates on multi-process meshes
                        # (the masked batch still shards over the whole mesh;
                        # see parallel/multiproc.py) — place_replicated
                        # handles the multi-controller construction
                        x = parallel.place_replicated(mesh, np.asarray(x))
                    else:
                        # the correctness filter makes the surviving batch
                        # size dynamic; shard it over the data axis when it
                        # divides, else replicate (per-image state is tiny
                        # next to the EOT activation batch)
                        x = parallel.place_batch_auto(mesh, x)

                with observe.span("artifact_io", op="load_patch"):
                    cached = store.load_patch(i)
                sp_batch["cached"] = cached is not None
                if cached is not None:
                    adv_mask, adv_pattern = map(jnp.asarray, cached)
                    if cfg.attack.targeted:
                        # recorded target (what the attack actually optimized)
                        # first; reference re-derivation fallback — shared
                        # contract in ArtifactStore.resolve_targets
                        def _rederive(s0):
                            delta0 = losses.l2_project(
                                jnp.asarray(s0[0]), jnp.asarray(s0[1]), x,
                                cfg.attack.eps)
                            return jnp.argmax(
                                victim.apply(victim.params, x + delta0), -1)

                        with observe.span("artifact_io", op="resolve_targets"):
                            target_list.append(
                                store.resolve_targets(i, _rederive))
                else:
                    if cfg.attack.targeted:
                        y_attack = jnp.asarray(
                            _random_targets(rng, y_np, victim.num_classes))
                    else:
                        y_attack = None
                    ck = None
                    if cfg.carry_checkpoints:
                        from dorpatch_tpu.checkpoint import CarryCheckpointer

                        ck = CarryCheckpointer(
                            os.path.join(store.result_dir, f"carry_{i}"),
                            fingerprint={
                                "seed": int(cfg.seed),
                                "batch": int(i),
                                "n_images": int(x.shape[0]),
                                "attack": repr(cfg.attack),
                            })
                        attack.checkpointer = ck
                    timer.start()
                    try:
                        with observe.span("attack"):
                            result = attack.generate(
                                x, y=y_attack, targeted=cfg.attack.targeted,
                                key=jax.random.PRNGKey(cfg.seed + i),
                                store=store, batch_id=i,
                            )
                            jax.block_until_ready(result.adv_pattern)
                        if ck is not None:
                            ck.clear()  # success: stale carries must not leak forward
                    finally:
                        attack.checkpointer = None
                        if ck is not None:
                            ck.close()  # on failure snapshots stay for resume
                    timer.stop()
                    generated_images += int(x.shape[0])
                    if cfg.attack.targeted:
                        # record the target the attack actually optimized
                        # toward: on a carry-checkpoint resume the restored
                        # state.y is the snapshot's target, not this process's
                        # fresh rng draw — recording the draw would silently
                        # corrupt certified-ASR. Persist it so cached re-runs
                        # score the same target.
                        target_list.append(np.asarray(result.y))
                        with observe.span("artifact_io", op="save_targets"):
                            store.save_targets(i, np.asarray(result.y))
                    adv_mask, adv_pattern = result.adv_mask, result.adv_pattern
                    with observe.span("artifact_io", op="save_patch"):
                        store.save_patch(i, np.asarray(adv_mask),
                                         np.asarray(adv_pattern))

                delta = losses.l2_project(adv_mask, adv_pattern, x,
                                          cfg.attack.eps)
                adv_x = x + delta

                # PatchCleanser evaluation with record cache
                # (`main.py:144-153`); a cache from a different defense bank
                # (wrong per-image record count) is recomputed rather than
                # silently reused
                with observe.span("artifact_io", op="load_pc_records"):
                    recs = store.load_pc_records(i)
                if recs is not None and any(
                        len(r) != len(defenses) for r in recs):
                    recs = None
                if recs is None:
                    with observe.span(
                            "certify", batch=i, images=int(x.shape[0]),
                            compute_dtype=(
                                "bf16"
                                if cfg.defense.compute_dtype == "bfloat16"
                                else "f32")) as sp_cert:
                        per_defense = [
                            d.robust_predict(victim.params, adv_x,
                                             victim.num_classes,
                                             bucket_sizes=cert_buckets)
                            for d in defenses
                        ]
                        # executed vs exhaustive masked-forward accounting
                        # (observe.report derives prune rate / speedup from
                        # these span attrs — single-chip and meshed runs
                        # alike, now that the pruned schedule runs on both)
                        sp_cert["forwards"] = sum(
                            max(0, r.forwards)
                            for recs_d in per_defense for r in recs_d)
                        # fractional full-forward cost: incremental entries
                        # (token-pruned ViT / stem-folded conv) are credited
                        # at their true fraction of a forward
                        sp_cert["forward_equivalents"] = round(sum(
                            max(0.0, r.forward_equivalents)
                            for recs_d in per_defense for r in recs_d), 2)
                        sp_cert["forwards_exhaustive"] = int(
                            x.shape[0]) * sum(d.num_forwards_exhaustive
                                              for d in defenses)
                    # records_batch[img][defense], the reference's nesting
                    recs = [list(r) for r in zip(*per_defense)]
                    with observe.span("artifact_io", op="save_pc_records"):
                        store.save_pc_records(i, recs)

                preds_list.append(preds)
                y_list.append(y_np)
                preds_adv_list.append(np.asarray(
                    jnp.argmax(victim.apply(victim.params, adv_x), -1)))
                records.extend(recs)
                if verbose and is_main:
                    observe.log(f"batch {i}: {len(y_np)} imgs in "
                                f"{time.time() - t0:.1f}s")

        with observe.span("finalize"):
            if not preds_list:
                empty = {"clean_accuracy": 0.0, "robust_accuracy": 0.0,
                         "acc_pc": [], "certified_acc_pc": [],
                         "certified_asr_pc": [], "evaluated_images": 0,
                         "report": "no correctly-classified images evaluated"}
                if verbose and is_main:
                    observe.log(empty["report"])
                return empty
            preds_clean = np.concatenate(preds_list)
            y_all = np.concatenate(y_list)
            preds_adv = np.concatenate(preds_adv_list)
            targets = np.concatenate(target_list) if target_list else None

            for di, d in enumerate(defenses):
                d.collect([r[di] for r in records])
            m = metrics.compute_metrics(
                preds_clean, y_all, preds_adv, [d.result for d in defenses],
                targets)
            m["evaluated_images"] = int(len(y_all))
            if targets is not None:
                m["targets"] = [int(t) for t in targets]
            if timer.block_seconds:
                # per-generate wall clock (each "block" is one
                # attack.generate call)
                m["attack_seconds"] = timer.block_seconds
                m["attack_images_per_sec"] = round(
                    generated_images / sum(timer.block_seconds), 4)
            m["report"] = metrics.report_line(m)
            if verbose and is_main:
                observe.log(m["report"])
            if is_main:
                try:
                    with open(os.path.join(store.result_dir,
                                           "summary.json"), "w") as fh:
                        json.dump(m, fh, indent=1, default=float)
                except OSError:
                    pass  # read-only results dir: the return value carries everything
            return m
