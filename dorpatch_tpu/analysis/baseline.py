"""Program-baseline tier: jaxpr fingerprints + static cost model (DP3xx).

The AST tier (DP1xx) proves what is visible in source and the trace tier
(DP2xx) proves what is visible in one version's jaxprs — but neither
compares programs *across versions*. A refactor that silently doubles the
FLOPs of `defense.phase1.r*`, regrows the pruned/incremental paths'
forward count, or drifts an entry point's aval signature is invisible
until a real-hardware bench runs. This module closes that hole with a
checked-in program baseline (`analysis/baselines.json`) and a drift gate:

- **Fingerprint** — a stable hash over the *canonical* form of each entry
  point's jaxpr: variable names are positional (first-appearance order),
  platform/process noise (function names, memory addresses, sharding
  placeholders, thunks) is normalized away, sub-jaxprs (pjit/scan/cond
  bodies) are rendered recursively. The body fingerprint deliberately
  excludes weak-type flags and donation so interface-only drift is
  separable (DP304); the interface record (input/output avals incl.
  weak_type, donation pattern) is hashed on its own.
- **Static cost vector** — flops / bytes-accessed / peak temp memory from
  `jit(...).trace().lower().compile()` `cost_analysis()` +
  `memory_analysis()` (zero device FLOPs on the CPU gate), plus an
  always-available pure jaxpr-walk estimator (`estimate_cost`) with a
  per-primitive breakdown, so cost checks work even where XLA's analysis
  is unavailable and DP301 can name the dominant regressing primitive.

Rules (the `--baseline check` gate; `--baseline update` regenerates the
file deterministically — sorted keys, normalized floats — so diffs review
cleanly):

- **DP300 fingerprint-drift** — the live program's body fingerprint
  differs from the baseline: the program changed but the baseline was not
  regenerated in the same PR.
- **DP301 cost-regression** — flops/bytes (compiled or estimated) grew
  past the entry's relative tolerance: the bench-free perf-regression
  gate. The finding names the dominant regressing primitive.
- **DP302 entrypoint-set-drift** — an entry point was added or removed
  relative to the baseline (coverage must stay exact: the future AOT
  executable cache keys on this set).
- **DP303 budget-ladder-mismatch** — the `recompile_budget` a
  `timed_first_call` wrap declares differs from the bucket count the
  registered program set actually implies (explicit bucket ladder, or the
  `name[bN]`-variant count).
- **DP304 interface-drift** — aval / weak-type / donation drift with an
  *unchanged* body fingerprint — exactly the change that would poison an
  AOT executable cache keyed on the fingerprint.

Suppression follows the trace tier's contract: `# noqa: DP3xx` on the
entry point's `def` line, or a reasoned `baseline.ALLOWLIST` entry
(fnmatch glob -> {rule: reason}) for intentional cost changes that land
in the same PR as their baseline update.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
import json
import pathlib
import re
from typing import (Any, Dict, Iterable, List, Mapping, Optional, Sequence,
                    Tuple)

from dorpatch_tpu.analysis.engine import Finding
from dorpatch_tpu.analysis.entrypoints import EntryPoint
from dorpatch_tpu.analysis import comms as comms_mod
from dorpatch_tpu.analysis import program as program_mod

#: The checked-in baseline, shipped inside the package so the gate and the
#: installed console scripts agree on one file.
BASELINE_FILENAME = "baselines.json"

#: DP301 default relative tolerance: cost growth up to this fraction is
#: accepted without a finding (XLA scheduling details move bytes-accessed a
#: little between minor refactors; real regressions are step functions).
DEFAULT_TOLERANCE = 0.10

#: Peak-temp-memory is the jitteriest metric XLA reports (buffer assignment
#: is a heuristic); it gets a widened tolerance.
TEMP_TOLERANCE_FACTOR = 2.0

#: Per-entry tolerance overrides: fnmatch glob -> relative tolerance.
#: An intentional cost change should instead land its `--baseline update`
#: in the same PR; overrides are for entries whose cost is legitimately
#: noisy across regenerations.
TOLERANCES: Dict[str, float] = {}

#: Entry-point-name glob -> {rule_id: reason} — the baseline tier's analog
#: of `program.ALLOWLIST`, for intentional drift no source line can own.
#: Shipped entries must carry their reason.
ALLOWLIST: Dict[str, Dict[str, str]] = {}

#: (id, name, description) rows for `--list-rules` (the baseline tier has
#: no TraceRule objects: its rules compare two snapshots, not one jaxpr).
BASELINE_RULE_ROWS: Tuple[Tuple[str, str, str], ...] = (
    ("DP300", "fingerprint-drift",
     "entry point's canonical jaxpr fingerprint differs from "
     "analysis/baselines.json — program changed but the baseline was not "
     "regenerated (--baseline update)"),
    ("DP301", "cost-regression",
     "entry point's static cost (flops/bytes, compiled or estimated) grew "
     "past its relative tolerance vs the baseline — the bench-free "
     "perf-regression gate"),
    ("DP302", "entrypoint-set-drift",
     "entry point added or removed relative to the baseline — the audited "
     "program set (and any AOT cache keyed on it) changed shape"),
    ("DP303", "budget-ladder-mismatch",
     "declared timed_first_call recompile_budget differs from the bucket "
     "count the registered program set implies"),
    ("DP304", "interface-drift",
     "input/output aval, weak-type, or donation drift with an UNCHANGED "
     "body fingerprint — poisons an AOT executable cache key"),
    ("DP305", "aot-store-drift",
     "AOT executable store manifest disagrees with analysis/baselines.json "
     "— stale or missing entry, corrupt payload, or build-env/topology "
     "mismatch; rebuild with `python -m dorpatch_tpu.aot build` (emitted "
     "by `python -m dorpatch_tpu.aot verify`)"),
)

BASELINE_RULE_IDS: Tuple[str, ...] = tuple(r[0] for r in BASELINE_RULE_ROWS)


def baseline_path() -> pathlib.Path:
    """The checked-in default baseline file (inside the package)."""
    return pathlib.Path(__file__).with_name(BASELINE_FILENAME)


# ------------------------------------------------------------- fingerprint

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")

#: Eqn params dropped from the canonical rendering: process/platform noise
#: (function names, thunks, compiler knobs) and placement metadata that the
#: fingerprint must not depend on. Donation is interface, not body.
_NOISE_PARAMS = frozenset({
    "name", "backend", "device", "inline", "keep_unused",
    "compiler_options_kvs", "jvp_jaxpr_thunk", "bwd", "fwd",
    "donated_invars", "in_shardings", "out_shardings",
    "in_layouts", "out_layouts", "resource_env", "ctx_mesh",
})


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()[:16]


def _aval_sig(a, weak: bool = False) -> str:
    """`f32[2,32,32,3]`-style aval signature. `weak=True` appends the
    weak-type marker — interface records keep it, the body canonicalization
    drops it so weak-only drift stays separable (DP304 vs DP300)."""
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is None or dtype is None:
        return _ADDR_RE.sub("0x*", str(a))
    sig = f"{dtype}[{','.join(str(int(d)) for d in shape)}]"
    if weak and getattr(a, "weak_type", False):
        sig += "~w"
    return sig


def _norm_value(v) -> str:
    """Deterministic, address-free rendering of a (non-jaxpr) param value."""
    import numpy as np

    if v is None or isinstance(v, (bool, int, str)):
        return repr(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, np.ndarray):
        if v.size <= 8:
            return f"arr({v.dtype}{list(v.shape)}:{v.tolist()!r})"
        return (f"arr({v.dtype}{list(v.shape)}:"
                f"{hashlib.sha256(np.ascontiguousarray(v).tobytes()).hexdigest()[:12]})")
    if isinstance(v, np.generic):
        return f"{v.dtype}:{v.item()!r}"
    if isinstance(v, np.dtype):
        return str(v)
    if isinstance(v, (tuple, list)):
        inner = ",".join(_norm_value(x) for x in v)
        return f"({inner})" if isinstance(v, tuple) else f"[{inner}]"
    if isinstance(v, (dict,)):
        items = ",".join(f"{k}:{_norm_value(v[k])}" for k in sorted(v, key=str))
        return "{" + items + "}"
    if isinstance(v, (set, frozenset)):
        return "{" + ",".join(sorted(_norm_value(x) for x in v)) + "}"
    # a Mesh renders as its axis names/sizes — device objects are process
    # noise, the logical topology is program structure
    names = getattr(v, "axis_names", None)
    if names and hasattr(v, "shape"):
        try:
            dims = ",".join(f"{n}:{int(v.shape[n])}" for n in names)
            return f"mesh({dims})"
        except Exception:
            pass
    if hasattr(v, "dtype") and hasattr(v, "shape"):
        return f"aval({_aval_sig(v)})"
    if callable(v):
        return "<fn>"
    return _ADDR_RE.sub("0x*", f"{type(v).__name__}:{v!r}"[:160])


def _raw(j):
    import jax

    return j.jaxpr if isinstance(j, jax.core.ClosedJaxpr) else j


def canonical_jaxpr(closed_or_raw) -> str:
    """Canonical textual form of a jaxpr: positional variable names
    (first-appearance order, scope-local), sorted params with noise keys
    dropped, sub-jaxprs rendered recursively in place. Two traces of the
    same program — fresh processes, fresh jit objects, renamed python
    locals — produce byte-identical output; any change to an equation, a
    literal constant, or an aval changes it."""
    import jax

    lines: List[str] = []

    def render(j, depth: int) -> None:
        j = _raw(j)
        names: Dict[Any, str] = {}

        def nm(v) -> str:
            if isinstance(v, jax.core.Literal):
                return f"lit({_norm_value(v.val)}:{_aval_sig(v.aval)})"
            if isinstance(v, jax.core.DropVar):
                return "_"
            if v not in names:
                names[v] = f"v{len(names)}"
            return f"{names[v]}:{_aval_sig(v.aval)}"

        pad = " " * depth
        lines.append(pad + "in " + " ".join(nm(v) for v in j.invars))
        if j.constvars:
            lines.append(pad + "const " + " ".join(nm(v) for v in j.constvars))
        for eqn in j.eqns:
            parts: List[str] = []
            subs: List[Any] = []
            for k in sorted(eqn.params):
                if k in _NOISE_PARAMS:
                    continue
                v = eqn.params[k]
                sub_js = [x for x in (v if isinstance(v, (list, tuple))
                                      else [v])
                          if isinstance(x, (jax.core.Jaxpr,
                                            jax.core.ClosedJaxpr))]
                if sub_js:
                    parts.append(f"{k}=<jaxpr:{len(sub_js)}>")
                    subs.extend(sub_js)
                else:
                    parts.append(f"{k}={_norm_value(v)}")
            outs = " ".join(nm(v) for v in eqn.outvars)
            ins = " ".join(nm(v) for v in eqn.invars)
            lines.append(f"{pad}{outs} = {eqn.primitive.name}"
                         f"[{' '.join(parts)}] {ins}")
            for s in subs:
                lines.append(pad + "{")
                render(s, depth + 1)
                lines.append(pad + "}")
        lines.append(pad + "out " + " ".join(nm(v) for v in j.outvars))

    render(closed_or_raw, 0)
    return "\n".join(lines)


def fingerprint(closed_or_raw) -> str:
    """16-hex stable hash of the canonical jaxpr body."""
    return _sha(canonical_jaxpr(closed_or_raw))


def interface_record(ctx: "program_mod.ProgramContext") -> Dict[str, Any]:
    """The entry point's boundary contract: flat input/output aval
    signatures (weak_type INCLUDED — the retrace/promotion hazard DP304
    exists to catch) and the donated-argument index pattern. Long aval
    lists (params pytrees run to hundreds of leaves) are stored as
    count + hash + a human-readable head."""
    import jax

    ins = [_aval_sig(a, weak=True) for a in ctx.jaxpr.in_avals]
    outs = [_aval_sig(a, weak=True) for a in ctx.jaxpr.out_avals]
    donated: List[int] = []
    if ctx.args_info is not None:
        leaves = jax.tree_util.tree_leaves(
            ctx.args_info, is_leaf=lambda x: hasattr(x, "donated"))
        donated = [i for i, x in enumerate(leaves)
                   if getattr(x, "donated", False)]
    rec: Dict[str, Any] = {
        "inputs": {"count": len(ins), "sha": _sha("|".join(ins)),
                   "head": ins[:4]},
        "outputs": {"count": len(outs), "sha": _sha("|".join(outs)),
                    "head": outs[:4]},
        "donated": donated,
    }
    rec["sha"] = _sha(json.dumps(rec, sort_keys=True))
    return rec


# -------------------------------------------------------------- cost model

#: Cap the stored per-primitive breakdown: enough to name the dominant
#: regressing primitive, small enough to keep baselines.json reviewable.
TOP_K_PRIMITIVES = 8


@dataclasses.dataclass
class _CostAcc:
    flops: float = 0.0
    bytes: float = 0.0
    by_primitive: Dict[str, float] = dataclasses.field(default_factory=dict)


def _eqn_flops(eqn) -> float:
    """Analytic flops estimate for one equation. Matmuls and convs get the
    real formula; everything else is 1 flop/output element (reductions:
    1 flop/input element). Deliberately coarse — the estimator exists to
    rank primitives and catch step-function regressions, not to rival
    XLA's model."""
    prim = eqn.primitive.name
    out_sizes = [int(_size(v.aval)) for v in eqn.outvars
                 if hasattr(getattr(v, "aval", None), "shape")]
    out_size = sum(out_sizes) or 1
    if prim == "dot_general":
        dn = eqn.params.get("dimension_numbers")
        lhs = getattr(eqn.invars[0], "aval", None)
        k = 1
        if dn is not None and lhs is not None:
            (lhs_contract, _), _ = dn
            for d in lhs_contract:
                k *= int(lhs.shape[d])
        return 2.0 * out_size * k
    if prim == "conv_general_dilated":
        rhs = getattr(eqn.invars[1], "aval", None)
        if rhs is None:
            return float(out_size)
        dn = eqn.params.get("dimension_numbers")
        rhs_size = _size(rhs)
        out_feat = 1
        if dn is not None and hasattr(dn, "rhs_spec"):
            out_feat = int(rhs.shape[dn.rhs_spec[0]])
        # per output element: 2 * (kernel spatial x in-channels-per-group)
        return 2.0 * out_size * (rhs_size / max(out_feat, 1))
    if prim.startswith("reduce_") or prim in ("argmax", "argmin"):
        return float(sum(int(_size(v.aval)) for v in eqn.invars
                         if hasattr(getattr(v, "aval", None), "shape")) or 1)
    return float(out_size)


def _size(a) -> int:
    n = 1
    for d in getattr(a, "shape", ()):
        n *= int(d)
    return n


def _eqn_bytes(eqn) -> float:
    """Boundary traffic estimate: bytes of every (non-literal) operand and
    result aval, once each."""
    import jax

    total = 0
    for v in list(eqn.invars) + list(eqn.outvars):
        if isinstance(v, jax.core.Literal):
            continue
        a = getattr(v, "aval", None)
        if a is None or not hasattr(a, "shape"):
            continue
        total += _size(a) * int(getattr(a.dtype, "itemsize", 4))
    return float(total)


def estimate_cost(closed_or_raw) -> Dict[str, Any]:
    """Pure jaxpr-walk static cost: flops, boundary bytes, arithmetic
    intensity (flops/byte — the roofline axis: low means bandwidth-bound),
    per-primitive flops breakdown. Scan bodies are multiplied by trip
    count; `cond` branches are summed (a conservative upper bound);
    `while` bodies count once (trip count is unknowable statically —
    documented, not guessed). `pallas_call` equations are costed as fused
    kernels: the inner jaxpr's arithmetic times the grid, but only the
    call-boundary operands/results as bytes — kernel intermediates live
    in VMEM, which is exactly the traffic reduction the kernel tier
    exists to show."""
    acc = _CostAcc()
    _walk_cost(closed_or_raw, 1.0, acc)
    by_prim = dict(sorted(acc.by_primitive.items(),
                          key=lambda kv: (-kv[1], kv[0]))[:TOP_K_PRIMITIVES])
    return {"est_flops": acc.flops, "est_bytes": acc.bytes,
            "est_ai": acc.flops / max(acc.bytes, 1.0),
            "primitives": by_prim}


def _walk_cost(j, mult: float, acc: _CostAcc) -> None:
    for eqn in _raw(j).eqns:
        prim = eqn.primitive.name
        subs = program_mod._eqn_subjaxprs(eqn)
        if prim == "pallas_call" and subs:
            inner = _CostAcc()
            for s in subs:
                _walk_cost(s, 1.0, inner)
            steps = 1.0
            gm = eqn.params.get("grid_mapping")
            for d in (getattr(gm, "grid", ()) or ()):
                try:
                    steps *= float(int(d))
                except (TypeError, ValueError):
                    pass
            f = inner.flops * steps * mult
            acc.flops += f
            acc.bytes += _eqn_bytes(eqn) * mult
            acc.by_primitive[prim] = acc.by_primitive.get(prim, 0.0) + f
            continue
        if subs:
            sub_mult = mult
            if prim == "scan":
                sub_mult = mult * float(eqn.params.get("length", 1) or 1)
            for s in subs:
                _walk_cost(s, sub_mult, acc)
            continue
        f = _eqn_flops(eqn) * mult
        acc.flops += f
        acc.bytes += _eqn_bytes(eqn) * mult
        acc.by_primitive[prim] = acc.by_primitive.get(prim, 0.0) + f


def compiled_cost(traced) -> Optional[Dict[str, float]]:
    """flops / bytes-accessed / peak-temp-bytes from XLA's own analysis of
    the compiled executable (`.lower().compile()`, CPU — zero device
    FLOPs). None when any stage of the AOT chain is unavailable; callers
    fall back to `estimate_cost`."""
    try:
        compiled = traced.lower().compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        out = {"flops": float(analysis.get("flops", 0.0) or 0.0),
               "bytes": float(analysis.get("bytes accessed", 0.0) or 0.0)}
        mem = compiled.memory_analysis()
        out["temp_bytes"] = float(
            getattr(mem, "temp_size_in_bytes", 0) or 0)
        return out
    except Exception:
        return None


# ---------------------------------------------------------------- snapshot

def snapshot_entrypoint(ep: EntryPoint, compiled: bool = True
                        ) -> Tuple[Optional[Dict[str, Any]], List[Finding]]:
    """One entry point -> its baseline entry dict. A program that cannot
    trace cannot be fingerprinted: that is a DP300 gate failure (the
    `--trace` tier additionally classifies WHY it failed)."""
    ctx, errs = program_mod.trace_entrypoint(ep)
    if ctx is None:
        first = errs[0] if errs else None
        return None, [Finding(
            path=first.path if first else "<entrypoint>",
            line=first.line if first else 1, col=1, rule_id="DP300",
            message=f"[{ep.name}] cannot fingerprint: program failed to "
                    "trace abstractly"
                    + (f" ({first.message.split(': ', 1)[-1][:160]})"
                       if first else ""))]
    entry: Dict[str, Any] = {
        "fingerprint": fingerprint(ctx.jaxpr),
        "interface": interface_record(ctx),
        "cost": {},
    }
    est = estimate_cost(ctx.jaxpr)
    entry["cost"]["est_flops"] = est["est_flops"]
    entry["cost"]["est_bytes"] = est["est_bytes"]
    # derived, NOT in _COST_METRICS: flops and bytes already gate DP301,
    # and a ratio of gated metrics would double-report every regression
    entry["cost"]["est_ai"] = est["est_ai"]
    entry["primitives"] = est["primitives"]
    # the comms tier's statically priced collective inventory: total bytes
    # as a gated DP301 metric, the per-collective breakdown next to the
    # flop `primitives` so a comm regression names its dominant collective.
    # Meshed-jit programs with only GSPMD-inserted collectives correctly
    # price to zero — the vector covers EXPLICIT collectives (shard_map /
    # pmap bodies), where every hand-written comm pattern lives.
    comm = comms_mod.comm_cost(ctx.jaxpr)
    entry["cost"]["comm_bytes"] = comm["comm_bytes"]
    entry["comm"] = comm["by_collective"]
    if compiled and getattr(ctx, "traced", None) is not None:
        cc = compiled_cost(ctx.traced)
        if cc is not None:
            entry["cost"].update(cc)
    entry["_path"] = ctx.path
    entry["_line"] = ctx.line
    return entry, []


def build_baseline(eps: Iterable[EntryPoint], compiled: bool = True
                   ) -> Tuple[Dict[str, Any], List[Finding]]:
    """Snapshot every entry point into the baseline-file structure.
    Findings (untraceable programs) make the build unusable for `update` —
    a baseline with holes would make every future check vacuous there."""
    entries: Dict[str, Any] = {}
    findings: List[Finding] = []
    for ep in eps:
        snap, errs = snapshot_entrypoint(ep, compiled=compiled)
        findings.extend(errs)
        if snap is not None:
            snap = {k: v for k, v in snap.items() if not k.startswith("_")}
            entries[ep.name] = snap
    import jax

    data = {
        "version": 1,
        "jax": jax.__version__,
        "tolerance_default": DEFAULT_TOLERANCE,
        "entries": entries,
    }
    return data, findings


def _normalize_numbers(x):
    """Floats that are whole numbers become ints; the rest round to 6
    significant-ish decimals — so regeneration diffs never churn on float
    repr noise."""
    if isinstance(x, dict):
        return {k: _normalize_numbers(v) for k, v in x.items()}
    if isinstance(x, list):
        return [_normalize_numbers(v) for v in x]
    if isinstance(x, float):
        if x != x or x in (float("inf"), float("-inf")):
            return str(x)
        if abs(x) < 1e15 and float(x).is_integer():
            return int(x)
        return round(x, 6)
    return x


def dump_baseline(data: Mapping[str, Any]) -> str:
    """Deterministic serialization: sorted keys, normalized numbers, one
    trailing newline — `--baseline update` twice is byte-identical."""
    return json.dumps(_normalize_numbers(dict(data)), sort_keys=True,
                      indent=1) + "\n"


def load_baseline(path: Optional[pathlib.Path] = None
                  ) -> Optional[Dict[str, Any]]:
    p = pathlib.Path(path) if path is not None else baseline_path()
    try:
        return json.loads(p.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None


def fingerprint_set_hash(entries: Mapping[str, Any]) -> str:
    """One hash over the whole program set (sorted `name:fingerprint`
    lines): the identity BENCH rows and an AOT executable cache key on."""
    lines = [f"{name}:{entries[name].get('fingerprint', '?')}"
             for name in sorted(entries)]
    return _sha("\n".join(lines))


def program_set_stamp(path: Optional[pathlib.Path] = None
                      ) -> Optional[Dict[str, Any]]:
    """BENCH stamp: {hash, entries, file} for the checked-in baseline, so
    recorded perf numbers are attributable to an exact program set. None
    when no baseline file exists (pre-baseline checkouts)."""
    data = load_baseline(path)
    if not data or not data.get("entries"):
        return None
    return {"hash": fingerprint_set_hash(data["entries"]),
            "entries": len(data["entries"]),
            "file": f"analysis/{BASELINE_FILENAME}"}


# ------------------------------------------------------------------- check

def allowed(name: str, rule_id: str,
            allow: Optional[Dict[str, Dict[str, str]]] = None) -> bool:
    """True when `ALLOWLIST` (or the per-call overlay) grants `rule_id`
    for entry point `name` (keys are fnmatch globs)."""
    for table in (ALLOWLIST, allow or {}):
        for pattern, rules in table.items():
            if fnmatch.fnmatchcase(name, pattern) and rule_id in rules:
                return True
    return False


def tolerance_for(name: str, data: Mapping[str, Any]) -> float:
    for pattern, tol in TOLERANCES.items():
        if fnmatch.fnmatchcase(name, pattern):
            return float(tol)
    return float(data.get("tolerance_default", DEFAULT_TOLERANCE))


def _fmt_count(x: float) -> str:
    return f"{int(x):,}" if float(x).is_integer() else f"{x:,.1f}"


#: metric name -> tolerance widening factor (temp memory is heuristic
#: buffer assignment and jitters; flops/bytes are step functions).
_COST_METRICS: Tuple[Tuple[str, float], ...] = (
    ("flops", 1.0), ("bytes", 1.0), ("temp_bytes", TEMP_TOLERANCE_FACTOR),
    ("est_flops", 1.0), ("est_bytes", 1.0), ("comm_bytes", 1.0),
)


def _cost_findings(name: str, live: Mapping[str, Any],
                   base: Mapping[str, Any], tol: float,
                   path: str, line: int) -> List[Finding]:
    """DP301: the worst relative cost growth across the metrics both sides
    carry, beyond tolerance; names the dominant regressing primitive."""
    lcost, bcost = live.get("cost", {}), base.get("cost", {})
    worst = None
    for metric, widen in _COST_METRICS:
        lv, bv = lcost.get(metric), bcost.get(metric)
        if lv is None or bv is None or float(bv) <= 0:
            continue
        rel = float(lv) / float(bv) - 1.0
        eff_tol = tol * widen
        if rel > eff_tol and (worst is None or rel > worst[1]):
            worst = (metric, rel, float(bv), float(lv), eff_tol)
    if worst is None:
        return []
    metric, rel, bv, lv, eff_tol = worst
    if metric == "comm_bytes":
        # a comm regression names its dominant collective, from the comms
        # tier's per-collective breakdown, not the flop table
        lprims = live.get("comm", {}) or {}
        bprims = base.get("comm", {}) or {}
        unit = "comm bytes"
        kind = "collective"
    else:
        lprims = live.get("primitives", {}) or {}
        bprims = base.get("primitives", {}) or {}
        unit = "est flops"
        kind = "primitive"
    deltas = sorted(
        ((p, float(lprims.get(p, 0.0)) - float(bprims.get(p, 0.0)))
         for p in set(lprims) | set(bprims)),
        key=lambda kv: (-kv[1], kv[0]))
    dom = ""
    if deltas and deltas[0][1] > 0:
        dom = (f"; dominant {kind} increase: {deltas[0][0]} "
               f"(+{_fmt_count(deltas[0][1])} {unit})")
    return [Finding(
        path=path, line=line, col=1, rule_id="DP301",
        message=f"[{name}] {metric} regressed {100.0 * rel:.1f}% over the "
                f"baseline ({_fmt_count(bv)} -> {_fmt_count(lv)}; "
                f"tolerance {100.0 * eff_tol:.0f}%){dom} — a perf "
                "regression, or a baseline missing its --baseline update")]


def _iface_findings(name: str, live: Mapping[str, Any],
                    base: Mapping[str, Any], path: str,
                    line: int) -> List[Finding]:
    li, bi = live.get("interface", {}), base.get("interface", {})
    if li.get("sha") == bi.get("sha"):
        return []
    drifted = []
    for side in ("inputs", "outputs"):
        ls, bs = li.get(side, {}), bi.get(side, {})
        if ls.get("sha") != bs.get("sha") or ls.get("count") != bs.get("count"):
            drifted.append(
                f"{side} {bs.get('count', '?')} leaf/leaves "
                f"{', '.join(bs.get('head', [])) or '?'}... -> "
                f"{ls.get('count', '?')} {', '.join(ls.get('head', [])) or '?'}...")
    if li.get("donated") != bi.get("donated"):
        drifted.append(f"donated args {bi.get('donated')} -> "
                       f"{li.get('donated')}")
    return [Finding(
        path=path, line=line, col=1, rule_id="DP304",
        message=f"[{name}] interface drifted with an UNCHANGED program "
                f"fingerprint ({'; '.join(drifted) or 'aval metadata'}) — "
                "this poisons an AOT executable cache keyed on the "
                "fingerprint; regenerate the baseline and audit the caller")]


def _implied_buckets(base_name: str, live_names: Iterable[str],
                     ladders: Mapping[str, int]) -> Optional[int]:
    """Bucket count the program set implies for a wrapped entry point: an
    explicitly registered ladder wins; otherwise the `name[...]` variant
    count in the registry. None = nothing implied (unbucketed program)."""
    if base_name in ladders:
        return int(ladders[base_name])
    variants = [n for n in live_names
                if n.startswith(base_name + "[") and n.endswith("]")]
    return len(variants) or None


def check_entrypoints(
        eps: Iterable[EntryPoint],
        data: Mapping[str, Any],
        budgets: Optional[Mapping[str, Optional[int]]] = None,
        ladders: Optional[Mapping[str, int]] = None,
        compiled: bool = True,
        select: Optional[Sequence[str]] = None,
        allow: Optional[Dict[str, Dict[str, str]]] = None) -> List[Finding]:
    """Diff the live program set against the baseline: DP300-DP304.
    `budgets`/`ladders` feed DP303 (from `entrypoints.declared_budgets()` /
    `bucket_ladders()`); `compiled=False` skips XLA compilation and
    compares the jaxpr-walk estimates only (the fast in-test mode)."""
    entries: Mapping[str, Any] = data.get("entries", {})
    live: Dict[str, Dict[str, Any]] = {}
    findings: List[Finding] = []
    anchors: Dict[str, Tuple[str, int]] = {}
    for ep in eps:
        snap, errs = snapshot_entrypoint(ep, compiled=compiled)
        findings.extend(errs)
        if snap is None:
            continue
        anchors[ep.name] = (snap.pop("_path"), snap.pop("_line"))
        live[ep.name] = snap

    for name in sorted(set(live) - set(entries)):
        path, line = anchors.get(name, ("<entrypoint>", 1))
        findings.append(Finding(
            path=path, line=line, col=1, rule_id="DP302",
            message=f"[{name}] entry point is registered in production but "
                    "missing from the baseline — regenerate with "
                    "--baseline update so the program set stays covered"))
    for name in sorted(set(entries) - set(live)):
        findings.append(Finding(
            path="<baseline>", line=1, col=1, rule_id="DP302",
            message=f"[{name}] entry point exists in the baseline but is "
                    "no longer registered — removed program, or a "
                    "registration hole; regenerate with --baseline update"))

    for name in sorted(set(live) & set(entries)):
        l, b = live[name], entries[name]
        path, line = anchors.get(name, ("<entrypoint>", 1))
        if l.get("fingerprint") != b.get("fingerprint"):
            findings.append(Finding(
                path=path, line=line, col=1, rule_id="DP300",
                message=f"[{name}] program fingerprint drifted "
                        f"({b.get('fingerprint', '?')} -> "
                        f"{l.get('fingerprint', '?')}) but the baseline "
                        "still records the old program — regenerate with "
                        "--baseline update in the same PR"))
        else:
            findings.extend(_iface_findings(name, l, b, path, line))
        findings.extend(_cost_findings(
            name, l, b, tolerance_for(name, data), path, line))

    for base_name in sorted(budgets or {}):
        budget = (budgets or {})[base_name]
        if budget is None:
            continue
        implied = _implied_buckets(base_name, live, ladders or {})
        if implied is None or int(budget) == implied:
            continue
        path, line = ("<entrypoint>", 1)
        for cand in ([base_name]
                     + [n for n in sorted(live)
                        if n.startswith(base_name + "[")]):
            if cand in anchors:
                path, line = anchors[cand]
                break
        findings.append(Finding(
            path=path, line=line, col=1, rule_id="DP303",
            message=f"[{base_name}] declared recompile_budget {budget} but "
                    f"the registered program set implies {implied} "
                    "bucket(s) — the watchdog budget and the bucket "
                    "ladder drifted apart"))

    out: List[Finding] = []
    for f in findings:
        name = f.message.split("]", 1)[0].lstrip("[")
        if select is not None and f.rule_id not in select:
            continue
        if allowed(name, f.rule_id, allow):
            continue
        if program_mod._suppressed_in_source(f.path, f.line, f.rule_id):
            continue
        out.append(f)
    return sorted(out)


def check_summary(findings: List[Finding], entries: int,
                  data: Mapping[str, Any],
                  path: pathlib.Path) -> Dict[str, Any]:
    """The machine-readable check result (`--baseline-report` writes it as
    `baseline_check.json`; the report CLI renders it)."""
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule_id] = by_rule.get(f.rule_id, 0) + 1
    # bandwidth profile: the heaviest entries by estimated boundary bytes
    # with their arithmetic intensity (flops/byte) — the roofline column
    # the report renders, so kernel-tier traffic reductions are visible
    # without opening baselines.json
    intensity = []
    for name, e in data.get("entries", {}).items():
        cost = e.get("cost", {}) or {}
        fl, by = cost.get("est_flops"), cost.get("est_bytes")
        if fl is None or by is None:
            continue
        intensity.append({
            "name": name, "est_flops": float(fl), "est_bytes": float(by),
            "est_ai": float(cost.get("est_ai",
                                     float(fl) / max(float(by), 1.0))),
        })
    intensity.sort(key=lambda r: (-r["est_bytes"], r["name"]))
    # mixed-precision rollup: every `.bf16`-tagged entry against its f32
    # twin (same name minus the tag); the report renders the aggregate
    # predicted-HBM ratio so the bf16 bank's bandwidth win — the invariant
    # the certify smoke gate enforces per entry — is visible at a glance
    bf16_bytes = f32_bytes = 0.0
    paired = 0
    all_entries = data.get("entries", {})
    for name, e in all_entries.items():
        if ".bf16" not in name:
            continue
        twin = all_entries.get(name.replace(".bf16", ""))
        if twin is None:
            continue
        by = (e.get("cost", {}) or {}).get("est_bytes")
        twin_by = (twin.get("cost", {}) or {}).get("est_bytes")
        if by is None or twin_by is None:
            continue
        paired += 1
        bf16_bytes += float(by)
        f32_bytes += float(twin_by)
    dtype_bytes = None
    if paired:
        dtype_bytes = {"paired_entries": paired,
                       "bf16_bytes": bf16_bytes, "f32_bytes": f32_bytes,
                       "ratio": round(bf16_bytes / f32_bytes, 4)
                       if f32_bytes else None}
    return {
        "entries": entries,
        "baseline_file": str(path),
        "baseline_entries": len(data.get("entries", {})),
        "fingerprint_set": fingerprint_set_hash(data.get("entries", {})),
        "clean": not findings,
        "findings_by_rule": dict(sorted(by_rule.items())),
        "findings": [
            {"rule": f.rule_id, "path": f.path, "line": f.line,
             "message": f.message} for f in findings],
        "intensity": intensity[:8],
        "dtype_bytes": dtype_bytes,
    }
