"""Concurrency analysis tier: lock-discipline rules DP500-DP504.

The platform's host side is heavily threaded — the replica-pool supervisor,
the shared micro-batcher, heartbeat daemons, farm lease contention, the
metrics registry — and every threading bug shipped so far was found the
hard way at runtime (PR 11's telemetry-call-strands-a-replica race, PR 16's
wall-clock lease skew). This module is the "find the bug class before the
chip does" philosophy applied to host concurrency: a stdlib-only,
intraprocedural AST pass over the threaded packages (`serve/`, `farm/`,
`observe/`, `recert/`, `gateway/`, `backoff.py`, `chaos.py`), registered
in the same
engine as DP1xx so findings ride the standard `--select` / `# noqa: DP5xx`
/ exit-code machinery (and the default lint gate), plus a dedicated
`--concurrency` CLI mode that runs only this wing.

Rules:

- **DP500 guarded-state violation** — a mutable instance attribute declares
  its lock with a trailing `# guarded-by: self._lock` comment on its
  assignment line (normally in `__init__`); any mutation of that attribute
  outside a `with self._lock:` block in any other method of the class is a
  finding. The annotation is the contract; the rule proves it.
- **DP501 lock-order cycle** — the per-class and cross-class lock
  acquisition graph is built from nested `with`-statements (lock-like
  context expressions, keyed by their final attribute name so an ABBA
  inversion across two classes still closes the cycle); any cycle is a
  potential deadlock, reported once per strongly connected component with
  the canonical (alphabetical) order in the message.
- **DP502 blocking call while holding a lock** — `time.sleep`, thread
  `join`, `socket.*`/HTTP-client/`subprocess` calls, untimed `.wait()`,
  and untimed queue `get`/`put` inside a `with <lock>` body: the exact
  shape of the PR 11 stranded-replica bug, now pre-run.
- **DP503 thread-lifecycle hygiene** — a non-daemon `threading.Thread`
  with no `join` on the owning object's `stop()`/`close()` path (or, for a
  function-local thread, none in its creating function), and any thread
  `start()`ed inside `__init__` before every `guarded-by` attribute of the
  class has been assigned (the thread observes a half-built object).
- **DP504 wall-clock liveness** — a `time.time()`-derived value (including
  injected `clock=time.time` defaults and `self._clock = clock` rebinds)
  compared against a ttl/deadline/expiry/staleness bound. A stepped or
  skewed wall clock flips the liveness decision — the PR 16 lease-skew bug
  class, generalized; liveness wants `time.monotonic()` or a seq-based
  freshness check.

All five rules are intraprocedural and deliberately conservative: locks
taken via bare `.acquire()`/`.release()` pairs, cross-file lock nesting,
and closures executed on other threads are out of scope (documented, not
guessed at). Like the rest of the AST wing this module is stdlib-only
(ast + tokenize) — linting never initializes a jax backend.

`static_lock_graph()` exposes the DP501 acquisition graph for the runtime
wing (`analysis/lockwatch.py`), which cross-checks the order actually
observed under `--sanitize` against the statically proven one.
"""

from __future__ import annotations

import ast
import fnmatch
import io
import pathlib
import re
import tokenize
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple, Union

from dorpatch_tpu.analysis.engine import (FileContext, Finding, Rule,
                                          dotted_name, iter_python_files,
                                          register)

#: The wing's stable rule IDs (CLI `--concurrency` select set).
CONCURRENCY_RULE_IDS = ("DP500", "DP501", "DP502", "DP503", "DP504")

#: Logical-path glob -> {rule_id: reason}: the file-level analog of a
#: `# noqa:` comment, for files whose offense has no single ownable line
#: (mirrors `analysis.program.ALLOWLIST`). Shipped entries must carry their
#: reason; everything else found in the shipped tree is FIXED or carries a
#: line-level `# noqa: DP5xx <reason>`.
ALLOWLIST: Dict[str, Dict[str, str]] = {}

_SCOPE_DIRS = ("serve", "farm", "observe", "recert", "gateway")
_SCOPE_FILES = ("backoff.py", "chaos.py")

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*self\.(\w+)")
_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
}
_LOCKISH_RE = re.compile(r"lock|mutex|cond(?:ition)?$", re.IGNORECASE)
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "update", "add", "setdefault", "sort", "reverse", "write",
}
_LIFECYCLE_METHODS = {"stop", "close", "shutdown", "join", "terminate",
                      "wedge", "drain", "__exit__", "__del__"}
_BLOCKING_EXACT = {
    "time.sleep", "select.select", "signal.pause",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
}
_BLOCKING_PREFIXES = ("socket.", "requests.", "urllib.request.",
                      "http.client.")
_LIVENESS_RE = re.compile(
    r"ttl|deadline|expir|stale|liveness", re.IGNORECASE)
_WALL_CLOCKS = {"time.time"}


def in_concurrency_scope(ctx: FileContext) -> bool:
    """True for files in the threaded packages this tier audits."""
    if not ctx.in_package():
        return False
    sp = ctx.scoped_parts
    if not sp:
        return False
    return sp[0] in _SCOPE_DIRS or (len(sp) == 1 and sp[0] in _SCOPE_FILES)


def allowlisted(rule_id: str, logical_path: str) -> Optional[str]:
    """The ALLOWLIST reason granting `rule_id` for this file, or None."""
    path = pathlib.PurePath(logical_path).as_posix()
    for pattern, rules in ALLOWLIST.items():
        if rule_id in rules and fnmatch.fnmatch(path, pattern):
            return rules[rule_id]
    return None


# ---------------- shared AST helpers ----------------


def _guard_annotations(source: str) -> Dict[int, str]:
    """line -> lock attribute name, from `# guarded-by: self.<lock>`."""
    out: Dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _GUARDED_RE.search(tok.string)
            if m:
                out[tok.start[0]] = m.group(1)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def _self_attr(node: ast.AST) -> Optional[str]:
    """`x` for a `self.x` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _guarded_attrs(cls: ast.ClassDef,
                   annotations: Dict[int, str]) -> Dict[str, str]:
    """attr -> declared lock attr, for one class's guarded-by lines."""
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        lock = annotations.get(node.lineno)
        if lock is None:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                out[attr] = lock
    return out


def _lock_names(ctx: FileContext) -> Set[str]:
    """Final-component names assigned from a threading lock factory."""
    names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not (isinstance(value, ast.Call)
                and ctx.resolve(value.func) in _LOCK_FACTORIES):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            name = dotted_name(t)
            if name:
                names.add(name.rpartition(".")[2])
    return names


def _lockish(name: str, known: Set[str]) -> bool:
    return name in known or bool(_LOCKISH_RE.search(name))


def _with_locks(stmt: Union[ast.With, ast.AsyncWith], known: Set[str]
                ) -> List[Tuple[str, str]]:
    """(key, spelling) for each lock-like context expression, in order.

    Keys are the FINAL attribute/name component so `self._lock` in class A
    and `pool._lock` in class B land on the same graph node — the only way
    an intraprocedural pass can close a cross-class ABBA cycle."""
    out: List[Tuple[str, str]] = []
    for item in stmt.items:
        spelling = dotted_name(item.context_expr)
        if spelling is None:
            continue
        key = spelling.rpartition(".")[2]
        if _lockish(key, known):
            out.append((key, spelling))
    return out


def _body_lists(stmt: ast.stmt) -> List[List[ast.stmt]]:
    """The nested statement lists of a compound statement (empty for a
    simple one)."""
    out: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if val and isinstance(val[0], ast.stmt):
            out.append(val)
    for handler in getattr(stmt, "handlers", []) or []:
        out.append(handler.body)
    for case in getattr(stmt, "cases", []) or []:
        out.append(case.body)
    return out


def _guard_exprs(stmt: ast.stmt) -> List[ast.expr]:
    """Expressions a compound statement evaluates itself (its test/iter)."""
    out: List[ast.expr] = []
    for field in ("test", "iter", "subject"):
        val = getattr(stmt, field, None)
        if isinstance(val, ast.expr):
            out.append(val)
    return out


def _walk_expr(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk minus Lambda bodies (deferred code runs on another
    thread's schedule; proving anything about it here would be a guess)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, ast.Lambda):
                stack.append(child)


def _functions(tree: ast.AST) -> Iterator[Tuple[Optional[ast.ClassDef],
                                                ast.FunctionDef]]:
    """(owning class or None, function) for every def in the module,
    including methods; nested defs are yielded with their own scope."""
    def visit(node: ast.AST, cls: Optional[ast.ClassDef]) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield cls, child
                yield from visit(child, cls)
            else:
                yield from visit(child, cls)
    yield from visit(tree, None)


def _scan_scopes(stmts: Sequence[ast.stmt], held: Tuple[str, ...],
                 known: Set[str]
                 ) -> Iterator[Tuple[ast.AST, Tuple[str, ...], bool]]:
    """Linear walk of one function body yielding (node, held-lock keys,
    is_statement). Compound statements yield their guard expressions with
    is_statement=False and recurse; nested defs are skipped (their bodies
    run under a different call's lock state)."""
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(s, (ast.With, ast.AsyncWith)):
            acquired = _with_locks(s, known)
            inner = list(held)
            for key, _ in acquired:
                if key not in inner:
                    inner.append(key)
            yield from _scan_scopes(s.body, tuple(inner), known)
            continue
        bodies = _body_lists(s)
        if bodies:
            for e in _guard_exprs(s):
                yield e, held, False
            for b in bodies:
                yield from _scan_scopes(b, held, known)
        else:
            yield s, held, True


def _mutated_attrs(node: ast.AST, is_statement: bool
                   ) -> Iterator[Tuple[str, ast.AST]]:
    """(self-attr, site) for every mutation the node performs: assignment
    / augmented assignment / deletion targeting `self.x` (or a subscript
    of it), and mutating method calls like `self.x.append(...)`."""
    targets: List[ast.expr] = []
    if is_statement:
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
    flat: List[ast.expr] = []
    while targets:
        t = targets.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            targets.extend(t.elts)
        elif isinstance(t, ast.Starred):
            targets.append(t.value)
        else:
            flat.append(t)
    for t in flat:
        attr = _self_attr(t)
        if attr is None and isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
        if attr is not None:
            yield attr, t
    for sub in _walk_expr(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _MUTATORS):
            attr = _self_attr(sub.func.value)
            if attr is not None:
                yield attr, sub
        # a subscript store buried in an expression statement
        # (e.g. `self.x[k] = v` handled above; `self.x[k] += 1` arrives
        # as AugAssign with a Subscript target, also handled above)


class _ConcurrencyRule(Rule):
    """Shared scope gate: DP5xx rules only audit the threaded packages."""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not in_concurrency_scope(ctx):
            return
        if allowlisted(self.id, ctx.logical_path) is not None:
            return
        yield from self._check(ctx)

    def _check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


@register
class GuardedStateRule(_ConcurrencyRule):
    id = "DP500"
    name = "guarded-state-violation"
    description = ("attribute declared `# guarded-by: self.<lock>` mutated "
                   "outside a `with self.<lock>` block")

    def _check(self, ctx: FileContext) -> Iterator[Finding]:
        annotations = _guard_annotations(ctx.source)
        if not annotations:
            return
        known = _lock_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            guarded = _guarded_attrs(node, annotations)
            if not guarded:
                continue
            for fn in node.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__":
                    # construction happens-before every reader thread; the
                    # publish-a-half-built-object hazard is DP503's check
                    continue
                seen: Set[Tuple[int, int]] = set()
                for sub, held, is_stmt in _scan_scopes(fn.body, (), known):
                    for attr, site in _mutated_attrs(sub, is_stmt):
                        lock = guarded.get(attr)
                        if lock is None or lock in held:
                            continue
                        key = (site.lineno, site.col_offset)
                        if key in seen:
                            continue
                        seen.add(key)
                        yield self.finding(
                            ctx, site,
                            f"{node.name}.{attr} is declared `# guarded-by: "
                            f"self.{lock}` but {fn.name}() mutates it "
                            f"outside `with self.{lock}`")


def _file_lock_graph(ctx: FileContext
                     ) -> Tuple[Dict[str, Set[str]],
                                Dict[Tuple[str, str], ast.AST]]:
    """(edges, first acquisition site per edge) from nested with-blocks."""
    known = _lock_names(ctx)
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], ast.AST] = {}

    def walk(stmts: Sequence[ast.stmt], held: Tuple[str, ...]) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                walk(s.body, ())
                continue
            if isinstance(s, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for key, _ in _with_locks(s, known):
                    for h in inner:
                        if h != key:
                            edges.setdefault(h, set()).add(key)
                            sites.setdefault((h, key), s)
                    if key not in inner:
                        inner.append(key)
                walk(s.body, tuple(inner))
                continue
            for b in _body_lists(s):
                walk(b, held)

    walk(ctx.tree.body, ())  # type: ignore[attr-defined]
    return edges, sites


def _cyclic_sccs(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components with a cycle (size > 1, or a
    self-loop), via iterative Tarjan — the graph is a handful of locks."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]
    nodes = sorted(set(edges) | {v for vs in edges.values() for v in vs})

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(edges.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in edges.get(v, ()):
                    sccs.append(sorted(comp))

    for n in nodes:
        if n not in index:
            strongconnect(n)
    return sccs


@register
class LockOrderRule(_ConcurrencyRule):
    id = "DP501"
    name = "lock-order-cycle"
    description = ("nested `with` blocks acquire locks in conflicting "
                   "orders (potential deadlock)")

    def _check(self, ctx: FileContext) -> Iterator[Finding]:
        edges, sites = _file_lock_graph(ctx)
        for scc in _cyclic_sccs(edges):
            members = set(scc)
            internal = sorted(
                ((site.lineno, site.col_offset), a, b)
                for (a, b), site in sites.items()
                if a in members and b in members)
            if not internal:
                continue
            (line, col), a, b = internal[0]
            site = sites[(a, b)]
            cycle = " -> ".join(scc + [scc[0]])
            canonical = " < ".join(scc)
            yield self.finding(
                ctx, site,
                f"lock-order cycle {cycle}: nested `with` blocks acquire "
                f"these locks in conflicting orders — a potential "
                f"deadlock; pick the canonical order {canonical} and "
                f"acquire in that order everywhere")


def _call_receiver(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return dotted_name(call.func.value)
    return None


def _has_timeout(call: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    return bool(call.args)


@register
class BlockingUnderLockRule(_ConcurrencyRule):
    id = "DP502"
    name = "blocking-call-under-lock"
    description = ("sleep/join/socket/HTTP/untimed-wait call inside a "
                   "`with <lock>` body")

    def _blocking_reason(self, ctx: FileContext, call: ast.Call,
                         known: Set[str]) -> Optional[str]:
        resolved = ctx.resolve(call.func)
        if resolved is not None:
            if resolved in _BLOCKING_EXACT:
                return f"{resolved}()"
            if resolved.startswith(_BLOCKING_PREFIXES):
                return f"{resolved}()"
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        receiver = dotted_name(call.func.value)
        if attr == "join":
            # str.join / os.path.join are pure; everything else named
            # .join in a lock body is a thread/process rendezvous
            if isinstance(call.func.value, ast.Constant):
                return None
            if resolved is not None and (
                    resolved.startswith("os.path.")
                    or ".path." in resolved or resolved.startswith("str.")):
                return None
            if receiver is None:
                return None
            return f"{receiver}.join()"
        if attr == "wait" and not _has_timeout(call):
            target = receiver or "<expr>"
            return f"{target}.wait() without a timeout"
        if attr in ("get", "put") and receiver is not None:
            last = receiver.rpartition(".")[2].lower()
            if "queue" in last and not _has_timeout(call):
                return f"{receiver}.{attr}() without a timeout"
        return None

    def _check(self, ctx: FileContext) -> Iterator[Finding]:
        known = _lock_names(ctx)
        seen: Set[Tuple[int, int]] = set()
        for _, fn in _functions(ctx.tree):
            for sub, held, _ in _scan_scopes(fn.body, (), known):
                if not held:
                    continue
                for node in _walk_expr(sub):
                    if not isinstance(node, ast.Call):
                        continue
                    reason = self._blocking_reason(ctx, node, known)
                    if reason is None:
                        continue
                    key = (node.lineno, node.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    locks = ", ".join(held)
                    yield self.finding(
                        ctx, node,
                        f"blocking call {reason} while holding {locks}: "
                        f"every other thread contending for the lock "
                        f"stalls behind it (the PR 11 stranded-replica "
                        f"shape)")


def _thread_call(ctx: FileContext, node: ast.AST) -> Optional[ast.Call]:
    if (isinstance(node, ast.Call)
            and ctx.resolve(node.func) == "threading.Thread"):
        return node
    return None


def _is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return (isinstance(kw.value, ast.Constant)
                    and bool(kw.value.value))
    return False


def _joins_in(node: ast.AST) -> Set[str]:
    """Dotted receivers of `.join(...)` calls anywhere under `node`."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "join"):
            receiver = dotted_name(sub.func.value)
            if receiver:
                out.add(receiver)
    return out


@register
class ThreadLifecycleRule(_ConcurrencyRule):
    id = "DP503"
    name = "thread-lifecycle-hygiene"
    description = ("non-daemon thread never joined on stop()/close(), or "
                   "thread started in __init__ before guarded state is "
                   "assigned")

    def _check(self, ctx: FileContext) -> Iterator[Finding]:
        annotations = _guard_annotations(ctx.source)
        for cls, fn in _functions(ctx.tree):
            yield from self._check_nondaemon(ctx, cls, fn)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_init_start(ctx, node, annotations)

    def _check_nondaemon(self, ctx: FileContext,
                         cls: Optional[ast.ClassDef],
                         fn: ast.FunctionDef) -> Iterator[Finding]:
        local_joins = _joins_in(fn)
        class_joins: Set[str] = set()
        if cls is not None:
            for m in cls.body:
                if (isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and m.name in _LIFECYCLE_METHODS):
                    class_joins |= _joins_in(m)
        starts = {dotted_name(s.func.value)
                  for s in ast.walk(fn)
                  if isinstance(s, ast.Call)
                  and isinstance(s.func, ast.Attribute)
                  and s.func.attr == "start"
                  and dotted_name(s.func.value)}
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign):
                call = _thread_call(ctx, stmt.value)
                if call is None or _is_daemon(call):
                    continue
                for t in stmt.targets:
                    name = dotted_name(t)
                    if name is None:
                        continue
                    if name.startswith("self."):
                        if name in class_joins or name in local_joins:
                            continue
                        owner = cls.name if cls else "<module>"
                        yield self.finding(
                            ctx, call,
                            f"non-daemon thread {name} is never joined on "
                            f"a {owner} stop()/close() path — process "
                            f"exit and test teardown will hang on it")
                    else:
                        if name in local_joins or name not in starts:
                            continue
                        yield self.finding(
                            ctx, call,
                            f"non-daemon thread {name} is start()ed in "
                            f"{fn.name}() but never joined there")
            elif (isinstance(stmt, ast.Expr)
                  and isinstance(stmt.value, ast.Call)
                  and isinstance(stmt.value.func, ast.Attribute)
                  and stmt.value.func.attr == "start"):
                call = _thread_call(ctx, stmt.value.func.value)
                if call is not None and not _is_daemon(call):
                    yield self.finding(
                        ctx, call,
                        "anonymous non-daemon thread start()ed with no "
                        "reference left to join")

    def _check_init_start(self, ctx: FileContext, cls: ast.ClassDef,
                          annotations: Dict[int, str]) -> Iterator[Finding]:
        guarded = _guarded_attrs(cls, annotations)
        init = next((m for m in cls.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None or not guarded:
            return
        thread_locals: Set[str] = set()
        first_start: Optional[ast.Call] = None
        for stmt in ast.walk(init):
            if isinstance(stmt, ast.Assign):
                if _thread_call(ctx, stmt.value) is not None:
                    for t in stmt.targets:
                        name = dotted_name(t)
                        if name:
                            thread_locals.add(name)
        for node in ast.walk(init):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "start"):
                receiver = dotted_name(node.func.value)
                if receiver in thread_locals or _thread_call(
                        ctx, node.func.value) is not None:
                    if first_start is None or node.lineno < first_start.lineno:
                        first_start = node
        if first_start is None:
            return
        late = sorted(
            attr for node in ast.walk(init)
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign))
            and node.lineno > first_start.lineno
            for attr in {a for a, _ in _mutated_attrs(node, True)}
            if attr in guarded)
        if late:
            yield self.finding(
                ctx, first_start,
                f"thread started in {cls.name}.__init__ before guarded "
                f"attribute(s) {', '.join(late)} are assigned — the "
                f"thread can observe a half-built object")


def _wall_clock_names(ctx: FileContext) -> Tuple[Set[str], Set[str]]:
    """(parameter names, self attrs) bound to time.time in this file:
    `def __init__(..., clock=time.time)` plus `self._clock = clock`."""
    params: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
            if ctx.resolve(default) in _WALL_CLOCKS:
                params.add(arg.arg)
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and ctx.resolve(default) in _WALL_CLOCKS:
                params.add(arg.arg)
    attrs: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        from_param = (isinstance(value, ast.Name) and value.id in params)
        direct = ctx.resolve(value) in _WALL_CLOCKS
        if not (from_param or direct):
            continue
        for t in node.targets:
            attr = _self_attr(t)
            if attr is not None:
                attrs.add(attr)
    return params, attrs


@register
class WallClockLivenessRule(_ConcurrencyRule):
    id = "DP504"
    name = "wall-clock-liveness"
    description = ("time.time()-derived value compared against a "
                   "ttl/deadline — liveness wants time.monotonic()")

    def _is_wall_call(self, ctx: FileContext, node: ast.AST,
                      params: Set[str], attrs: Set[str]) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if ctx.resolve(node.func) in _WALL_CLOCKS:
            return True
        if isinstance(node.func, ast.Name) and node.func.id in params:
            return True
        attr = _self_attr(node.func)
        return attr is not None and attr in attrs

    def _check(self, ctx: FileContext) -> Iterator[Finding]:
        params, attrs = _wall_clock_names(ctx)
        for _, fn in _functions(ctx.tree):
            tainted: Set[str] = set()
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign)
                        and self._is_wall_call(ctx, node.value, params,
                                               attrs)):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Compare):
                    continue
                sides = [node.left] + list(node.comparators)
                wall = any(
                    self._is_wall_call(ctx, sub, params, attrs)
                    or (isinstance(sub, ast.Name) and sub.id in tainted)
                    for side in sides for sub in ast.walk(side))
                if not wall:
                    continue
                words: List[str] = []
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        words.append(sub.id)
                    elif isinstance(sub, ast.Attribute):
                        words.append(sub.attr)
                    elif (isinstance(sub, ast.Constant)
                          and isinstance(sub.value, str)):
                        words.append(sub.value)
                if not any(_LIVENESS_RE.search(w) for w in words):
                    continue
                yield self.finding(
                    ctx, node,
                    "wall-clock liveness: a time.time()-derived value is "
                    "compared against a ttl/deadline; a stepped or skewed "
                    "wall clock flips the decision (the PR 16 lease-skew "
                    "class) — use time.monotonic() or a seq-based "
                    "freshness check")


# ---------------- static graph export (runtime lockwatch) ----------------


def static_lock_graph(paths: Optional[Sequence[Union[str, pathlib.Path]]]
                      = None) -> Dict[str, Set[str]]:
    """The merged DP501 acquisition graph over `paths` (default: the
    installed dorpatch_tpu package), keyed by final lock-attribute name.
    The runtime lockwatch (`analysis/lockwatch.py`) cross-checks the order
    it actually observes against this statically proven order."""
    if paths is None:
        paths = [pathlib.Path(__file__).resolve().parents[1]]
    merged: Dict[str, Set[str]] = {}
    for f in iter_python_files(paths):
        try:
            ctx = FileContext(str(f), f.read_text(encoding="utf-8"))
        except (OSError, UnicodeDecodeError, SyntaxError):
            continue
        if not in_concurrency_scope(ctx):
            continue
        edges, _ = _file_lock_graph(ctx)
        for a, bs in edges.items():
            merged.setdefault(a, set()).update(bs)
    return merged
