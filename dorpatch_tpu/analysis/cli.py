"""Analysis CLI: `python -m dorpatch_tpu.analysis [paths...]`.

Six modes behind one exit contract (0 = clean, 1 = findings, 2 = usage
error; `run_tests.sh` gates on it):

- **Lint** (default): the AST rules (DP101-DP108 plus the concurrency
  wing DP500-DP504) over the package and tools — pure ast/tokenize
  logic, never initializes a jax backend.
- **Concurrency** (`--concurrency`): ONLY the lock-discipline rules
  (DP500-DP504) over the threaded packages — the same findings the
  default lint gate folds in, isolated for CI labelling and focused
  local runs.
- **Trace** (`--trace`): the jaxpr-level auditor (DP200-DP206) over every
  registered production jit entry point, abstractly traced on CPU
  (`JAX_PLATFORMS=cpu`; zero device FLOPs). This mode imports jax and the
  production modules — it is the one analysis mode that is not
  backend-neutral to *import*, which is why it is opt-in.
- **Comms** (`--comms`): the sharding & collectives auditor (DP600-DP603)
  over the same entry points `--trace` audits — statically priced
  collective inventories, accidental replication, boundary reshards, and
  the shard-local kernel proof. Imports jax like `--trace`; run it under
  `XLA_FLAGS=--xla_force_host_platform_device_count=8` so the `.mesh`
  program bank enumerates.
- **Baseline** (`--baseline check|update`): the program-baseline tier
  (DP300-DP304) — fingerprints + static cost vectors for every registered
  entry point, diffed against the checked-in `analysis/baselines.json`
  (`check`, the gate) or regenerated deterministically (`update`, run in
  the same PR as any intentional program change). `--baseline-cost
  estimate` skips XLA compilation and compares the jaxpr-walk estimates
  only (fast; the compiled flops/bytes/temp columns go unchecked).
- **Fix** (`--fix [--diff]`): applies the mechanical DP106 rewriter
  (`fix.py`); `--diff` prints the unified diff without writing.

Output: one `path:line:col: DPxxx message` line per finding on stdout
(`--format json` swaps in one JSON object per line for CI and the report
tooling; `--format sarif` emits one SARIF 2.1.0 document over the whole
finding set — all six wings share the serializer); the human summary goes
to stderr so the finding stream stays machine-parseable either way.
"""

from __future__ import annotations

import argparse
import importlib
import json
import pathlib
import sys
from typing import List, Optional

from dorpatch_tpu.analysis.engine import Finding, all_rules, analyze_paths

DEFAULT_PATHS = ["dorpatch_tpu", "tools"]


def default_paths() -> List[str]:
    """The no-args targets, resolved so the installed `dorpatch-lint` script
    works from any cwd: cwd-relative names win (a checkout), otherwise fall
    back to the installed package location (where `tools` may not exist)."""
    root = pathlib.Path(__file__).resolve().parents[2]
    out = []
    for name in DEFAULT_PATHS:
        if pathlib.Path(name).exists():
            out.append(name)
        elif (root / name).exists():
            out.append(str(root / name))
    return out or [str(pathlib.Path(__file__).resolve().parents[1])]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dorpatch_tpu.analysis",
        description="Static analysis for the dorpatch-tpu tree: AST rules "
                    "DP101-DP108 + concurrency rules DP500-DP504 "
                    "(default), the concurrency wing alone "
                    "(--concurrency), the jaxpr-level program auditor "
                    "DP200-DP206 (--trace), the sharding/collectives "
                    "auditor DP600-DP603 (--comms), and the "
                    "program-baseline drift gate DP300-DP304 "
                    "(--baseline); see --list-rules")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: "
                        f"{' '.join(DEFAULT_PATHS)}; ignored under --trace)")
    p.add_argument("--select", default="",
                   help="comma-separated rule IDs to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table (AST + trace) and exit")
    p.add_argument("--fixable", action="store_true",
                   help="list only mechanically fixable offenses")
    p.add_argument("--format", choices=("human", "json", "sarif"),
                   default="human",
                   help="finding output format: human `path:line:col:` "
                        "lines (default), one JSON object per line, or "
                        "one SARIF 2.1.0 document over the whole set")
    p.add_argument("--concurrency", action="store_true",
                   help="run only the lock-discipline rules (DP500-DP504) "
                        "over the target paths — the concurrency gate "
                        "(these rules also run in the default lint mode)")
    p.add_argument("--trace", action="store_true",
                   help="audit the registered jit entry points at the "
                        "jaxpr level (DP2xx) instead of linting source")
    p.add_argument("--comms", action="store_true",
                   help="audit the registered jit entry points for "
                        "sharding/collective hazards (DP600-DP603): "
                        "unpriced collectives, accidental replication, "
                        "boundary reshards, shard-unsafe kernels")
    p.add_argument("--entrypoints", default="",
                   help="--trace/--baseline source override, "
                        "`module:callable` returning a list of EntryPoints "
                        "(default: the production registry)")
    p.add_argument("--baseline", nargs="?", const="check", default=None,
                   choices=("check", "update"), metavar="{check,update}",
                   help="program-baseline mode (DP300-DP304): `check` "
                        "diffs the live fingerprints/costs against the "
                        "checked-in baseline, `update` regenerates it "
                        "deterministically (default mode: check)")
    p.add_argument("--baseline-file", default="",
                   help="baseline file override (default: the package's "
                        "analysis/baselines.json)")
    p.add_argument("--baseline-cost", choices=("compiled", "estimate"),
                   default="compiled",
                   help="cost source for --baseline: `compiled` runs "
                        "XLA's cost_analysis per entry point (the gate "
                        "default), `estimate` compares the pure jaxpr-walk "
                        "estimates only (fast, compile-free)")
    p.add_argument("--baseline-report", default="",
                   help="with --baseline check: also write the machine-"
                        "readable result as baseline_check.json into this "
                        "directory (the telemetry report renders it)")
    p.add_argument("--allow-remove", action="store_true",
                   help="with --baseline update: accept dropping entries "
                        "that exist in the checked-in file but not in the "
                        "regenerated set. Without it, update REFUSES when "
                        "entries would disappear — the usual cause is a "
                        "single-device regeneration silently losing the "
                        ".mesh entries (run under XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8)")
    p.add_argument("--fix", action="store_true",
                   help="apply the DP106 unused-import fixer to the "
                        "target paths (idempotent)")
    p.add_argument("--diff", action="store_true",
                   help="with --fix: print the unified diff, write nothing")
    return p


def _trace_rule_table() -> List[tuple]:
    """(id, fixable, name, description) for the trace rules. program.py
    keeps its jax imports inside rule bodies, so building the table (for
    `--list-rules` / `--select` validation) stays backend-neutral — no
    accelerator is initialized, same contract as the AST wing."""
    from dorpatch_tpu.analysis.program import DP200_ROW, all_trace_rules

    rows = [(r.id, False, r.name, r.description) for r in all_trace_rules()]
    rows.append((DP200_ROW[0], False, DP200_ROW[1], DP200_ROW[2]))
    return rows


def _baseline_rule_table() -> List[tuple]:
    """(id, fixable, name, description) for the baseline rules — like the
    trace table, importable without initializing any jax backend (the
    baseline module keeps its jax imports inside function bodies)."""
    from dorpatch_tpu.analysis.baseline import BASELINE_RULE_ROWS

    return [(rid, False, name, desc) for rid, name, desc in BASELINE_RULE_ROWS]


def _comms_rule_table() -> List[tuple]:
    """(id, fixable, name, description) for the comms rules — comms.py
    keeps its jax imports inside rule bodies, same backend-neutral
    contract as the trace table."""
    from dorpatch_tpu.analysis.comms import all_comms_rules

    return [(r.id, False, r.name, r.description) for r in all_comms_rules()]


def list_rules(out=None) -> None:
    out = out if out is not None else sys.stdout
    rows = [(r.id, r.fixable, r.name, r.description) for r in all_rules()]
    rows += _trace_rule_table()
    rows += _baseline_rule_table()
    rows += _comms_rule_table()
    for rid, fixable, name, description in sorted(rows):
        fix = "fixable" if fixable else "       "
        out.write(f"{rid}  {fix}  {name}: {description}\n")


def sarif_report(findings: List[Finding]) -> str:
    """One SARIF 2.1.0 document over a finding set: the single serializer
    every mode's `--format sarif` goes through, with the rule metadata
    (name/description) of whichever wings the findings reference."""
    meta = {}
    rows = [(r.id, r.name, r.description) for r in all_rules()]
    for rid, _fx, name, desc in (_trace_rule_table() + _baseline_rule_table()
                                 + _comms_rule_table()):
        rows.append((rid, name, desc))
    for rid, name, desc in rows:
        meta.setdefault(rid, (name, desc))
    used = sorted({f.rule_id for f in findings})
    index = {rid: i for i, rid in enumerate(used)}
    rules = [{"id": rid,
              "name": meta.get(rid, (rid, ""))[0] or rid,
              "shortDescription": {
                  "text": meta.get(rid, ("", rid))[1] or rid}}
             for rid in used]
    results = [{
        "ruleId": f.rule_id,
        "ruleIndex": index[f.rule_id],
        "level": "error",
        "message": {"text": f.message},
        "locations": [{"physicalLocation": {
            "artifactLocation": {"uri": f.path},
            "region": {"startLine": max(f.line, 1),
                       "startColumn": max(f.col, 1)}}}],
    } for f in findings]
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": "dorpatch-analysis",
                                "rules": rules}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"


def emit(findings: List[Finding], fmt: str, out=None) -> None:
    out = out if out is not None else sys.stdout
    if fmt == "sarif":
        out.write(sarif_report(findings))
        return
    for f in findings:
        if fmt == "json":
            out.write(json.dumps(
                {"rule": f.rule_id, "path": f.path, "line": f.line,
                 "col": f.col, "message": f.message,
                 "fixable": f.fixable}) + "\n")
        else:
            out.write(f.render() + "\n")


def _parse_select(raw: str, mode: str) -> Optional[List[str]]:
    """Validate --select against the rules of the mode actually running
    (`mode` in lint/trace/baseline): a cross-wing ID (`--select DP201`
    without `--trace`, or `--trace --select DP106`) would run ZERO rules
    and turn a CI gate into a vacuous pass — it must be a loud usage
    error instead."""
    if not raw:
        return None
    select = [s.strip().upper() for s in raw.split(",") if s.strip()]
    from dorpatch_tpu.analysis.baseline import BASELINE_RULE_IDS
    from dorpatch_tpu.analysis.comms import COMMS_RULE_IDS
    from dorpatch_tpu.analysis.concurrency import CONCURRENCY_RULE_IDS
    from dorpatch_tpu.analysis.program import TRACE_RULE_IDS

    wings = {
        "lint": {r.id for r in all_rules()} | {"DP000"},
        "concurrency": set(CONCURRENCY_RULE_IDS) | {"DP000"},
        "trace": set(TRACE_RULE_IDS),
        "comms": set(COMMS_RULE_IDS),
        "baseline": set(BASELINE_RULE_IDS),
    }
    bad = set(select) - wings[mode]
    if bad:
        # Lint rules need the mode flag dropped; trace/baseline rules need
        # theirs added (--baseline outranks --trace, so "add" suffices).
        hints = [(f"{sorted(bad & ids)}: drop --{mode}" if m == "lint"
                  else f"{sorted(bad & ids)}: add --{m}")
                 for m, ids in wings.items()
                 if m != mode and bad & ids]
        hint = f" ({'; '.join(hints)})" if hints else ""
        sys.stderr.write(
            f"rule id(s) not runnable in this mode: {sorted(bad)}{hint}\n")
        return ["<usage-error>"]
    return select


def _run_fix(paths: List[str], diff_only: bool) -> int:
    from dorpatch_tpu.analysis.fix import fix_paths

    files, removed, diffs = fix_paths(paths, write=not diff_only)
    if diff_only:
        for d in diffs:
            sys.stdout.write(d)
    verb = "would remove" if diff_only else "removed"
    sys.stderr.write(
        f"--fix: {verb} {removed} unused import(s) across {files} "
        "file(s)\n" if removed else "--fix: nothing to fix\n")
    return 0


def _load_entrypoints(spec: str):
    """Resolve the audit work list: the `--entrypoints module:callable`
    override, or the production registry. Returns (eps, budgets, ladders,
    uncovered) — budget/ladder ledgers are read AFTER enumeration so a
    custom loader that registers ladders is honored too — or None on a
    bad spec (usage error; message already on stderr)."""
    from dorpatch_tpu.analysis import entrypoints as ep_mod

    if spec:
        mod_name, _, attr = spec.partition(":")
        try:
            loader = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError) as e:
            sys.stderr.write(f"cannot load --entrypoints {spec!r}: {e}\n")
            return None
        ep_mod.clear_entrypoints()  # stale ledgers must not leak into DP303
        eps = list(loader())
        uncovered: List[str] = []
    else:
        eps = ep_mod.production_entrypoints()
        uncovered = ep_mod.uncovered_names()
    return eps, ep_mod.declared_budgets(), ep_mod.bucket_ladders(), uncovered


def _run_trace(select: Optional[List[str]], spec: str,
               fmt: str) -> int:
    from dorpatch_tpu.analysis import program

    loaded = _load_entrypoints(spec)
    if loaded is None:
        return 2
    eps, _, _, uncovered = loaded
    findings = program.audit_entrypoints(eps, select=select,
                                         uncovered=uncovered)
    n_progs = len(eps)
    emit(findings, fmt)
    if findings:
        sys.stderr.write(
            f"{len(findings)} trace finding(s) across {n_progs} entry "
            "point(s). Suppress a deliberate one with `# noqa: DP2xx` on "
            "the program's def line, or a reasoned "
            "analysis.program.ALLOWLIST entry when no source line can "
            "own it.\n")
        return 1
    sys.stderr.write(f"trace audit: {n_progs} entry point(s) clean\n")
    return 0


def _run_comms(select: Optional[List[str]], spec: str, fmt: str) -> int:
    from dorpatch_tpu.analysis import comms

    loaded = _load_entrypoints(spec)
    if loaded is None:
        return 2
    eps, _, _, _ = loaded
    findings = comms.audit_entrypoints(eps, select=select)
    n_progs = len(eps)
    emit(findings, fmt)
    if findings:
        sys.stderr.write(
            f"{len(findings)} comms finding(s) across {n_progs} entry "
            "point(s). Suppress a deliberate one with `# noqa: DP6xx` on "
            "the program's def line, or a reasoned "
            "analysis.comms.ALLOWLIST entry when no source line can own "
            "it.\n")
        return 1
    sys.stderr.write(f"comms audit: {n_progs} entry point(s) clean\n")
    return 0


def _run_baseline(mode: str, select: Optional[List[str]], spec: str,
                  fmt: str, cost: str, file_override: str,
                  report_dir: str, allow_remove: bool = False) -> int:
    from dorpatch_tpu.analysis import baseline

    loaded = _load_entrypoints(spec)
    if loaded is None:
        return 2
    eps, budgets, ladders, _ = loaded
    compiled = cost == "compiled"
    path = (pathlib.Path(file_override) if file_override
            else baseline.baseline_path())

    if mode == "update":
        old = baseline.load_baseline(path)
        mesh_entries = sorted(n for n in (old or {}).get("entries", {})
                              if ".mesh" in n)
        if mesh_entries:
            import jax

            if jax.device_count() < 2:
                # the .mesh program bank (and its comm_bytes vectors) only
                # enumerates on a multi-device topology; writing here would
                # silently strip it and its comm baselines from the gate
                sys.stderr.write(
                    f"--baseline update: {len(mesh_entries)} baselined "
                    ".mesh entry point(s) cannot be enumerated on a "
                    f"{jax.device_count()}-device host (e.g. "
                    f"{mesh_entries[0]}). Re-run under XLA_FLAGS="
                    "--xla_force_host_platform_device_count=8; baseline "
                    "NOT written\n")
                return 2
        data, findings = baseline.build_baseline(eps, compiled=compiled)
        if findings:
            # a baseline with holes would make every later check vacuous
            # exactly where the gate is needed most — refuse to write one
            emit(findings, fmt)
            sys.stderr.write(
                f"--baseline update: {len(findings)} entry point(s) failed "
                "to trace; baseline NOT written\n")
            return 1
        removed = sorted(set((old or {}).get("entries", {}))
                         - set(data.get("entries", {})))
        if removed and not allow_remove:
            # regenerating on the wrong topology (no 8-device virtual
            # mesh) silently drops every .mesh-tagged entry and turns the
            # gate vacuous exactly where it matters — make the shrink loud
            sys.stderr.write(
                f"--baseline update: would drop {len(removed)} baselined "
                f"entry point(s): {', '.join(removed[:5])}"
                + (" ..." if len(removed) > 5 else "") + "\n"
                "Likely cause: regeneration without the baseline's device "
                "topology (run under XLA_FLAGS="
                "--xla_force_host_platform_device_count=8). Pass "
                "--allow-remove if the removal is intentional; baseline "
                "NOT written\n")
            return 1
        text = baseline.dump_baseline(data)
        try:
            unchanged = path.read_text(encoding="utf-8") == text
        except OSError:
            unchanged = False
        path.write_text(text, encoding="utf-8")
        verb = "unchanged" if unchanged else "wrote"
        sys.stderr.write(
            f"--baseline update: {verb} {len(data['entries'])} entry "
            f"point(s) -> {path}\n")
        return 0

    data = baseline.load_baseline(path)
    if data is None:
        sys.stderr.write(f"no readable baseline at {path}; run --baseline "
                         "update first\n")
        return 2
    findings = baseline.check_entrypoints(
        eps, data, budgets=budgets, ladders=ladders, compiled=compiled,
        select=select)
    emit(findings, fmt)
    if report_dir:
        summary = baseline.check_summary(findings, len(eps), data, path)
        rd = pathlib.Path(report_dir)
        rd.mkdir(parents=True, exist_ok=True)
        (rd / "baseline_check.json").write_text(
            json.dumps(summary, indent=2, sort_keys=True) + "\n",
            encoding="utf-8")
    if findings:
        sys.stderr.write(
            f"{len(findings)} baseline finding(s) across {len(eps)} entry "
            "point(s). An intentional program/cost change must land its "
            "`--baseline update` in the same PR; suppress a deliberate "
            "residual with `# noqa: DP3xx` on the program's def line or a "
            "reasoned analysis.baseline.ALLOWLIST entry.\n")
        return 1
    sys.stderr.write(
        f"baseline check: {len(eps)} entry point(s) match "
        f"{path.name} ({len(data.get('entries', {}))} baselined)\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        list_rules()
        return 0
    # --baseline and --comms outrank --trace so `dorpatch-audit --baseline`
    # / `dorpatch-audit --comms` (which prepend --trace) reach their tiers
    mode = ("baseline" if args.baseline
            else "comms" if args.comms
            else "trace" if args.trace
            else "concurrency" if args.concurrency else "lint")
    select = _parse_select(args.select, mode)
    if select == ["<usage-error>"]:
        return 2
    if args.diff and not args.fix:
        sys.stderr.write("--diff requires --fix\n")
        return 2
    if args.fix and (args.trace or args.baseline or args.concurrency
                     or args.comms):
        sys.stderr.write("--fix and --trace/--baseline/--comms/"
                         "--concurrency are separate modes; run them as "
                         "two invocations\n")
        return 2
    if args.concurrency and (args.trace or args.baseline or args.comms):
        sys.stderr.write("--concurrency is a lint-side mode; run it "
                         "separately from --trace/--baseline/--comms\n")
        return 2
    paths = args.paths or default_paths()
    if args.fix:
        return _run_fix(paths, args.diff)
    if args.baseline:
        return _run_baseline(args.baseline, select, args.entrypoints,
                             args.format, args.baseline_cost,
                             args.baseline_file, args.baseline_report,
                             args.allow_remove)
    if args.comms:
        return _run_comms(select, args.entrypoints, args.format)
    if args.trace:
        return _run_trace(select, args.entrypoints, args.format)
    if args.concurrency and select is None:
        from dorpatch_tpu.analysis.concurrency import CONCURRENCY_RULE_IDS
        select = list(CONCURRENCY_RULE_IDS)
    try:
        findings = analyze_paths(paths, select=select)
    except (OSError, UnicodeDecodeError) as e:
        sys.stderr.write(
            f"cannot lint {getattr(e, 'filename', None) or paths}: {e}\n")
        return 2
    if args.fixable:
        findings = [f for f in findings if f.fixable]
    emit(findings, args.format)
    n_fix = sum(1 for f in findings if f.fixable)
    if findings:
        sys.stderr.write(
            f"{len(findings)} finding(s), {n_fix} fixable. Suppress a "
            "deliberate one with `# noqa: DPxxx <reason>`; run --fix for "
            "the fixable ones.\n")
        return 1
    return 0


def audit_main(argv: Optional[List[str]] = None) -> int:
    """`dorpatch-audit` console script: the trace audit as a first-class
    command (`dorpatch-audit` == `python -m dorpatch_tpu.analysis --trace`).
    `dorpatch-audit --baseline [check|update]` reaches the baseline tier
    and `dorpatch-audit --comms` the comms tier: both outrank the
    prepended --trace."""
    return main(["--trace"] + list(argv if argv is not None else sys.argv[1:]))


if __name__ == "__main__":
    sys.exit(main())
