"""Analysis CLI: `python -m dorpatch_tpu.analysis [paths...]`.

Three modes behind one exit contract (0 = clean, 1 = findings, 2 = usage
error; `run_tests.sh` gates on it):

- **Lint** (default): the AST rules (DP101-DP107) over the package and
  tools — pure ast/tokenize logic, never initializes a jax backend.
- **Trace** (`--trace`): the jaxpr-level auditor (DP200-DP206) over every
  registered production jit entry point, abstractly traced on CPU
  (`JAX_PLATFORMS=cpu`; zero device FLOPs). This mode imports jax and the
  production modules — it is the one analysis mode that is not
  backend-neutral to *import*, which is why it is opt-in.
- **Fix** (`--fix [--diff]`): applies the mechanical DP106 rewriter
  (`fix.py`); `--diff` prints the unified diff without writing.

Output: one `path:line:col: DPxxx message` line per finding on stdout
(`--format json` swaps in one JSON object per line for CI and the report
tooling); the human summary goes to stderr so the finding stream stays
machine-parseable either way.
"""

from __future__ import annotations

import argparse
import importlib
import json
import pathlib
import sys
from typing import List, Optional

from dorpatch_tpu.analysis.engine import Finding, all_rules, analyze_paths

DEFAULT_PATHS = ["dorpatch_tpu", "tools"]


def default_paths() -> List[str]:
    """The no-args targets, resolved so the installed `dorpatch-lint` script
    works from any cwd: cwd-relative names win (a checkout), otherwise fall
    back to the installed package location (where `tools` may not exist)."""
    root = pathlib.Path(__file__).resolve().parents[2]
    out = []
    for name in DEFAULT_PATHS:
        if pathlib.Path(name).exists():
            out.append(name)
        elif (root / name).exists():
            out.append(str(root / name))
    return out or [str(pathlib.Path(__file__).resolve().parents[1])]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dorpatch_tpu.analysis",
        description="Static analysis for the dorpatch-tpu tree: AST rules "
                    "DP101-DP107 (default) and the jaxpr-level program "
                    "auditor DP200-DP206 (--trace); see --list-rules")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: "
                        f"{' '.join(DEFAULT_PATHS)}; ignored under --trace)")
    p.add_argument("--select", default="",
                   help="comma-separated rule IDs to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table (AST + trace) and exit")
    p.add_argument("--fixable", action="store_true",
                   help="list only mechanically fixable offenses")
    p.add_argument("--format", choices=("human", "json"), default="human",
                   help="finding output format: human `path:line:col:` "
                        "lines (default) or one JSON object per line")
    p.add_argument("--trace", action="store_true",
                   help="audit the registered jit entry points at the "
                        "jaxpr level (DP2xx) instead of linting source")
    p.add_argument("--entrypoints", default="",
                   help="--trace source override, `module:callable` "
                        "returning a list of EntryPoints (default: the "
                        "production registry)")
    p.add_argument("--fix", action="store_true",
                   help="apply the DP106 unused-import fixer to the "
                        "target paths (idempotent)")
    p.add_argument("--diff", action="store_true",
                   help="with --fix: print the unified diff, write nothing")
    return p


def _trace_rule_table() -> List[tuple]:
    """(id, fixable, name, description) for the trace rules. program.py
    keeps its jax imports inside rule bodies, so building the table (for
    `--list-rules` / `--select` validation) stays backend-neutral — no
    accelerator is initialized, same contract as the AST wing."""
    from dorpatch_tpu.analysis.program import DP200_ROW, all_trace_rules

    rows = [(r.id, False, r.name, r.description) for r in all_trace_rules()]
    rows.append((DP200_ROW[0], False, DP200_ROW[1], DP200_ROW[2]))
    return rows


def list_rules(out=None) -> None:
    out = out if out is not None else sys.stdout
    rows = [(r.id, r.fixable, r.name, r.description) for r in all_rules()]
    rows += _trace_rule_table()
    for rid, fixable, name, description in sorted(rows):
        fix = "fixable" if fixable else "       "
        out.write(f"{rid}  {fix}  {name}: {description}\n")


def emit(findings: List[Finding], fmt: str, out=None) -> None:
    out = out if out is not None else sys.stdout
    for f in findings:
        if fmt == "json":
            out.write(json.dumps(
                {"rule": f.rule_id, "path": f.path, "line": f.line,
                 "col": f.col, "message": f.message,
                 "fixable": f.fixable}) + "\n")
        else:
            out.write(f.render() + "\n")


def _parse_select(raw: str, trace_mode: bool) -> Optional[List[str]]:
    """Validate --select against the rules of the mode actually running:
    a cross-wing ID (`--select DP201` without `--trace`, or `--trace
    --select DP106`) would run ZERO rules and turn a CI gate into a
    vacuous pass — it must be a loud usage error instead."""
    if not raw:
        return None
    select = [s.strip().upper() for s in raw.split(",") if s.strip()]
    from dorpatch_tpu.analysis.program import TRACE_RULE_IDS

    ast_ids = {r.id for r in all_rules()} | {"DP000"}
    trace_ids = set(TRACE_RULE_IDS)
    known = trace_ids if trace_mode else ast_ids
    bad = set(select) - known
    if bad:
        other = sorted(bad & (ast_ids if trace_mode else trace_ids))
        if other:
            hint = (f" ({other} are AST rules; drop --trace)" if trace_mode
                    else f" ({other} are trace rules; add --trace)")
        else:
            hint = ""
        sys.stderr.write(
            f"rule id(s) not runnable in this mode: {sorted(bad)}{hint}\n")
        return ["<usage-error>"]
    return select


def _run_fix(paths: List[str], diff_only: bool) -> int:
    from dorpatch_tpu.analysis.fix import fix_paths

    files, removed, diffs = fix_paths(paths, write=not diff_only)
    if diff_only:
        for d in diffs:
            sys.stdout.write(d)
    verb = "would remove" if diff_only else "removed"
    sys.stderr.write(
        f"--fix: {verb} {removed} unused import(s) across {files} "
        "file(s)\n" if removed else "--fix: nothing to fix\n")
    return 0


def _run_trace(select: Optional[List[str]], spec: str,
               fmt: str) -> int:
    from dorpatch_tpu.analysis import entrypoints as ep_mod
    from dorpatch_tpu.analysis import program

    if spec:
        mod_name, _, attr = spec.partition(":")
        try:
            loader = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError) as e:
            sys.stderr.write(f"cannot load --entrypoints {spec!r}: {e}\n")
            return 2
        eps = list(loader())
        findings = program.audit_entrypoints(eps, select=select)
        n_progs = len(eps)
    else:
        eps = ep_mod.production_entrypoints()
        findings = program.audit_entrypoints(
            eps, select=select, uncovered=ep_mod.uncovered_names())
        n_progs = len(eps)
    emit(findings, fmt)
    if findings:
        sys.stderr.write(
            f"{len(findings)} trace finding(s) across {n_progs} entry "
            "point(s). Suppress a deliberate one with `# noqa: DP2xx` on "
            "the program's def line, or a reasoned "
            "analysis.program.ALLOWLIST entry when no source line can "
            "own it.\n")
        return 1
    sys.stderr.write(f"trace audit: {n_progs} entry point(s) clean\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        list_rules()
        return 0
    select = _parse_select(args.select, trace_mode=args.trace)
    if select == ["<usage-error>"]:
        return 2
    if args.diff and not args.fix:
        sys.stderr.write("--diff requires --fix\n")
        return 2
    if args.fix and args.trace:
        sys.stderr.write("--fix and --trace are separate modes; run them "
                         "as two invocations\n")
        return 2
    paths = args.paths or default_paths()
    if args.fix:
        return _run_fix(paths, args.diff)
    if args.trace:
        return _run_trace(select, args.entrypoints, args.format)
    try:
        findings = analyze_paths(paths, select=select)
    except (OSError, UnicodeDecodeError) as e:
        sys.stderr.write(
            f"cannot lint {getattr(e, 'filename', None) or paths}: {e}\n")
        return 2
    if args.fixable:
        findings = [f for f in findings if f.fixable]
    emit(findings, args.format)
    n_fix = sum(1 for f in findings if f.fixable)
    if findings:
        sys.stderr.write(
            f"{len(findings)} finding(s), {n_fix} fixable. Suppress a "
            "deliberate one with `# noqa: DPxxx <reason>`; run --fix for "
            "the fixable ones.\n")
        return 1
    return 0


def audit_main(argv: Optional[List[str]] = None) -> int:
    """`dorpatch-audit` console script: the trace audit as a first-class
    command (`dorpatch-audit` == `python -m dorpatch_tpu.analysis --trace`)."""
    return main(["--trace"] + list(argv if argv is not None else sys.argv[1:]))


if __name__ == "__main__":
    sys.exit(main())
