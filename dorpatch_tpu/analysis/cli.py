"""Lint CLI: `python -m dorpatch_tpu.analysis [paths...]`.

Exit status is the gate contract (`run_tests.sh` runs this before pytest):
0 = clean, 1 = findings, 2 = usage error. Stdout carries one
`path:line:col: DPxxx message` line per finding; the summary goes to stderr
so the finding stream stays machine-parseable.

The lint logic is stdlib-only and calls no jax API (see `engine.py`), so
the gate never initializes an accelerator backend.
"""

from __future__ import annotations

import argparse
import pathlib
import sys
from typing import List, Optional

from dorpatch_tpu.analysis.engine import all_rules, analyze_paths

DEFAULT_PATHS = ["dorpatch_tpu", "tools"]


def default_paths() -> List[str]:
    """The no-args targets, resolved so the installed `dorpatch-lint` script
    works from any cwd: cwd-relative names win (a checkout), otherwise fall
    back to the installed package location (where `tools` may not exist)."""
    root = pathlib.Path(__file__).resolve().parents[2]
    out = []
    for name in DEFAULT_PATHS:
        if pathlib.Path(name).exists():
            out.append(name)
        elif (root / name).exists():
            out.append(str(root / name))
    return out or [str(pathlib.Path(__file__).resolve().parents[1])]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dorpatch_tpu.analysis",
        description="JAX-aware static analysis for the dorpatch-tpu tree "
                    "(rules DP101-DP107; see --list-rules)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--select", default="",
                   help="comma-separated rule IDs to run (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    p.add_argument("--fixable", action="store_true",
                   help="list only mechanically fixable offenses")
    return p


def list_rules(out=None) -> None:
    out = out if out is not None else sys.stdout
    for rule in all_rules():
        fix = "fixable" if rule.fixable else "       "
        out.write(f"{rule.id}  {fix}  {rule.name}: {rule.description}\n")


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        list_rules()
        return 0
    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        known = {r.id for r in all_rules()}
        unknown = set(select) - known
        if unknown:
            sys.stderr.write(f"unknown rule id(s): {sorted(unknown)}\n")
            return 2
    paths = args.paths or default_paths()
    try:
        findings = analyze_paths(paths, select=select)
    except (OSError, UnicodeDecodeError) as e:
        sys.stderr.write(
            f"cannot lint {getattr(e, 'filename', None) or paths}: {e}\n")
        return 2
    if args.fixable:
        findings = [f for f in findings if f.fixable]
    for f in findings:
        sys.stdout.write(f.render() + "\n")
    n_fix = sum(1 for f in findings if f.fixable)
    if findings:
        sys.stderr.write(
            f"{len(findings)} finding(s), {n_fix} fixable. Suppress a "
            "deliberate one with `# noqa: DPxxx <reason>`.\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
