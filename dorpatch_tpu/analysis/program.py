"""Jaxpr-level program auditor: the DP2xx trace-time rule family.

The AST wing (`rules_jax.py`) proves what is visible in *source*; this
module proves what is only visible in the *traced program*. Every
registered jit entry point (`entrypoints.py`) is traced abstractly — the
jit AOT `.trace()` API on `ShapeDtypeStruct` example args, CPU-only, zero
device FLOPs — and the resulting jaxpr is checked for invariants the PR 2
runtime watchdog could previously only catch after paying a real compile:

- **DP201 carry-instability** — a pytree slot that crosses the program
  boundary as a carry (same tree structure in and out) with a different
  dtype / weak-type / shape, or a `lax.scan`/`while_loop` whose carry
  types fail to unify at trace time. The watchdog's bug class (the seed's
  weak-typed `loss_best`/`lr` init re-traced every attack block), now
  caught before any device run.
- **DP202 precision-leak** — float64/complex128 avals at the program
  boundary or inside any equation, and weak-typed floating outputs (a
  python-scalar-derived value escaping the program is a promotion/retrace
  hazard for every downstream consumer).
- **DP203 const-bloat** — host (numpy) literal arrays above a byte
  threshold baked into the program as closed-over constants instead of
  passed as arguments: they inflate every executable and re-stage to
  device on every compile. (Closed-over *device* arrays — the attack's
  params idiom — are shared buffers and exempt.)
- **DP204 dead-code** — equation chains whose results reach no output and
  carry no effect, flagged when the dead chain contains real compute
  (matmul/conv/scan/collective); cheap dead equations are endemic VJP
  residue and stay quiet.
- **DP205 collective-axis** — a `psum`-family collective over an axis
  name its enclosing `shard_map`/`pmap` mesh does not bind: at run time
  on a real multihost mesh this is a deadlock, at trace time it is one
  string comparison.
- **DP206 donation** — an argument declared donated whose buffer no
  output can reuse (no shape/dtype match): the donation silently buys
  nothing and XLA warns at compile time on device.
- **DP208 bf16-silent-upcast** — inside a declared-bf16 program (name
  carries the `.bf16` tag), large float32 compute fed by a bf16->f32
  upcast: dtype promotion (which the jnp layer materializes as an
  inserted `convert_element_type`) has silently pulled part of the bank
  back to f32, doubling that slab's HBM traffic and eroding exactly the
  bytes win the bank exists for — the defect flax's `nn.GroupNorm`
  planted in the conv bank. Exempt: f32 accumulations that reduce
  straight back down (the `E[x^2]` stats idiom,
  `fused_gn.gn_preserve_dtype`), dot/conv equations declaring
  `preferred_element_type=float32` (`ops/stem_fold._delta_conv`), and
  readout-scale outputs (the f32 logit/margin tables,
  `utils.preds_margins`).

Findings flow through the existing engine types (`engine.Finding`, stable
IDs, `# noqa:` suppression against the entry point's defining source
line). Rules where a source comment cannot reach the offense (traced
lambdas, generated wrappers) use the programmatic allowlist instead:
`ALLOWLIST` maps an entry-point-name glob to the rule IDs it may trip,
with a reason string.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import pathlib
from typing import (Any, Dict, Iterable, Iterator, List, Optional, Sequence,
                    Set, Tuple)

from dorpatch_tpu.analysis.engine import Finding, _parse_noqa
from dorpatch_tpu.analysis.entrypoints import EntryPoint

#: Entry-point-name glob -> {rule_id: reason}. The trace-level analog of a
#: `# noqa:` comment, for programs whose offense has no ownable source line.
#: Shipped entries carry their reason; everything else found in the shipped
#: tree is FIXED, not allowlisted.
ALLOWLIST: Dict[str, Dict[str, str]] = {
    # flax's `Module.init` traces the full forward and keeps only the
    # variables: the forward equations (convs/matmuls included) are dead by
    # construction, DCE'd by XLA, and paid exactly once per process. The
    # offense lives inside flax's tracer, not on an ownable source line.
    "model.init.*": {"DP204": "flax init discards the traced forward"},
    "train.init": {"DP204": "flax init discards the traced forward"},
}

#: DP203 default: constants this large belong in the argument list, where
#: the runtime can donate/share them, not baked into the executable.
CONST_BYTES_THRESHOLD = 128 * 1024

#: DP204 reports a dead chain only when it contains one of these (real
#: compute/communication). Cheap dead equations — broadcasts, slices,
#: selects — are endemic VJP residue: `value_and_grad` leaves unused primal
#: pieces in the jaxpr for XLA to DCE, ~1 per layer even in a clean model,
#: and flagging them would bury the signal.
_EXPENSIVE_PRIMS = {
    "dot_general", "conv_general_dilated", "scan", "while", "cond",
    "custom_call", "shard_map", "all_gather", "all_to_all", "psum", "psum2",
    "reduce_scatter", "sort", "top_k",
}

_COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmin", "pmax", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index", "pgather",
    "psum_invariant",
}


# ---------------------------------------------------------------- plumbing

@dataclasses.dataclass
class ProgramContext:
    """Everything a trace rule needs about one abstractly traced program."""

    name: str
    fn: Any
    jaxpr: Any                       # ClosedJaxpr of the program body
    args: Tuple[Any, ...]            # abstract example args (pytree leaves)
    out_avals_tree: Any              # output avals in the fn's out pytree
    args_info: Any                   # Traced.args_info (donation), or None
    path: str
    line: int
    #: the jit AOT `Traced` object when the entry point exposes `.trace()`
    #: (None for bare callables) — the baseline tier's bridge to
    #: `.lower().compile().cost_analysis()`
    traced: Any = None


class TraceRule:
    """Base for jaxpr-level rules; mirrors `engine.Rule` but checks a
    `ProgramContext` instead of a `FileContext`."""

    id: str = ""
    name: str = ""
    description: str = ""

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ProgramContext, message: str) -> Finding:
        return Finding(path=ctx.path, line=ctx.line, col=1, rule_id=self.id,
                       message=f"[{ctx.name}] {message}")


_TRACE_REGISTRY: Dict[str, TraceRule] = {}


def register_trace(cls):
    if not cls.id:
        raise ValueError(f"trace rule {cls.__name__} has no id")
    if cls.id in _TRACE_REGISTRY:
        raise ValueError(f"duplicate trace rule id {cls.id}")
    _TRACE_REGISTRY[cls.id] = cls()
    return cls


def all_trace_rules() -> List[TraceRule]:
    return [_TRACE_REGISTRY[k] for k in sorted(_TRACE_REGISTRY)]


def _source_location(fn) -> Tuple[str, int]:
    """Best-effort (file, line) of the python function under a jit/timer
    wrapper chain — the anchor `# noqa:` suppressions attach to. For a
    decorated function `co_firstlineno` is the first decorator line; the
    location advances to the `def` line, where a suppression comment can
    actually live."""
    seen = 0
    f = fn
    while hasattr(f, "__wrapped__") and seen < 10:
        f = f.__wrapped__
        seen += 1
    code = getattr(f, "__code__", None)
    if code is None:
        return "<entrypoint>", 1
    path = code.co_filename
    line = code.co_firstlineno
    try:
        lines = pathlib.Path(path).read_text(encoding="utf-8").splitlines()
        for i in range(line - 1, min(line + 30, len(lines))):
            stripped = lines[i].lstrip()
            if stripped.startswith(("def ", "async def ", "lambda")):
                line = i + 1
                break
    except OSError:
        pass
    try:
        path = str(pathlib.Path(path).resolve().relative_to(
            pathlib.Path.cwd()))
    except ValueError:
        pass
    return path, line


def _is_aval(x) -> bool:
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _aval_str(a) -> str:
    weak = ", weak" if getattr(a, "weak_type", False) else ""
    return f"{a.dtype}{list(a.shape)}{weak}"


def iter_jaxprs(closed_or_raw) -> Iterator[Any]:
    """The jaxpr plus every sub-jaxpr reachable through equation params
    (pjit/scan/while/cond/shard_map/custom_* bodies), depth-first."""
    import jax

    def raw(j):
        return j.jaxpr if isinstance(j, jax.core.ClosedJaxpr) else j

    stack = [closed_or_raw]
    while stack:
        j = stack.pop()
        yield j
        for eqn in raw(j).eqns:
            for sub in _eqn_subjaxprs(eqn):
                stack.append(sub)


def _eqn_subjaxprs(eqn) -> List[Any]:
    import jax

    out = []
    for v in eqn.params.values():
        for item in (v if isinstance(v, (list, tuple)) else [v]):
            if isinstance(item, (jax.core.Jaxpr, jax.core.ClosedJaxpr)):
                out.append(item)
    return out


def _raw(j):
    import jax

    return j.jaxpr if isinstance(j, jax.core.ClosedJaxpr) else j


# ---------------------------------------------------------------- DP201

def _carry_candidates(args: Tuple[Any, ...], out_tree) -> List[Tuple[Any, Any]]:
    """(input subtree, output subtree) pairs that plausibly form a carry:
    the whole output against each argument, and — when the output is a
    plain tuple/list — its elements zipped against the leading arguments
    (the `step(state, ...) -> (state', aux...)` convention)."""
    import jax

    cands = [(a, out_tree) for a in args]
    if type(out_tree) in (tuple, list):
        cands.extend(zip(args, out_tree))
    seen: List[Tuple[int, int]] = []
    uniq = []
    for a, o in cands:
        key = (id(a), id(o))
        if key in seen:
            continue
        seen.append(key)
        if jax.tree_util.tree_structure(a) == jax.tree_util.tree_structure(o):
            uniq.append((a, o))
    return uniq


@register_trace
class CarryInstabilityRule(TraceRule):
    id = "DP201"
    name = "carry-instability"
    description = ("carry pytree slot whose aval (dtype/weak-type/shape) "
                   "differs between program input and output — every "
                   "host-level iteration re-traces the program")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        import jax

        for a_tree, o_tree in _carry_candidates(ctx.args, ctx.out_avals_tree):
            a_paths = jax.tree_util.tree_flatten_with_path(a_tree)[0]
            o_leaves = jax.tree_util.tree_leaves(o_tree)
            multi = len(a_paths) > 1
            for (kp, a), o in zip(a_paths, o_leaves):
                if not (_is_aval(a) and _is_aval(o)):
                    continue
                shape_ok = tuple(a.shape) == tuple(o.shape)
                dtype_ok = a.dtype == o.dtype
                weak_ok = bool(getattr(a, "weak_type", False)) == \
                    bool(getattr(o, "weak_type", False))
                if multi:
                    bad = not (shape_ok and dtype_ok and weak_ok)
                else:
                    # a single-leaf structure matches ANY array->array fn;
                    # only an identical shape with drifting dtype/weak-type
                    # is evidence of a carry (not a plain transformation)
                    bad = shape_ok and not (dtype_ok and weak_ok)
                if bad:
                    yield self.finding(
                        ctx,
                        f"carry leaf {jax.tree_util.keystr(kp) or '<root>'} "
                        f"is {_aval_str(a)} going in but {_aval_str(o)} "
                        "coming out — the next call re-traces (weak-typed "
                        "or mismatched init; declare explicit dtypes)")


# ---------------------------------------------------------------- DP202

@register_trace
class PrecisionLeakRule(TraceRule):
    id = "DP202"
    name = "precision-leak"
    description = ("float64/complex128 aval at a program boundary or "
                   "inside the program, or a weak-typed floating output "
                   "escaping the boundary")

    _WIDE = ("float64", "complex128")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        import jax
        import numpy as np

        for side, avals in (("input", ctx.jaxpr.in_avals),
                            ("output", ctx.jaxpr.out_avals)):
            for i, a in enumerate(avals):
                if not _is_aval(a):
                    continue
                if str(a.dtype) in self._WIDE:
                    yield self.finding(
                        ctx, f"{side} {i} is {_aval_str(a)} — double "
                        "precision at a program boundary (x64 leak)")
                elif (side == "output"
                      and getattr(a, "weak_type", False)
                      and np.issubdtype(a.dtype, np.floating)):
                    yield self.finding(
                        ctx, f"output {i} is weak-typed {_aval_str(a)} — a "
                        "python-scalar-derived value is escaping the "
                        "program boundary (promotion/retrace hazard)")
        reported = 0
        for j in iter_jaxprs(ctx.jaxpr):
            for eqn in _raw(j).eqns:
                for v in eqn.outvars:
                    a = getattr(v, "aval", None)
                    if a is not None and _is_aval(a) \
                            and str(a.dtype) in self._WIDE:
                        yield self.finding(
                            ctx, f"equation `{eqn.primitive.name}` produces "
                            f"{_aval_str(a)} inside the program (x64 leak)")
                        reported += 1
                        break
                if reported >= 3:  # one program, one story: cap the noise
                    return


# ---------------------------------------------------------------- DP203

@register_trace
class ConstBloatRule(TraceRule):
    id = "DP203"
    name = "const-bloat"
    description = ("closed-over literal array above the byte threshold "
                   "baked into the program instead of passed as an "
                   "argument")

    threshold = CONST_BYTES_THRESHOLD

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        import jax
        import numpy as np

        for j in iter_jaxprs(ctx.jaxpr):
            if not isinstance(j, jax.core.ClosedJaxpr):
                continue
            for c in j.consts:
                # a closed-over DEVICE array (jax.Array) is a buffer the
                # executable references by handle — the attack's deliberate
                # params-closure idiom shares it across every program at
                # zero copy. A closed-over HOST array is genuinely baked:
                # re-staged to device per program, per recompile.
                if not isinstance(c, np.ndarray):
                    continue
                nbytes = getattr(c, "nbytes", 0)
                if nbytes and nbytes > self.threshold:
                    yield self.finding(
                        ctx,
                        f"closed-over host constant {c.dtype}"
                        f"{list(c.shape)} ({nbytes / 1024:.0f} KiB > "
                        f"{self.threshold / 1024:.0f} KiB) is baked into "
                        "the program and re-staged on every compile — pass "
                        "it as an argument (or device_put it once)")


# ---------------------------------------------------------------- DP204

@register_trace
class DeadCodeRule(TraceRule):
    id = "DP204"
    name = "dead-code"
    description = ("equation chain whose results reach no program output "
                   "and carry no effect — traced and compiled for nothing")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        dead_prims: List[str] = []
        for j in iter_jaxprs(ctx.jaxpr):
            dead_prims.extend(self._dead_eqns(_raw(j)))
        heavy = sorted(set(dead_prims) & _EXPENSIVE_PRIMS)
        if heavy:
            yield self.finding(
                ctx, f"{len(dead_prims)} dead equation(s) including real "
                f"compute ({', '.join(heavy[:3])}) — their outputs reach "
                "no program output; delete the computation or return it")

    @staticmethod
    def _dead_eqns(jaxpr) -> List[str]:
        import jax

        live: Set[Any] = {v for v in jaxpr.outvars
                          if not isinstance(v, jax.core.Literal)}
        dead: List[str] = []
        for eqn in reversed(jaxpr.eqns):
            outs = [v for v in eqn.outvars
                    if not isinstance(v, jax.core.DropVar)]
            if getattr(eqn, "effects", None) or any(v in live for v in outs):
                for v in eqn.invars:
                    if not isinstance(v, jax.core.Literal):
                        live.add(v)
                # sub-jaxpr outvars feed this eqn's semantics; their own
                # dead chains are found when iter_jaxprs visits them
            else:
                dead.append(eqn.primitive.name)
        return dead


# ---------------------------------------------------------------- DP205

def _collective_axes(eqn) -> List[str]:
    axes = eqn.params.get("axes", eqn.params.get(
        "axis_name", eqn.params.get("axis", ())))
    if isinstance(axes, (str,)):
        axes = (axes,)
    return [a for a in tuple(axes) if isinstance(a, str)]


@register_trace
class CollectiveAxisRule(TraceRule):
    id = "DP205"
    name = "collective-axis"
    description = ("collective (psum family) over an axis name its "
                   "enclosing shard_map/pmap mesh does not bind — a "
                   "multihost deadlock at run time")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        yield from self._walk(ctx, ctx.jaxpr, frozenset())

    def _walk(self, ctx: ProgramContext, j, bound: frozenset
              ) -> Iterator[Finding]:
        for eqn in _raw(j).eqns:
            prim = eqn.primitive.name
            if prim in _COLLECTIVE_PRIMS:
                for ax in _collective_axes(eqn):
                    if ax not in bound:
                        yield self.finding(
                            ctx, f"`{prim}` over axis {ax!r}, but the "
                            f"enclosing mesh binds only "
                            f"{sorted(bound) or '(no axes)'} — this "
                            "deadlocks a multihost run")
            inner_bound = bound
            if prim == "shard_map":
                mesh = eqn.params.get("mesh")
                names = tuple(getattr(mesh, "axis_names", ()) or ())
                inner_bound = bound | frozenset(names)
            elif prim == "xla_pmap":
                name = eqn.params.get("axis_name")
                if isinstance(name, str):
                    inner_bound = bound | {name}
            for sub in _eqn_subjaxprs(eqn):
                yield from self._walk(ctx, sub, inner_bound)


# ---------------------------------------------------------------- DP206

@register_trace
class DonationRule(TraceRule):
    id = "DP206"
    name = "donation"
    description = ("argument declared donated but no output can reuse its "
                   "buffer (no shape/dtype match) — the donation is dead "
                   "weight and XLA warns at every compile")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        import jax

        if ctx.args_info is None:
            return
        leaves = jax.tree_util.tree_leaves(
            ctx.args_info, is_leaf=lambda x: hasattr(x, "donated"))
        donated = [x for x in leaves if getattr(x, "donated", False)]
        if not donated:
            return
        pool: List[Tuple[Tuple[int, ...], Any]] = [
            (tuple(a.shape), a.dtype) for a in ctx.jaxpr.out_avals]
        for info in donated:
            aval = getattr(info, "aval", None) or info._aval
            key = (tuple(aval.shape), aval.dtype)
            if key in pool:
                pool.remove(key)  # one output reuses one donated buffer
            else:
                yield self.finding(
                    ctx, f"donated argument {_aval_str(aval)} matches no "
                    "output buffer — the donation frees nothing; drop it "
                    "or return an updated value of the same shape/dtype")


# ---------------------------------------------------------------- DP208

def _n_elems(a) -> int:
    n = 1
    for d in a.shape:
        try:
            n *= int(d)
        except (TypeError, ValueError):
            return 1 << 30  # dynamic dim: assume big
    return n


@register_trace
class SilentUpcastRule(TraceRule):
    id = "DP208"
    name = "bf16-silent-upcast"
    description = ("large float32 compute fed by a bfloat16->float32 "
                   "upcast inside a declared-bf16 program — promotion has "
                   "silently pulled part of the bank back to f32 (f32 "
                   "accumulations that reduce straight back down, declared "
                   "preferred_element_type, and readout-scale outputs are "
                   "exempt)")

    #: consuming an f32 upcast here is the accumulate idiom (means/stats),
    #: not a leak — the big f32 tensor collapses immediately
    _REDUCERS = frozenset({
        "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
        "reduce_and", "reduce_or", "argmax", "argmin"})
    #: f32 outputs at or below this element count are readout-scale
    #: (margins, label tables, per-group stats), never the bank's slabs
    _SMALL_ELEMS = 8192

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        if ".bf16" not in ctx.name:
            return
        reported = 0
        for j in iter_jaxprs(ctx.jaxpr):
            raw = _raw(j)
            consumers: Dict[int, List[Any]] = {}
            for eqn in raw.eqns:
                for v in eqn.invars:
                    if _is_aval(getattr(v, "aval", None)):
                        consumers.setdefault(id(v), []).append(eqn)
            # every bf16 -> f32 convert result: the promotion frontier
            upcast: Set[int] = set()
            for eqn in raw.eqns:
                if eqn.primitive.name != "convert_element_type":
                    continue
                src = getattr(eqn.invars[0], "aval", None)
                dst = eqn.outvars[0].aval
                if _is_aval(src) and str(src.dtype) == "bfloat16" \
                        and str(dst.dtype) == "float32":
                    upcast.add(id(eqn.outvars[0]))
            if not upcast:
                continue
            for eqn in raw.eqns:
                prim = eqn.primitive.name
                if prim == "convert_element_type" or prim in self._REDUCERS \
                        or _eqn_subjaxprs(eqn):
                    continue
                pet = eqn.params.get("preferred_element_type")
                if pet is not None and str(pet) == "float32":
                    continue  # declared f32 accumulation, explicit in source
                if not any(id(v) in upcast for v in eqn.invars):
                    continue
                big = [v.aval for v in eqn.outvars
                       if _is_aval(getattr(v, "aval", None))
                       and str(v.aval.dtype) == "float32"
                       and _n_elems(v.aval) > self._SMALL_ELEMS]
                if not big:
                    continue
                # the E[x^2] idiom: a large f32 product is fine when every
                # consumer reduces it straight back down
                cons = [c for v in eqn.outvars
                        for c in consumers.get(id(v), [])]
                if cons and all(c.primitive.name in self._REDUCERS
                                for c in cons):
                    continue
                yield self.finding(
                    ctx, f"equation `{prim}` turns a bf16->f32 upcast into "
                    f"a {_aval_str(big[0])} intermediate inside a bf16 bank "
                    "— promotion is silently running this math at f32; "
                    "keep the slab at bfloat16 or reduce it immediately")
                reported += 1
                if reported >= 3:  # one program, one story: cap the noise
                    return


# ---------------------------------------------------------------- driver

#: Trace-failure message fragments -> the rule that owns the failure mode.
_ERROR_RULES = (
    ("carry", "DP201"),
    ("unbound axis name", "DP205"),
)


def allowed(name: str, rule_id: str,
            allow: Optional[Dict[str, Dict[str, str]]] = None) -> bool:
    """True when `ALLOWLIST` (or the per-call `allow` overlay) grants
    `rule_id` for entry-point `name` (keys are fnmatch globs)."""
    for table in (ALLOWLIST, allow or {}):
        for pattern, rules in table.items():
            if fnmatch.fnmatchcase(name, pattern) and rule_id in rules:
                return True
    return False


_noqa_cache: Dict[str, Dict[int, Any]] = {}


def _suppressed_in_source(path: str, line: int, rule_id: str) -> bool:
    """Honor a `# noqa: DP2xx` on the entry point's `def` line, the same
    contract the AST rules give — the allowlist covers everything a source
    comment cannot reach."""
    from dorpatch_tpu.analysis.engine import ALL_CODES

    if path not in _noqa_cache:
        try:
            src = pathlib.Path(path).read_text(encoding="utf-8")
            _noqa_cache[path] = _parse_noqa(src)
        except OSError:
            _noqa_cache[path] = {}
    codes = _noqa_cache[path].get(line)
    if codes is None:
        return False
    return codes == ALL_CODES or rule_id in codes


def trace_entrypoint(ep: EntryPoint) -> Tuple[Optional[ProgramContext],
                                              List[Finding]]:
    """Abstractly trace one entry point. Returns (context, findings): a
    trace failure maps to the rule owning that failure mode (scan-carry
    TypeErrors are DP201, unbound-axis NameErrors are DP205) or to DP200 —
    a program that cannot trace must fail the gate loudly, like a syntax
    error fails lint."""
    import jax

    path, line = _source_location(ep.fn)
    traced = None
    try:
        if hasattr(ep.fn, "trace"):
            traced = ep.fn.trace(*ep.args, **ep.kwargs)
            jaxpr = traced.jaxpr
            out_tree = jax.tree_util.tree_structure(traced.out_info)
            out_avals_tree = jax.tree_util.tree_unflatten(
                out_tree, jaxpr.out_avals)
            args_info = traced.args_info
        else:
            jaxpr, out_shape = jax.make_jaxpr(ep.fn, return_shape=True)(
                *ep.args, **ep.kwargs)
            out_tree = jax.tree_util.tree_structure(out_shape)
            out_avals_tree = jax.tree_util.tree_unflatten(
                out_tree, jaxpr.out_avals)
            args_info = None
    except Exception as e:  # the error class varies by jax version
        msg = f"{type(e).__name__}: {e}"
        rule_id = "DP200"
        for fragment, rid in _ERROR_RULES:
            if fragment in msg.lower():
                rule_id = rid
                break
        first = msg.splitlines()[0][:300]
        return None, [Finding(
            path=path, line=line, col=1, rule_id=rule_id,
            message=f"[{ep.name}] failed to trace abstractly: {first}")]
    return ProgramContext(name=ep.name, fn=ep.fn, jaxpr=jaxpr, args=ep.args,
                          out_avals_tree=out_avals_tree, args_info=args_info,
                          path=path, line=line, traced=traced), []


def audit_entrypoint(ep: EntryPoint,
                     select: Optional[Sequence[str]] = None,
                     allow: Optional[Dict[str, Dict[str, str]]] = None
                     ) -> List[Finding]:
    ctx, findings = trace_entrypoint(ep)
    if ctx is not None:
        for rule in all_trace_rules():
            if select is not None and rule.id not in select:
                continue
            findings.extend(rule.check(ctx))
    out = []
    for f in findings:
        if select is not None and f.rule_id not in select:
            continue
        if allowed(ep.name, f.rule_id, allow):
            continue
        if _suppressed_in_source(f.path, f.line, f.rule_id):
            continue
        out.append(f)
    return sorted(out)


def audit_entrypoints(eps: Iterable[EntryPoint],
                      select: Optional[Sequence[str]] = None,
                      allow: Optional[Dict[str, Dict[str, str]]] = None,
                      uncovered: Sequence[str] = ()) -> List[Finding]:
    """Audit a batch of entry points; `uncovered` names (discovered via a
    timed_first_call wrap but never given example args) are DP200 findings
    — an unauditable production program is a hole in the gate."""
    findings: List[Finding] = []
    for name in uncovered:
        if select is not None and "DP200" not in select:
            continue
        if not allowed(name, "DP200", allow):
            findings.append(Finding(
                path="<entrypoint>", line=1, col=1, rule_id="DP200",
                message=f"[{name}] entry point was wrapped by "
                        "timed_first_call but no example args were "
                        "registered — the auditor cannot trace it "
                        "(register it in analysis/entrypoints.py)"))
    for ep in eps:
        findings.extend(audit_entrypoint(ep, select=select, allow=allow))
    return sorted(findings)


def audit_production(select: Optional[Sequence[str]] = None,
                     allow: Optional[Dict[str, Dict[str, str]]] = None
                     ) -> List[Finding]:
    """Enumerate + audit every registered production entry point — the
    `--trace` gate's whole job."""
    from dorpatch_tpu.analysis import entrypoints as ep_mod

    eps = ep_mod.production_entrypoints()
    return audit_entrypoints(eps, select=select, allow=allow,
                             uncovered=ep_mod.uncovered_names())


#: The trace-failure meta rule: not a registered TraceRule (it has no jaxpr
#: to check — it IS the absence of one), but it owns a stable ID, a row in
#: `--list-rules`, and a slot in `--select` like any other rule.
DP200_ROW = ("DP200", "untraceable-entrypoint",
             "registered jit entry point failed to trace abstractly (or "
             "has no registered example args)")

#: Rule IDs the trace wing owns (DP200 is the trace-failure meta rule).
TRACE_RULE_IDS = ("DP200",) + tuple(sorted(_TRACE_REGISTRY))
