"""Runtime lock sanitizer: acquisition-order + hold-budget watchdog.

The static concurrency tier (`analysis/concurrency.py`, DP500-DP504) can
only see lock nestings that are syntactically visible in one file. This is
its runtime wing: an instrumenting wrapper around `threading.Lock` /
`threading.RLock` that records, per thread, the *actual* acquisition order
and held durations, and cross-checks them against the static DP501 graph.

- **Order violations** — before acquiring lock `b` while holding `a`, the
  watch asks whether the combined graph (every order observed at runtime
  so far, union the static nested-`with` graph) already contains a path
  `b ⇝ a`. If it does, this acquisition closes an ABBA cycle: some other
  code path takes the same pair in the opposite order, and the two paths
  can deadlock each other under the right interleaving. The watch emits a
  `sanitize.lock_order` event and raises `LockOrderViolation` — crucially
  *before* touching the underlying lock, so nothing is left stranded in
  the acquired state when the `with` body never runs.
- **Hold budgets** — with `hold_budget_s` set, releasing a lock that was
  held longer than the budget emits `sanitize.lock_held` and raises
  `LockHoldBudgetExceeded` — *after* the real release, so the violation
  report never itself wedges the fleet. Same contract as the recompile
  watchdog in `sanitize.py`: the event is written first, so post-mortem
  telemetry has the record even if the raise is swallowed.

Armed process-wide by `Sanitizer(lock_order=True)` (`--sanitize`), which
installs the watch via `set_active_watch`. Production code opts in at
lock-construction time with the `watched_lock` factory, which degrades to
a bare `threading.Lock` when no watch is armed — zero overhead in normal
serving.

Like the static tier, this is a *sanitizer*, not a verifier: it only sees
orders that actually execute. Its value is catching the inversion the
static tier cannot see (cross-file, cross-callable) the first time it
runs, not proving absence.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional, Set, Tuple


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here closes an ABBA cycle with an order seen
    elsewhere (at runtime or in the static DP501 graph)."""


class LockHoldBudgetExceeded(RuntimeError):
    """A watched lock was held longer than the sanitizer's hold budget."""


def _has_path(graph: Dict[str, Set[str]], src: str, dst: str) -> bool:
    """True when `dst` is reachable from `src` (iterative DFS)."""
    if src == dst:
        return True
    seen = {src}
    stack = [src]
    while stack:
        node = stack.pop()
        for nxt in graph.get(node, ()):
            if nxt == dst:
                return True
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


class WatchedLock:
    """One instrumented lock: context manager with the same acquire/release
    surface as the underlying `threading.Lock`/`RLock` it wraps."""

    def __init__(self, watch: "LockWatch", raw, name: str):
        self._watch = watch
        self._raw = raw
        self.name = name

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        # order check BEFORE the raw acquire: raising afterwards would
        # strand the lock (the `with` body — and release — never runs)
        self._watch._pre_acquire(self.name)
        got = self._raw.acquire(blocking, timeout)
        if got:
            self._watch._post_acquire(self.name)
        return got

    def release(self) -> None:
        held_s = self._watch._pre_release(self.name)
        self._raw.release()
        # budget check AFTER the raw release: the violation report must
        # never itself leave the fleet wedged on this lock
        self._watch._post_release(self.name, held_s)

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._raw.locked()


class LockWatch:
    """Process-wide acquisition recorder + checker (see module docstring).

    `static_graph` seeds the order relation with the analyzer's DP501
    nested-`with` edges, so a runtime acquisition that inverts an order
    the *source* commits to is caught on its first execution, before the
    opposite runtime path has ever run.
    """

    def __init__(self, hold_budget_s: Optional[float] = None,
                 static_graph: Optional[
                     Dict[str, Iterable[Tuple[str, object]]]] = None,
                 clock=time.monotonic):
        self.hold_budget_s = hold_budget_s
        self._clock = clock
        self._meta_lock = threading.Lock()
        # name -> set of names acquired while it was held
        self._observed: Dict[str, Set[str]] = {}  # guarded-by: self._meta_lock
        self._static: Dict[str, Set[str]] = {}
        for src, edges in (static_graph or {}).items():
            for edge in edges:
                dst = edge[0] if isinstance(edge, tuple) else edge
                self._static.setdefault(str(src), set()).add(str(dst))
        # per-thread stack of (name, acquired-at) — thread-local, unshared
        self._held = threading.local()
        self.violations = 0  # guarded-by: self._meta_lock

    # ---------------- construction ----------------

    def lock(self, name: str) -> WatchedLock:
        return WatchedLock(self, threading.Lock(), name)

    def rlock(self, name: str) -> WatchedLock:
        return WatchedLock(self, threading.RLock(), name)

    def wrap(self, raw, name: str) -> WatchedLock:
        return WatchedLock(self, raw, name)

    # ---------------- introspection ----------------

    def observed_edges(self) -> Dict[str, Set[str]]:
        with self._meta_lock:
            return {k: set(v) for k, v in self._observed.items()}

    def held_by_current_thread(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self._stack())

    def _stack(self):
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    # ---------------- acquire/release hooks ----------------

    def _combined_path(self, src: str, dst: str) -> bool:
        """Reachability over observed ∪ static edges. Caller holds
        `_meta_lock` (observed) — static is frozen after __init__."""
        merged = dict(self._static)
        for node, nxt in self._observed.items():
            merged[node] = merged.get(node, set()) | nxt
        return _has_path(merged, src, dst)

    def _pre_acquire(self, name: str) -> None:
        stack = self._stack()
        held = [h for h, _ in stack]
        if not held or name in held:
            # first lock, or a reentrant re-acquire (RLock): no new order
            return
        with self._meta_lock:
            for h in held:
                if self._combined_path(name, h):
                    self.violations += 1
                    cycle = f"{h} -> {name} here, {name} ~> {h} elsewhere"
                    from dorpatch_tpu.observe import events as _events
                    _events.record_event(
                        "sanitize.lock_order", lock=name, held=held,
                        cycle=cycle,
                        thread=threading.current_thread().name)
                    raise LockOrderViolation(
                        f"lock order violation: acquiring {name!r} while "
                        f"holding {held!r} closes a cycle ({cycle}); "
                        f"canonical order is alphabetical by lock name "
                        f"(DP501)")
            for h in held:
                self._observed.setdefault(h, set()).add(name)

    def _post_acquire(self, name: str) -> None:
        self._stack().append((name, self._clock()))

    def _pre_release(self, name: str) -> float:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                _, t0 = stack.pop(i)
                return self._clock() - t0
        return 0.0  # release without a recorded acquire: nothing to time

    def _post_release(self, name: str, held_s: float) -> None:
        budget = self.hold_budget_s
        if budget is not None and held_s > budget:
            with self._meta_lock:
                self.violations += 1
            from dorpatch_tpu.observe import events as _events
            _events.record_event(
                "sanitize.lock_held", lock=name,
                held_s=round(held_s, 6), budget_s=budget,
                thread=threading.current_thread().name)
            raise LockHoldBudgetExceeded(
                f"lock {name!r} held {held_s:.3f}s, over the "
                f"{budget:g}s sanitizer budget (DP502's runtime twin: "
                f"something blocking ran under this lock)")


# ---------------- process-wide arming (mirrors events._ACTIVE) ----------------

_ACTIVE_WATCH: Optional[LockWatch] = None


def active_watch() -> Optional[LockWatch]:
    return _ACTIVE_WATCH


def set_active_watch(watch: Optional[LockWatch]) -> Optional[LockWatch]:
    """Install `watch` as the process-active lock watch; returns the
    previous one so callers (the Sanitizer) can restore it on exit."""
    global _ACTIVE_WATCH
    prev = _ACTIVE_WATCH
    _ACTIVE_WATCH = watch
    return prev


def watched_lock(name: str, factory=threading.Lock):
    """Construction-time opt-in for production code: an instrumented lock
    when a watch is armed (`--sanitize`), a bare `factory()` otherwise —
    the unsanitized fleet pays nothing."""
    watch = _ACTIVE_WATCH
    if watch is None:
        return factory()
    return watch.wrap(factory(), name)
