"""Output- and hygiene-rules: DP101 (bare print) and DP106 (unused import).

DP101 absorbs the PR 1 tokenize guard (`tests/test_print_guard.py`, now a
thin wrapper over this rule): under an N-process SPMD driver, anonymous
`print` output from the package interleaves unattributably — everything
routes through `observe.log()` (`[pN +T.Ts]` prefix). The rule is scoped to
modules *inside* the dorpatch_tpu package, excluding `observe/` itself
(which implements the sink and the report CLI's stdout); standalone tools
and scripts outside the package may print.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from dorpatch_tpu.analysis.engine import FileContext, Finding, Rule, register


@register
class BarePrintRule(Rule):
    id = "DP101"
    name = "bare-print"
    description = ("bare print() inside the dorpatch_tpu package (outside "
                   "observe/) — route output through observe.log() so "
                   "multi-process logs stay attributable")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_package() or ctx.in_observe():
            return
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield self.finding(
                    ctx, node,
                    "bare print() call — use observe.log() so multi-process "
                    "output stays attributable")


def _all_exports(tree: ast.AST) -> Set[str]:
    """Names listed in `__all__` (string constants in list/tuple/set
    assignments and `__all__ +=` augmentations)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "__all__"
                for t in node.targets):
            target = node.value
        elif (isinstance(node, ast.AugAssign)
              and isinstance(node.target, ast.Name)
              and node.target.id == "__all__"):
            target = node.value
        if isinstance(target, (ast.List, ast.Tuple, ast.Set)):
            for elt in target.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    names.add(elt.value)
    return names


def _string_annotation_names(tree: ast.AST) -> Set[str]:
    """Names referenced inside explicitly quoted annotations
    (`def f(x: "np.ndarray")`, `y: "List[int]" = ...`)."""
    ann: List[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            ann.extend(a.annotation for a in
                       args.posonlyargs + args.args + args.kwonlyargs)
            ann.extend([args.vararg and args.vararg.annotation,
                        args.kwarg and args.kwarg.annotation,
                        node.returns])
        elif isinstance(node, (ast.AnnAssign, ast.arg)):
            ann.append(node.annotation)
    names: Set[str] = set()
    for a in ann:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            try:
                parsed = ast.parse(a.value, mode="eval")
            except SyntaxError:
                continue
            names |= {n.id for n in ast.walk(parsed)
                      if isinstance(n, ast.Name)}
    return names


@register
class UnusedImportRule(Rule):
    id = "DP106"
    name = "unused-import"
    fixable = True
    description = ("imported name is never used (names in __all__ and "
                   "explicit `import x as x` re-exports are considered used)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        imported: List[Tuple[str, ast.AST, str]] = []  # (name, node, shown)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    # `import a.b.c` binds `a`; `import a.b.c as d` binds `d`
                    bound = a.asname or a.name.split(".")[0]
                    if a.asname is not None and a.asname == a.name:
                        continue  # `import x as x`: explicit re-export
                    imported.append((bound, node, a.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    if a.asname is not None and a.asname == a.name:
                        continue  # `from m import x as x`: re-export
                    imported.append((a.asname or a.name, node,
                                     f"{node.module or '.'}.{a.name}"))
        if not imported:
            return

        used: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
        # unquoted annotations (incl. under `from __future__ import
        # annotations`) are real AST nodes and already counted above;
        # explicitly QUOTED ones (`x: "np.ndarray"`) are string constants
        # and need parsing so their imports count as used
        used |= _string_annotation_names(ctx.tree)
        used |= _all_exports(ctx.tree)

        for name, node, shown in imported:
            if name not in used:
                yield self.finding(
                    ctx, node, f"unused import: {shown!r} (bound as {name!r})")
