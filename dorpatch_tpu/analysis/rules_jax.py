"""JAX-aware rules: DP102 host-sync-in-jit, DP103 PRNG key reuse,
DP104 literal PRNGKey seeds, DP105 unwrapped jax.jit call sites,
DP107 host syncs in serve/ outside the marshalling point,
DP108 hand-rolled counter state in serve//farm/ outside the registry.

What these protect (PAPER.md "EOT inner loop", ROADMAP north star):

- DP102: a jitted entry point that syncs to the host (`.item()`,
  `float()`/`int()` on a traced array, `np.asarray`, `jax.device_get`,
  `block_until_ready`) either fails at trace time or — worse — silently
  forces a device round-trip per step, destroying TPU throughput.
- DP103: EOT transform/occlusion sampling is i.i.d. only if every
  `jax.random.*` consumer gets a fresh key; feeding the same key variable to
  two consumers without an intervening `split` correlates the draws.
- DP104: seeds must flow from `config.py` (reproducibility is config-keyed,
  like the results-dir contract); a hard-coded `PRNGKey(<int>)` forks the
  seed universe. `utils.py` (the seed root) and tests are exempt.
- DP105: the PR 1 telemetry contract — every `jax.jit` entry point is
  wrapped in `observe.timed_first_call` so its trace+compile wall time lands
  in events.jsonl as a `compile` record (and, under `--sanitize`, so the
  recompile-budget watchdog can see its cache growth).
- DP107: the serving worker loop must stay sync-free — a `.item()` /
  `jax.device_get` / `block_until_ready` anywhere in `serve/` other than
  the designated `marshal_response` function stalls the dispatch pipeline
  per batch and silently serializes the micro-batching hot path. (DP102
  can't see these: serving code is eager host code, not jitted bodies.)
- DP108: fleet accounting reads ONE typed registry (`observe.metrics`) —
  a hand-rolled `self.completed += 1` in serve/ or farm/ is a counter the
  `/metrics` exposition, `/stats`, the report CLI and the loadgen
  cross-check can never see, so the books silently fork. Control state
  that is genuinely not a metric carries a reasoned `# noqa: DP108`.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from dorpatch_tpu.analysis.engine import FileContext, Finding, Rule, register

_JIT_TARGETS = {"jax.jit", "jax.pmap"}
_LOOP_TARGETS = {"jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop"}
_PARTIAL_TARGETS = {"functools.partial", "partial"}

# jax.random.* functions that are not draw-consumers of their key argument:
# constructors, key plumbing, and `split`/`fold_in` (which *derive* keys).
_NON_CONSUMERS = {"PRNGKey", "key", "key_data", "wrap_key_data", "key_impl",
                  "split", "fold_in", "clone"}


def _parent_map(tree: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _is_jit_expr(ctx: FileContext, node: ast.AST) -> bool:
    """True for an expression that evaluates to jax.jit: `jax.jit` itself or
    `partial(jax.jit, ...)` (decorator idiom for static_argnums etc.)."""
    if ctx.resolve(node) in _JIT_TARGETS:
        return True
    if (isinstance(node, ast.Call)
            and ctx.resolve(node.func) in _PARTIAL_TARGETS
            and node.args and ctx.resolve(node.args[0]) in _JIT_TARGETS):
        return True
    return False


def _jit_context_functions(ctx: FileContext) -> List[ast.AST]:
    """Function/lambda nodes whose bodies execute under trace: jit-decorated
    defs, defs passed to `jax.jit(...)`, and `lax.scan`/`fori_loop`/
    `while_loop` body functions (by local name or inline lambda)."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    contexts: List[ast.AST] = []
    seen: Set[int] = set()

    def add(node: Optional[ast.AST]) -> None:
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            contexts.append(node)

    def add_ref(arg: ast.AST) -> None:
        if isinstance(arg, ast.Lambda):
            add(arg)
        elif isinstance(arg, ast.Name):
            for d in defs_by_name.get(arg.id, []):
                add(d)

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(ctx, dec) for dec in node.decorator_list):
                add(node)
        elif isinstance(node, ast.Call):
            target = ctx.resolve(node.func)
            if target in _JIT_TARGETS and node.args:
                add_ref(node.args[0])
            elif target in _LOOP_TARGETS:
                # scan(body, ...) / while_loop(cond, body, ...) /
                # fori_loop(lo, hi, body, ...): every callable positional
                # argument is a traced body
                for arg in node.args:
                    add_ref(arg)
    return contexts


def _mentions_static_attr(node: ast.AST) -> bool:
    """Heuristic: expressions over `.shape`/`.ndim`/`.size` or `len()` are
    static under trace — `int(x.shape[0])` is fine inside jit."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                           "size", "dtype"):
            return True
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            return True
    return False


@register
class HostSyncInJitRule(Rule):
    id = "DP102"
    name = "host-sync-in-jit"
    description = ("host-synchronizing call inside a jax.jit-decorated "
                   "function or lax.scan/fori_loop/while_loop body")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        emitted: Set[Tuple[int, int, str]] = set()
        for fn in _jit_context_functions(ctx):
            for node in ast.walk(fn):
                msg = self._offense(ctx, node)
                if msg is None:
                    continue
                key = (node.lineno, node.col_offset, msg)
                if key in emitted:
                    continue
                emitted.add(key)
                yield self.finding(ctx, node, msg)

    def _offense(self, ctx: FileContext, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item":
                return ".item() forces a device->host sync under trace"
            if node.func.attr == "block_until_ready":
                return "block_until_ready() is a host sync — illegal under trace"
        target = ctx.resolve(node.func)
        if target in ("jax.device_get", "jax.block_until_ready"):
            return f"{target}() is a host sync — illegal under trace"
        if target in ("numpy.asarray", "numpy.array"):
            return (f"{target}() materializes a traced array on the host; "
                    "use jnp inside jit")
        if (isinstance(node.func, ast.Name) and node.func.id in ("float", "int")
                and len(node.args) == 1):
            arg = node.args[0]
            if isinstance(arg, ast.Constant) or _mentions_static_attr(arg):
                return None
            return (f"{node.func.id}() on a (likely traced) value is a "
                    "concretization host sync under trace")
        return None


class _KeyScopeWalker:
    """Linear-order key-use tracker for one function (or module) scope.

    State is the set of key variable names already fed to a `jax.random.*`
    consumer; a second consumer use without an intervening REBINDING of
    that name (the split idiom `key, sub = jax.random.split(key)`, or any
    other assignment) is a DP103 offense — an unbound `split(key)` call
    does not refresh the name. `if`/`else` branches each run against a copy
    of the state and merge by replacing with the union of branch-final
    states (consumed on any path stays consumed; rebound on every path is
    fresh). Loop bodies are walked twice so loop-invariant reuse across
    iterations is caught. Nested function bodies are separate scopes,
    walked independently by the rule.
    """

    def __init__(self, rule: "KeyReuseRule", ctx: FileContext):
        self.rule = rule
        self.ctx = ctx
        self.findings: List[Finding] = []

    def walk_scope(self, body: List[ast.stmt]) -> None:
        self._walk_body(body, set())

    def _walk_body(self, body: List[ast.stmt], used: Set[str]) -> None:
        for stmt in body:
            self._walk_stmt(stmt, used)

    def _walk_stmt(self, stmt: ast.stmt, used: Set[str]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scope
        if isinstance(stmt, ast.If):
            self._scan_exprs([stmt.test], used)  # test evaluates first
            branch_states = []
            for branch in (stmt.body, stmt.orelse):
                s = set(used)
                self._walk_body(branch, s)
                branch_states.append(s)
            # REPLACE with the union of branch-final states: consumed on any
            # path stays consumed, but a key re-derived (split/rebound) in
            # every branch is genuinely fresh afterwards
            used.clear()
            used.update(branch_states[0] | branch_states[1])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            # two passes over the body: the second models the next iteration,
            # catching the canonical loop-invariant reuse (`for i in ...:
            # jax.random.normal(key, ...)` draws correlated samples every
            # pass). Duplicate findings from re-walking dedupe in check().
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_exprs([stmt.iter], used)  # iter evaluates ONCE
                for _ in range(2):
                    # the loop target rebinds each iteration (e.g. `for key
                    # in jax.random.split(master, n):`) — fresh every pass
                    for name in self._names_in(stmt.target):
                        used.discard(name)
                    self._walk_body(stmt.body, used)
            else:
                for _ in range(2):  # a while-test re-evaluates per pass
                    self._scan_exprs([stmt.test], used)
                    self._walk_body(stmt.body, used)
            self._walk_body(stmt.orelse, used)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(stmt.body, used)
            for h in stmt.handlers:
                self._walk_body(h.body, used)
            self._walk_body(stmt.orelse, used)
            self._walk_body(stmt.finalbody, used)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._scan_exprs([i.context_expr for i in stmt.items], used)
            for item in stmt.items:  # `with ... as key:` rebinds
                if item.optional_vars is not None:
                    for name in self._names_in(item.optional_vars):
                        used.discard(name)
            self._walk_body(stmt.body, used)
            return
        # simple statement: consumer calls first (RHS evaluates before the
        # store), then name bindings reset their state
        self._scan_exprs([stmt], used)
        for name in self._stored_names(stmt):
            used.discard(name)

    @staticmethod
    def _walk_without_lambdas(root: ast.AST):
        """ast.walk, but do not descend into lambda bodies: a lambda's draws
        happen at CALL time, not at the definition site, and each lambda is
        already collected as its own scope by KeyReuseRule.check."""
        stack = [root]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, ast.Lambda):
                    stack.append(child)

    def _scan_exprs(self, nodes: List[ast.AST], used: Set[str]) -> None:
        for root in nodes:
            if root is None:
                continue
            for node in self._walk_without_lambdas(root):
                if not isinstance(node, ast.Call):
                    continue
                target = self.ctx.resolve(node.func)
                if not target or not target.startswith("jax.random."):
                    continue
                tail = target.rsplit(".", 1)[1]
                if not node.args or not isinstance(node.args[0], ast.Name):
                    continue
                key_name = node.args[0].id
                if tail not in _NON_CONSUMERS:
                    # NOTE: `split`/`fold_in`/`clone` are non-consumers but
                    # do NOT refresh the name by themselves — only REBINDING
                    # does (`key, sub = split(key)`), which the stored-names
                    # pass handles. `use(key); split(key); use(key)` keeps
                    # consuming the same key and still flags.
                    if key_name in used:
                        self.findings.append(self.rule.finding(
                            self.ctx, node,
                            f"key {key_name!r} already consumed by a "
                            f"jax.random call — split it before jax.random."
                            f"{tail} (EOT draws must stay i.i.d.)"))
                    else:
                        used.add(key_name)

    @staticmethod
    def _stored_names(stmt: ast.stmt) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                names.add(node.id)
        return names

    @staticmethod
    def _names_in(target: ast.AST) -> Set[str]:
        """All Name identifiers in a binding target (handles tuples)."""
        return {n.id for n in ast.walk(target) if isinstance(n, ast.Name)}


@register
class KeyReuseRule(Rule):
    id = "DP103"
    name = "prng-key-reuse"
    description = ("same PRNG key variable fed to two jax.random.* "
                   "consumers without an intervening split")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        scopes: List[List[ast.stmt]] = [ctx.tree.body]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
            elif isinstance(node, ast.Lambda):
                scopes.append([ast.Expr(value=node.body)])
        seen = set()
        for body in scopes:
            w = _KeyScopeWalker(self, ctx)
            w.walk_scope(body)
            for f in w.findings:
                # the loop-body second pass re-visits call sites; one
                # finding per location
                if (f.line, f.col) not in seen:
                    seen.add((f.line, f.col))
                    yield f


@register
class LiteralSeedRule(Rule):
    id = "DP104"
    name = "literal-prng-seed"
    description = ("literal jax.random.PRNGKey(<int>) outside utils.py/"
                   "tests — seeds must flow from config.py (via "
                   "utils.set_global_seed / utils.global_key)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.in_tests():
            return
        # only the package-root utils.py (home of set_global_seed/global_key)
        # may construct literal keys — not any file that happens to be
        # named utils.py deeper in the tree
        if ctx.scoped_parts == ("utils.py",):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.resolve(node.func) not in ("jax.random.PRNGKey",
                                              "jax.random.key"):
                continue
            if (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, int)):
                yield self.finding(
                    ctx, node,
                    f"hard-coded PRNGKey({node.args[0].value!r}) — derive "
                    "the key from the config seed (utils.global_key)")


@register
class UnwrappedJitRule(Rule):
    id = "DP105"
    name = "unwrapped-jit"
    description = ("jax.jit entry point not wrapped by "
                   "observe.timed_first_call — its compile time is invisible "
                   "to the telemetry layer and the recompile watchdog")

    _MSG = ("jax.jit call site not wrapped by observe.timed_first_call "
            "(PR 1 telemetry contract: compile wall time must land in "
            "events.jsonl)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        parents = _parent_map(ctx.tree)

        wrapped_names: Set[str] = set()
        wrapped_nodes: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = ctx.resolve(node.func)
            if not target or not target.split(".")[-1] == "timed_first_call":
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                wrapped_names.add(arg.id)
            else:
                wrapped_nodes.add(id(arg))

        # call-form sites: jax.jit(fn, ...)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.resolve(node.func) in _JIT_TARGETS):
                continue
            if id(node) in wrapped_nodes:
                continue
            parent = parents.get(id(node))
            bound = self._bound_name(parent, node)
            if bound is not None and bound in wrapped_names:
                continue
            if self._is_decorator(parents, node):
                fn = self._decorated_function(parents, node)
                if fn is not None and fn.name in wrapped_names:
                    continue
            yield self.finding(ctx, node, self._MSG)

        # decorator-form sites: @jax.jit / @partial(jax.jit, ...)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    # `@jax.jit` as bare attribute handled here; the Call
                    # forms (`@partial(jax.jit, ...)`, `@jax.jit(...)`) were
                    # already covered by the call-form walk above
                    if not (ctx.resolve(dec.func) in _PARTIAL_TARGETS
                            and dec.args
                            and ctx.resolve(dec.args[0]) in _JIT_TARGETS):
                        continue
                elif ctx.resolve(dec) not in _JIT_TARGETS:
                    continue
                if node.name in wrapped_names:
                    continue
                yield self.finding(ctx, dec, self._MSG)

    @staticmethod
    def _bound_name(parent: Optional[ast.AST], node: ast.AST) -> Optional[str]:
        if isinstance(parent, ast.Assign) and parent.value is node \
                and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            return parent.targets[0].id
        if isinstance(parent, (ast.AnnAssign, ast.AugAssign)) \
                and getattr(parent, "value", None) is node \
                and isinstance(parent.target, ast.Name):
            return parent.target.id
        return None

    @staticmethod
    def _is_decorator(parents: Dict[int, ast.AST], node: ast.AST) -> bool:
        parent = parents.get(id(node))
        return isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            and node in parent.decorator_list

    @staticmethod
    def _decorated_function(parents: Dict[int, ast.AST], node: ast.AST):
        parent = parents.get(id(node))
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return parent
        return None


@register
class ServeHostSyncRule(Rule):
    id = "DP107"
    name = "serve-host-sync"
    description = ("blocking host sync (.item()/device_get/"
                   "block_until_ready) inside serve/ outside the designated "
                   "response-marshalling function")

    #: The ONE function in serve/ allowed to synchronize device results to
    #: the host (`serve.service.marshal_response`). Everything else in the
    #: worker-loop path must stay dispatch-only, or every batch stalls the
    #: pipeline mid-flight and the micro-batcher serializes.
    MARSHAL_FUNCTION = "marshal_response"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_package() or "serve" not in ctx.scoped_parts:
            return
        # module-level statements sync too (import-time device pulls)
        for node in self._own_nodes(ctx.tree):
            msg = self._offense(ctx, node)
            if msg is not None:
                yield self.finding(ctx, node, msg)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name == self.MARSHAL_FUNCTION:
                continue
            for node in self._own_nodes(fn):
                msg = self._offense(ctx, node)
                if msg is not None:
                    yield self.finding(ctx, node, msg)

    @staticmethod
    def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
        """Walk a function body WITHOUT descending into nested defs (each
        nested def is visited — and possibly exempted — on its own)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _offense(self, ctx: FileContext, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        tail = (f" — only {self.MARSHAL_FUNCTION}() may sync to the host "
                "in serve/")
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item":
                return (".item() blocks the serving worker on a device "
                        "round-trip" + tail)
            if node.func.attr == "block_until_ready":
                return "block_until_ready() stalls the dispatch pipeline" \
                    + tail
        target = ctx.resolve(node.func)
        if target in ("jax.device_get", "jax.block_until_ready"):
            return f"{target}() blocks the serving worker" + tail
        if target in ("numpy.asarray", "numpy.array"):
            # the codebase's canonical sync spelling: blocking when fed a
            # device array. Host-data parsing that needs it carries a
            # reasoned `# noqa: DP107`.
            return (f"{target}() materializes a device array on the host "
                    "when fed one" + tail)
        return None


@register
class AdHocCounterRule(Rule):
    id = "DP108"
    name = "adhoc-counter-state"
    description = ("hand-rolled counter/gauge mutation in serve//farm/ "
                   "outside observe.metrics — accounting the /metrics "
                   "exposition and the fleet cross-check cannot see")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_package():
            return
        if not {"serve", "farm"} & set(ctx.scoped_parts):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AugAssign):
                continue
            if not isinstance(node.op, (ast.Add, ast.Sub)):
                continue
            spelled = self._attr_target(node.target)
            if spelled is None:
                continue
            yield self.finding(
                ctx, node,
                f"`{spelled} {'+=' if isinstance(node.op, ast.Add) else '-='}"
                f" ...` is counter state outside the metric registry — "
                f"route it through observe.metrics (MetricRegistry.counter/"
                f"gauge) so /metrics, /stats and the report CLI read one "
                f"set of books, or mark genuine control state with a "
                f"reasoned `# noqa: DP108`")

    @staticmethod
    def _attr_target(target: ast.AST) -> Optional[str]:
        """The flagged spelling for attribute-state mutations: `x.attr` and
        `x.attr[key]`. Plain locals (`n += 1`) and Name-rooted subscripts
        (`counts[k] += 1` on a local dict) are loop bookkeeping, not
        published state, and stay exempt."""
        if isinstance(target, ast.Attribute):
            return f"<obj>.{target.attr}"
        if isinstance(target, ast.Subscript) \
                and isinstance(target.value, ast.Attribute):
            return f"<obj>.{target.value.attr}[...]"
        return None
