"""AST rule engine: file contexts, the rule registry, noqa suppressions.

The framework's correctness invariants (host-sync-free jit bodies, split-
before-reuse PRNG discipline, observe-routed output) are not checkable by a
generic linter — they need JAX-aware rules. PR 1 enforced one of them with a
bespoke tokenize pass (`tests/test_print_guard.py`); this module is that idea
grown into a real static-analysis layer: rules are small `ast.NodeVisitor`
subclasses registered under stable `DPxxx` IDs, files are parsed once into a
`FileContext` (tree + import-alias map + per-line suppressions), and every
rule runs over the shared context.

The engine's own logic is deliberately stdlib-only (ast + tokenize) and
never touches a jax API, so linting cannot initialize — and on shared
accelerators, claim — a backend. (Importing this module does pull jax into
the process transitively, via the parent package's config imports; import
alone does not initialize any backend.)

Suppression syntax (flake8-compatible):

    x = jax.random.PRNGKey(0)  # noqa: DP104 — fixed seed is the point here
    from foo import bar        # noqa          (blanket: all rules)
    from foo import baz        # noqa: F401    (alias for DP106)

Codes are matched per finding line; unknown codes are ignored. `F401` is
accepted as an alias for DP106 so existing re-export annotations keep
working.

Path scoping: rules that are scoped to the package (DP101) or exempt certain
locations (DP104) decide from the *logical* path — normally the scanned path
itself, but overridable via `analyze_file(..., logical_path=...)` so tests
can exercise path-scoped rules on fixture files living elsewhere. When the
path contains a `dorpatch_tpu` component, only the components AFTER it are
scope-significant — a checkout under e.g. `/data/tests/repo/` must not
disable rules for the whole package.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import pathlib
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Union

#: Sentinel for a blanket `# noqa` (suppresses every rule on the line).
ALL_CODES = "ALL"

#: Codes accepted as aliases for our stable IDs (flake8 compatibility).
CODE_ALIASES = {"F401": "DP106"}

_NOQA_RE = re.compile(r"#\s*noqa\b(?P<codes>\s*:[^#]*)?", re.IGNORECASE)
# case-insensitive like flake8: `# noqa: dp104` suppresses DP104, it does
# NOT degrade to a blanket suppression of every rule on the line
_CODE_RE = re.compile(r"\b[A-Za-z]{1,3}\d{3}\b")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule offense at a source location."""

    path: str
    line: int
    col: int
    rule_id: str
    message: str
    fixable: bool = False

    def render(self) -> str:
        tail = "  [fixable]" if self.fixable else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}{tail}"


def _parse_noqa(source: str) -> Dict[int, Union[str, Set[str]]]:
    """line -> ALL_CODES (blanket) or the set of suppressed rule IDs."""
    out: Dict[int, Union[str, Set[str]]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for line, text in comments:
        m = _NOQA_RE.search(text)
        if not m:
            continue
        codes_part = m.group("codes")
        if not codes_part:
            out[line] = ALL_CODES
            continue
        codes = {CODE_ALIASES.get(c.upper(), c.upper())
                 for c in _CODE_RE.findall(codes_part)}
        # `# noqa:` with no parseable code degrades to a blanket suppression
        # (matching flake8), rather than silently suppressing nothing
        out[line] = codes or ALL_CODES
    return out


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local name -> fully dotted module path, for resolving call targets.

    `import numpy as np` -> {"np": "numpy"}; `from jax import random as jr`
    -> {"jr": "jax.random"}; `from jax.random import split` ->
    {"split": "jax.random.split"}. Relative imports are left unresolved
    (their targets are in-package, never jax/numpy).
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST) -> Optional[str]:
    """`jax.random.uniform` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class FileContext:
    """Everything a rule needs about one parsed file."""

    def __init__(self, path: str, source: str,
                 logical_path: Optional[str] = None):
        self.path = path
        self.source = source
        self.logical_path = logical_path or path
        self.parts = tuple(pathlib.PurePath(self.logical_path).parts)
        # scope decisions ignore everything up to (and including) the LAST
        # `dorpatch_tpu` component: an absolute checkout prefix that happens
        # to contain `tests`/`observe` must not flip path-scoped rules
        if "dorpatch_tpu" in self.parts:
            last = len(self.parts) - 1 - self.parts[::-1].index("dorpatch_tpu")
            self.scoped_parts = self.parts[last + 1:]
        else:
            self.scoped_parts = self.parts
        self.tree = ast.parse(source, filename=path)
        self.noqa = _parse_noqa(source)
        self.aliases = _import_aliases(self.tree)

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Fully-qualified dotted name of a call target, through the file's
        import aliases: with `from jax import random as jr`, the node for
        `jr.uniform` resolves to "jax.random.uniform"."""
        name = dotted_name(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        full = self.aliases.get(head)
        if full is None:
            return name
        return f"{full}.{rest}" if rest else full

    def in_package(self) -> bool:
        """True when the logical path lies inside the dorpatch_tpu package.

        Scoped: a CHECKOUT directory that happens to be named dorpatch_tpu
        must not pull the repo-level siblings (`tools/`, `tests/`) into
        package scope."""
        if "dorpatch_tpu" not in self.parts:
            return False
        return bool(self.scoped_parts) and \
            self.scoped_parts[0] not in ("tools", "tests")

    def in_observe(self) -> bool:
        """True inside the package's observe/ subpackage (checkout-prefix
        directories named `observe` don't count — see scoped_parts)."""
        return "observe" in self.scoped_parts

    def in_tests(self) -> bool:
        """True for test-tree files (path under a `tests` component after
        any package prefix)."""
        return "tests" in self.scoped_parts

    def suppressed(self, line: int, rule_id: str) -> bool:
        codes = self.noqa.get(line)
        if codes is None:
            return False
        return codes == ALL_CODES or rule_id in codes


class Rule:
    """Base class: subclasses set the class attributes and implement
    `check`, usually by running an `ast.NodeVisitor` over `ctx.tree`."""

    id: str = ""
    name: str = ""
    description: str = ""
    fixable: bool = False

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str) -> Finding:
        return Finding(path=ctx.path, line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       rule_id=self.id, message=message, fixable=self.fixable)


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate and register a rule under its stable ID."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> List[Rule]:
    _ensure_rules_loaded()
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    _ensure_rules_loaded()
    return _REGISTRY[rule_id]


def _ensure_rules_loaded() -> None:
    # Rule modules self-register on import; importing here (not at module
    # top) keeps engine importable from the rule modules themselves.
    from dorpatch_tpu.analysis import (concurrency, rules_jax,  # noqa: F401
                                       rules_output)


def analyze_source(source: str, path: str = "<string>",
                   logical_path: Optional[str] = None,
                   select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run (selected) rules over one source blob; suppressions applied.

    A file that does not parse yields a single DP000 finding — a syntax
    error must fail the lint gate loudly, not vanish."""
    try:
        ctx = FileContext(path, source, logical_path)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=(e.offset or 0) + 1,
                        rule_id="DP000", message=f"syntax error: {e.msg}")]
    findings: List[Finding] = []
    for rule in all_rules():
        if select is not None and rule.id not in select:
            continue
        for f in rule.check(ctx):
            if not ctx.suppressed(f.line, f.rule_id):
                findings.append(f)
    return sorted(findings)


def analyze_file(path: Union[str, pathlib.Path],
                 logical_path: Optional[str] = None,
                 select: Optional[Sequence[str]] = None) -> List[Finding]:
    p = pathlib.Path(path)
    # explicit utf-8: the gate must not depend on the runner's locale
    # (LANG=C would decode as ASCII and crash on any non-ASCII comment)
    return analyze_source(p.read_text(encoding="utf-8"), str(p),
                          logical_path, select)


def iter_python_files(paths: Iterable[Union[str, pathlib.Path]]) -> Iterator[pathlib.Path]:
    """Expand files/directories into a sorted stream of .py files
    (skipping __pycache__ and hidden directories)."""
    for raw in paths:
        p = pathlib.Path(raw)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                # "." / ".." path components are navigation, not hidden dirs
                if any(part == "__pycache__"
                       or (part.startswith(".") and part not in (".", ".."))
                       for part in f.parts):
                    continue
                yield f
        else:
            yield p


def analyze_paths(paths: Iterable[Union[str, pathlib.Path]],
                  select: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(analyze_file(f, select=select))
    return findings
