"""`python -m dorpatch_tpu.analysis` entry point."""

import sys

from dorpatch_tpu.analysis.cli import main

sys.exit(main())
