"""Mechanical fixer for DP106 (unused import), the one rule flagged
`fixable` — `python -m dorpatch_tpu.analysis --fix [--diff]`.

The fixer re-runs the DP106 rule itself (so `# noqa` suppressions, `__all__`
re-exports, and string-annotation uses are honored exactly as the lint gate
honors them), maps each finding back to its import statement, and rewrites
the statement keeping only the used aliases — dropping the whole statement
when nothing survives. Regenerated statements are canonical single-line
imports (parenthesized and wrapped when they would exceed 79 columns);
comments inside a rewritten statement are not preserved, since a comment
naming dropped imports would be stale anyway. A statement that shares a
physical line with any other statement (`import os; x = 1`) is left alone
rather than risk clobbering its neighbor.

Fixing is idempotent by construction: the second pass re-lints the rewritten
source, finds zero DP106 findings, and changes nothing
(`tests/test_analysis.py::test_fix_idempotent`).
"""

from __future__ import annotations

import ast
import difflib
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple, Union

from dorpatch_tpu.analysis.engine import (
    analyze_source,
    iter_python_files,
)

_BOUND_RE = re.compile(r"\(bound as '([^']+)'\)")


def _bound_name(message: str) -> Optional[str]:
    m = _BOUND_RE.search(message)
    return m.group(1) if m else None


def _alias_text(alias: ast.alias) -> str:
    return f"{alias.name} as {alias.asname}" if alias.asname else alias.name


def _regenerate(node: Union[ast.Import, ast.ImportFrom],
                keep: List[ast.alias], indent: str) -> str:
    names = ", ".join(_alias_text(a) for a in keep)
    if isinstance(node, ast.Import):
        line = f"{indent}import {names}"
    else:
        module = "." * node.level + (node.module or "")
        line = f"{indent}from {module} import {names}"
    if len(line) <= 79:
        return line + "\n"
    # wrap: one alias per line inside parentheses (ImportFrom only; a plain
    # `import` this long is vanishingly rare and stays on one line)
    if isinstance(node, ast.ImportFrom):
        module = "." * node.level + (node.module or "")
        body = "".join(f"{indent}    {_alias_text(a)},\n" for a in keep)
        return f"{indent}from {module} import (\n{body}{indent})\n"
    return line + "\n"


def fix_source(source: str, path: str = "<string>",
               logical_path: Optional[str] = None) -> Tuple[str, int]:
    """Remove DP106-flagged imports from `source`; returns
    `(fixed_source, n_removed)`. The input comes back unchanged (and 0)
    when there is nothing to fix — including when it does not parse."""
    findings = analyze_source(source, path, logical_path, select=["DP106"])
    findings = [f for f in findings if f.rule_id == "DP106"]
    if not findings:
        return source, 0
    tree = ast.parse(source, filename=path)

    # finding line -> bound names to drop there
    drop: Dict[int, Set[str]] = {}
    for f in findings:
        name = _bound_name(f.message)
        if name:
            drop.setdefault(f.line, set()).add(name)

    # each import statement owns the line span [lineno, end_lineno]; a span
    # shared with any OTHER statement (semicolon compounds) is untouchable
    stmts = [n for n in ast.walk(tree) if isinstance(n, ast.stmt)]
    # statement -> the block body list that owns it, so a whole-statement
    # removal that would EMPTY an indented block leaves `pass` behind
    # (deleting the sole statement of `def f():` writes invalid Python)
    owner: Dict[int, Tuple[ast.AST, list]] = {}
    for container in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(container, field, None)
            if isinstance(block, list):
                for s in block:
                    if isinstance(s, ast.stmt):
                        owner[id(s)] = (container, block)

    lines = source.splitlines(keepends=True)
    n_removed = 0
    edits: List[Tuple[int, int, str, ast.stmt]] = []
    emptied: List[ast.stmt] = []  # whole-statement removals
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        names = drop.get(node.lineno)
        if not names:
            continue
        span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
        # another statement starting inside this span = a semicolon compound
        # (`import os; x = 1`) or a one-line suite (`if x: import os`) —
        # line surgery would clobber the neighbor, so leave the finding
        if any(other is not node and other.lineno in span
               for other in stmts):
            continue
        keep = [a for a in node.names
                if (a.asname or a.name.split(".")[0]) not in names]
        n_removed += len(node.names) - len(keep)
        first = lines[node.lineno - 1]
        indent = first[:len(first) - len(first.lstrip())]
        text = _regenerate(node, keep, indent) if keep else ""
        if not keep:
            emptied.append(node)
        edits.append((node.lineno - 1, (node.end_lineno or node.lineno),
                      text, node))

    # a block whose every statement is being removed gets one `pass` (an
    # empty MODULE is legal, an empty indented suite is a SyntaxError)
    removed_ids = {id(n) for n in emptied}
    needs_pass: set = set()
    for node in emptied:
        container, block = owner.get(id(node), (None, []))
        if container is None or isinstance(container, ast.Module):
            continue
        if all(id(s) in removed_ids for s in block):
            needs_pass.add(id(min(block, key=lambda s: s.lineno)))
    final: List[Tuple[int, int, str]] = []
    for start, end, text, node in edits:
        if not text and id(node) in needs_pass:
            first = lines[start]
            indent = first[:len(first) - len(first.lstrip())]
            text = f"{indent}pass\n"
        final.append((start, end, text))

    for start, end, text in sorted(final, reverse=True):
        lines[start:end] = [text] if text else []
    return "".join(lines), n_removed


def fix_file(path: Union[str, pathlib.Path], write: bool = True,
             logical_path: Optional[str] = None) -> Tuple[int, str]:
    """Fix one file; returns `(n_removed, unified_diff)`. Writes back only
    when `write` and something changed."""
    p = pathlib.Path(path)
    source = p.read_text(encoding="utf-8")
    fixed, n = fix_source(source, str(p), logical_path)
    if n == 0:
        return 0, ""
    diff = "".join(difflib.unified_diff(
        source.splitlines(keepends=True), fixed.splitlines(keepends=True),
        fromfile=str(p), tofile=f"{p} (fixed)"))
    if write:
        p.write_text(fixed, encoding="utf-8")
    return n, diff


def fix_paths(paths: Iterable[Union[str, pathlib.Path]],
              write: bool = True) -> Tuple[int, int, List[str]]:
    """Fix every python file under `paths`; returns
    `(files_changed, imports_removed, diffs)`."""
    files = 0
    total = 0
    diffs: List[str] = []
    for f in iter_python_files(paths):
        n, diff = fix_file(f, write=write)
        if n:
            files += 1
            total += n
            diffs.append(diff)
    return files, total, diffs
