"""Entry-point registry: the exact jit programs production compiles.

The trace-level auditor (`program.py`) needs two things the AST rules never
do: the *callable* for every jitted entry point, and abstract example
arguments to trace it with. This module supplies both:

- **Discovery** — `observe.timed_first_call` reports every wrap (and every
  call) through the recorder hook (`observe.set_entrypoint_recorder`), so
  constructing a subsystem under `capture_entrypoints()` records the exact
  `(name, fn)` pairs production registers with the telemetry layer. A
  timed entry point the enumerators construct but never attach example
  args to is *discovered but unauditable* — the audit fails loudly on it
  (DP200) instead of silently skipping the program.
- **Registration** — `register_entrypoint(fn, args=...)` attaches abstract
  example args (``jax.ShapeDtypeStruct`` pytrees, via `abstractify` /
  `jax.eval_shape`) to a discovered wrapper, or registers a non-timed jit
  directly under an explicit name.
- **Enumeration** — `production_entrypoints()` constructs (without ever
  executing) the programs the production stack compiles: the attack
  stage-0/1 block and sweep programs, the per-radius defense
  predict/certify tables, the incremental certify programs (the
  token-pruned ViT phase1/pairs/rows and the stem-folded conv phase 1,
  one bank per engine family), the train init/step/eval programs, the
  jitted model initializer, the serve bucket programs, and (on
  multi-device hosts) the shard_map'd masked-fill gradient with its
  mask-axis psum.
  Example args are `ShapeDtypeStruct`s throughout — enumeration costs
  tracing only, no device FLOPs — with the victim scaled to the small
  CIFAR family so the gate stays CPU-cheap while exercising the exact
  production code paths.

Unlike the AST wing this module (and everything it enumerates) imports
jax; only `--trace` audits and tests load it.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from dorpatch_tpu import observe


@dataclasses.dataclass
class EntryPoint:
    """One auditable jit entry point: the (unwrapped) callable plus the
    abstract example args `jax.make_jaxpr` traces it with. `kwargs` values
    and non-array `args` leaves pass through concrete (static args)."""

    name: str
    fn: Callable
    args: Tuple[Any, ...] = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    #: "registered" (explicit example args) or "captured" (args recorded
    #: from a live call through the timed_first_call wrapper)
    source: str = "registered"


#: name -> EntryPoint with example args attached (auditable)
_REGISTRY: Dict[str, EntryPoint] = {}
#: every name seen through a timed_first_call wrap (discoverability ledger)
_WRAPPED: Dict[str, Callable] = {}
#: base name -> the recompile_budget its timed_first_call wrap declared
#: (None = undeclared); feeds the baseline tier's DP303 consistency check
_BUDGETS: Dict[str, Optional[int]] = {}
#: base name -> the bucket-ladder length the constructing subsystem
#: registered (`register_bucket_ladder`); the ground truth DP303 compares
#: declared budgets against
_LADDERS: Dict[str, int] = {}


def abstractify(tree):
    """Pytree of values -> pytree of `ShapeDtypeStruct`s (weak_type
    preserved — the carry-stability rule depends on it); non-array leaves
    (python ints/bools, None) pass through as static values."""
    import jax

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        return jax.ShapeDtypeStruct(
            tuple(shape), dtype, weak_type=bool(getattr(x, "weak_type", False)))

    return jax.tree_util.tree_map(leaf, tree)


def _unwrap(fn: Callable) -> Callable:
    """Strip `timed_first_call` wrappers — and ONLY those. The jit object
    underneath must survive: it carries the static_argnums/donate_argnums
    the audit traces with (`Traced.args_info`), and unwrapping past it
    would re-abstract static arguments."""
    from dorpatch_tpu.observe.events import _FirstCallTimer

    while isinstance(fn, _FirstCallTimer):
        fn = fn.__wrapped__
    return fn


def register_entrypoint(fn: Callable, args: Tuple[Any, ...] = (),
                        kwargs: Optional[Dict[str, Any]] = None,
                        name: Optional[str] = None) -> EntryPoint:
    """Attach abstract example args to a jit entry point.

    `fn` may be a `timed_first_call` wrapper (its registered telemetry name
    is reused) or a bare jitted callable (pass `name`). Array-like leaves in
    `args`/`kwargs` are abstractified; the program is never executed."""
    resolved = name or getattr(fn, "_name", None) or getattr(
        fn, "__name__", None)
    if not resolved:
        raise ValueError(f"cannot derive a name for entry point {fn!r}")
    ep = EntryPoint(name=resolved, fn=_unwrap(fn),
                    args=tuple(abstractify(a) for a in args),
                    kwargs={k: abstractify(v)
                            for k, v in (kwargs or {}).items()})
    _REGISTRY[resolved] = ep
    return ep


def registered_entrypoints() -> List[EntryPoint]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def wrapped_names() -> List[str]:
    """Every entry-point name discovered through a timed_first_call wrap
    since the last `clear_entrypoints` (capture scope)."""
    return sorted(_WRAPPED)


def uncovered_names() -> List[str]:
    """Discovered-but-unauditable names: a `timed_first_call` site was
    constructed, but no registration attached example args (bucketed
    registrations like `serve.clean_predict[b8]` cover their base name)."""
    out = []
    for name in sorted(_WRAPPED):
        if name in _REGISTRY:
            continue
        if any(r.startswith(name + "[") for r in _REGISTRY):
            continue
        out.append(name)
    return out


def register_bucket_ladder(name: str, sizes) -> None:
    """Record the bucket ladder a subsystem actually builds for a wrapped
    entry point (e.g. the defense row programs' `row_bucket_sizes`). DP303
    checks the wrap's declared `recompile_budget` against this count; for
    names with no explicit ladder the `name[...]`-variant count in the
    registry is the fallback ground truth."""
    _LADDERS[name] = len(tuple(sizes))


def declared_budgets() -> Dict[str, Optional[int]]:
    """base name -> `recompile_budget` declared at its timed_first_call
    wrap (captured through the recorder's `on_budget` hook)."""
    return dict(_BUDGETS)


def bucket_ladders() -> Dict[str, int]:
    """base name -> explicitly registered bucket-ladder length."""
    return dict(_LADDERS)


def clear_entrypoints() -> None:
    _REGISTRY.clear()
    _WRAPPED.clear()
    _BUDGETS.clear()
    _LADDERS.clear()


class _CaptureRecorder:
    """The `observe.set_entrypoint_recorder` hook: wraps land in the
    discoverability ledger; live calls contribute example args (abstracted
    pre-dispatch) for any entry point not explicitly registered."""

    def on_wrap(self, name: str, fn: Callable) -> None:
        _WRAPPED[name] = fn

    def on_budget(self, name: str, budget: Optional[int]) -> None:
        # last-write-wins: a name wrapped twice (e.g. the defense tables
        # re-wrapped by the serve layer) keeps its most recent declaration,
        # matching which wrapper is actually live
        _BUDGETS[name] = budget

    def on_call(self, name: str, fn: Callable, args, kwargs) -> None:
        _WRAPPED.setdefault(name, fn)
        if name not in _REGISTRY:
            _REGISTRY[name] = EntryPoint(
                name=name, fn=_unwrap(fn),
                args=tuple(abstractify(a) for a in args),
                kwargs={k: abstractify(v) for k, v in kwargs.items()},
                source="captured")


@contextlib.contextmanager
def capture_entrypoints() -> Iterator[None]:
    """Record every `timed_first_call` wrap/call in the scope into the
    registry; restores any previously installed recorder on exit."""
    prev = observe.entrypoint_recorder()
    observe.set_entrypoint_recorder(_CaptureRecorder())
    try:
        yield
    finally:
        observe.set_entrypoint_recorder(prev)


# ---------------------------------------------------------------- enumerators

#: Victim geometry for enumeration: the small CIFAR family keeps the gate's
#: tracing cost in CPU seconds while driving the identical production code
#: paths (the audited invariants — carry stability, dtype discipline, axis
#: names, constant capture — are shape-generic).
AUDIT_IMG_SIZE = 32
AUDIT_BATCH = 2
AUDIT_CLASSES = 10


def _audit_victim():
    """Small real victim with zero-filled params (abstract-init shapes, one
    cheap `jnp.zeros` per leaf): the attack/defense programs close over
    `params`, so the leaves must be concrete arrays — but never random, and
    never forwarded."""
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.models import registry

    model = registry.build_bare_model("cifar_resnet18", AUDIT_CLASSES)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    dummy = jax.ShapeDtypeStruct(
        (1, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    shapes = jax.eval_shape(model.init, key, dummy)
    params = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    def apply(params, images01):
        return model.apply(params, (images01 - 0.5) / 0.5)

    return apply, params


def _enumerate_attack(apply_fn, params) -> None:
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu import losses
    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.attack import DorPatch
    from dorpatch_tpu.config import AttackConfig

    cfg = AttackConfig(sampling_size=8, dropout=1, sweep_interval=50,
                       max_iterations=100)
    atk = DorPatch(apply_fn, params, AUDIT_CLASSES, cfg)
    b, img = AUDIT_BATCH, AUDIT_IMG_SIZE
    universe = abstractify(jnp.asarray(masks_lib.dropout_universe(
        img, cfg.dropout, cfg.dropout_sizes)))
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    x = jax.ShapeDtypeStruct((b, img, img, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((b,), jnp.int32)
    state = jax.eval_shape(
        lambda k, xx, yy: atk._init_state(k, xx, yy, False,
                                          universe.shape[0]), key, x, y)
    lvx = jax.eval_shape(
        lambda xx: jnp.mean(losses.local_variance(xx)[0], axis=-1), x)
    for stage in (0, 1):
        block = atk._get_block(stage, img, cfg.sweep_interval)
        register_entrypoint(block, (state, x, lvx, universe))
    sweep = atk._get_sweep()
    register_entrypoint(
        sweep, (state.adv_mask, state.adv_pattern, x, y,
                jax.ShapeDtypeStruct((b,), jnp.bool_), universe))


def _enumerate_defense(apply_fn, params) -> None:
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import build_defenses

    cfg = DefenseConfig(chunk_size=64)
    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    params_abs = abstractify(params)
    for d in build_defenses(apply_fn, AUDIT_IMG_SIZE, cfg,
                            recompile_budget=1):
        register_entrypoint(d._predict, (params_abs, imgs, AUDIT_CLASSES))
        # the pruned two-phase schedule's programs (defense.prune="exact",
        # the production default): first-round table + pair audit share the
        # image-batch buckets; the ragged second-round row program runs at
        # its own row buckets (declared recompile budget = bucket count on
        # each wrapper)
        register_entrypoint(d._phase1, (params_abs, imgs))
        register_entrypoint(d._pairs, (params_abs, imgs))
        w = int(d.row_bucket_sizes[0])
        imgs_g = jax.ShapeDtypeStruct(
            (w, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
        mask_idx = jax.ShapeDtypeStruct((w,), jnp.int32)
        register_entrypoint(d._rows, (params_abs, imgs_g, mask_idx))
        # the row program's declared recompile_budget is its bucket-ladder
        # length; record the ladder so the baseline tier (DP303) can check
        # the declaration against the ground truth
        register_bucket_ladder(d._rows._name, d.row_bucket_sizes)


def _bf16_params_abs(params):
    """Abstract bf16-cast weight tree: the avals `PatchCleanser._cast_params`
    hands the bf16 bank's programs (floating leaves -> bfloat16, everything
    else passes through)."""
    import jax
    import jax.numpy as jnp

    def leaf(s):
        if jnp.issubdtype(s.dtype, jnp.floating):
            return jax.ShapeDtypeStruct(tuple(s.shape), jnp.bfloat16)
        return s

    return jax.tree_util.tree_map(leaf, abstractify(params))


def _enumerate_bf16_defense(apply_fn, params) -> None:
    """The bf16 certify bank (`DefenseConfig.compute_dtype="bfloat16"`):
    `.bf16`-tagged twins of the per-radius phase1/pairs/rows programs, fed
    the bf16-cast weight avals production's `_cast_params` produces. Images
    stay f32 — the cast happens inside the traced program, so jit cache
    keys never fork on input dtype. `d._predict` is NOT re-registered:
    under bf16 it IS the f32 escalation oracle, the identical program and
    wrapper name the f32 bank already covers. DP301 prices this bank as a
    distinct program set next to the untagged twins; the smoke gate
    (`tools/certify_bf16_smoke.py`) asserts strictly fewer bytes entry by
    entry."""
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import build_defenses

    cfg = DefenseConfig(chunk_size=64, compute_dtype="bfloat16")
    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    cast_abs = _bf16_params_abs(params)
    for d in build_defenses(apply_fn, AUDIT_IMG_SIZE, cfg,
                            recompile_budget=1):
        register_entrypoint(d._phase1, (cast_abs, imgs))
        register_entrypoint(d._pairs, (cast_abs, imgs))
        w = int(d.row_bucket_sizes[0])
        imgs_g = jax.ShapeDtypeStruct(
            (w, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
        mask_idx = jax.ShapeDtypeStruct((w,), jnp.int32)
        register_entrypoint(d._rows, (cast_abs, imgs_g, mask_idx))
        register_bucket_ladder(d._rows._name, d.row_bucket_sizes)


def _enumerate_bf16_incremental() -> None:
    """The incremental engines' bf16 banks: the token/stem/mixer certify
    programs with `compute_dtype="bfloat16"` (engine tables and weights
    cast at family build, images at the program boundary) — one bank per
    engine family at the shared representative radius, mirroring
    `_enumerate_incremental` so every `defense.*.bf16.*` incremental entry
    has an untagged f32 twin in the baseline."""
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import build_defenses
    from dorpatch_tpu.models import registry

    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    dummy = jax.ShapeDtypeStruct(
        (1, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    for arch in ("cifar_vit", "cifar_resnet18", "cifar_resmlp"):
        model = registry.build_bare_model(arch, AUDIT_CLASSES)
        engine = registry.incremental_engine(arch, model, AUDIT_IMG_SIZE)

        def apply(params, images01, _m=model):
            return _m.apply(params, (images01 - 0.5) / 0.5)

        cast_abs = _bf16_params_abs(jax.eval_shape(model.init, key, dummy))
        d = build_defenses(apply, AUDIT_IMG_SIZE,
                           DefenseConfig(ratios=(0.06,), chunk_size=64,
                                         compute_dtype="bfloat16"),
                           recompile_budget=1, incremental=engine)[0]
        w = int(d.row_bucket_sizes[0])
        imgs_g = jax.ShapeDtypeStruct(
            (w, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
        register_bucket_ladder(d._rows._name, d.row_bucket_sizes)
        if d._rows_incr is not None:
            register_bucket_ladder(d._rows_incr._name, d.row_bucket_sizes)
        for name, fn, kind in d.pruned_programs():
            if kind == "imgs":
                register_entrypoint(fn, (cast_abs, imgs), name=name)
            elif kind == "rows_sets":
                sets = jax.ShapeDtypeStruct((w, d.num_first), jnp.int32)
                register_entrypoint(fn, (cast_abs, imgs_g, sets),
                                    name=name)
            else:
                mask_idx = jax.ShapeDtypeStruct((w,), jnp.int32)
                register_entrypoint(fn, (cast_abs, imgs_g, mask_idx),
                                    name=name)


def _enumerate_incremental() -> None:
    """The mask-aware incremental certify programs (DefenseConfig.
    incremental): one bank per engine family — the token-pruned ViT
    programs on the small ViT victim, the stem-folded conv phase 1 on the
    conv victim, the mixer-pruned ResMLP programs on the small ResMLP
    victim — at one representative radius (0.06, shared with the
    standard bank so the per-radius wrapper names stay covered). The
    engines' lookup tables are closed-over DEVICE arrays (the params idiom
    DP203 exempts); registration attaches abstract args only, nothing
    executes."""
    import jax
    import jax.numpy as jnp

    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import build_defenses
    from dorpatch_tpu.models import registry

    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    dummy = jax.ShapeDtypeStruct(
        (1, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    for arch in ("cifar_vit", "cifar_resnet18", "cifar_resmlp"):
        model = registry.build_bare_model(arch, AUDIT_CLASSES)
        engine = registry.incremental_engine(arch, model, AUDIT_IMG_SIZE)

        def apply(params, images01, _m=model):
            return _m.apply(params, (images01 - 0.5) / 0.5)

        params_abs = abstractify(jax.eval_shape(model.init, key, dummy))
        d = build_defenses(apply, AUDIT_IMG_SIZE,
                           DefenseConfig(ratios=(0.06,), chunk_size=64),
                           recompile_budget=1, incremental=engine)[0]
        w = int(d.row_bucket_sizes[0])
        imgs_g = jax.ShapeDtypeStruct(
            (w, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
        register_bucket_ladder(d._rows._name, d.row_bucket_sizes)
        if d._rows_incr is not None:
            register_bucket_ladder(d._rows_incr._name, d.row_bucket_sizes)
        for name, fn, kind in d.pruned_programs():
            if kind == "imgs":
                register_entrypoint(fn, (params_abs, imgs), name=name)
            elif kind == "rows_sets":
                sets = jax.ShapeDtypeStruct((w, d.num_first), jnp.int32)
                register_entrypoint(fn, (params_abs, imgs_g, sets),
                                    name=name)
            else:
                mask_idx = jax.ShapeDtypeStruct((w,), jnp.int32)
                register_entrypoint(fn, (params_abs, imgs_g, mask_idx),
                                    name=name)


def _enumerate_sharded_defense(apply_fn, params) -> None:
    """The meshed pruned-certification bank (`.mesh`-tagged program names —
    a distinct program set: sharded fills, replicated out_shardings,
    `[S * bucket]` phase-2 wave shapes; see defense._schedule_mesh). One
    representative radius on a (2, n/2) mesh. `d._predict` is NOT
    re-registered: its wrapper name is radius-keyed, not mesh-keyed, and
    the single-chip bank already covers it. Enumerated only when the host
    exposes an even multi-device count (the test gate forces an 8-device
    virtual CPU mesh), like `_enumerate_sharded_ops`."""
    import jax
    import jax.numpy as jnp

    if jax.device_count() < 2 or jax.device_count() % 2:
        return
    from dorpatch_tpu.config import DefenseConfig
    from dorpatch_tpu.defense import build_defenses
    from dorpatch_tpu.parallel import make_mesh, shard_apply_fn

    mesh = make_mesh(2, jax.device_count() // 2)
    d = build_defenses(shard_apply_fn(apply_fn, mesh), AUDIT_IMG_SIZE,
                       DefenseConfig(ratios=(0.06,), chunk_size=64),
                       mesh=mesh, recompile_budget=1)[0]
    params_abs = abstractify(params)
    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    register_entrypoint(d._phase1, (params_abs, imgs))
    # phase 2 dispatches at [S * bucket] waves over the row ladder (pairs
    # included — on a mesh their declared budget is the row ladder's length)
    wave = int(mesh.shape["data"]) * int(d.row_bucket_sizes[0])
    imgs_g = jax.ShapeDtypeStruct(
        (wave, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    register_entrypoint(d._pairs, (params_abs, imgs_g))
    register_entrypoint(d._rows,
                        (params_abs, imgs_g,
                         jax.ShapeDtypeStruct((wave,), jnp.int32)))
    register_bucket_ladder(d._pairs._name, d.row_bucket_sizes)
    register_bucket_ladder(d._rows._name, d.row_bucket_sizes)


def _enumerate_train() -> None:
    from dorpatch_tpu import train

    for fn, args in train.trace_entrypoints():
        register_entrypoint(fn, args)


def _enumerate_model_init() -> None:
    from dorpatch_tpu.models import registry

    prog, args = registry.init_program("cifar_resnet18", AUDIT_CLASSES,
                                       AUDIT_IMG_SIZE)
    register_entrypoint(prog, args)


def _enumerate_serve(apply_fn, params) -> None:
    from dorpatch_tpu.config import DefenseConfig, ServeConfig
    from dorpatch_tpu.serve.service import CertifiedInferenceService

    svc = CertifiedInferenceService(
        apply_fn, params, num_classes=AUDIT_CLASSES,
        img_size=AUDIT_IMG_SIZE,
        serve_cfg=ServeConfig(max_batch=4, bucket_sizes=(1, 4)),
        defense_cfg=DefenseConfig(ratios=(0.1,), chunk_size=64))
    for name, fn, args in svc.trace_entrypoints():
        register_entrypoint(fn, args, name=name)
    for d in svc.defenses:
        register_bucket_ladder(d._rows._name, d.row_bucket_sizes)
        if d._rows_incr is not None:
            register_bucket_ladder(d._rows_incr._name, d.row_bucket_sizes)


def _enumerate_kernel_tier() -> None:
    """Audit-only kernel-tier probes: the stem and token engines' phase-1
    programs with the Pallas gate forced to "interpret" (abstract tracing
    keeps the `pallas_call` equations on any backend), registered next to
    their pure-XLA twins. The baseline then carries BOTH cost vectors —
    the jaxpr-walk estimator costs `pallas_call` as a fused kernel
    (boundary bytes only), so the kernels' bytes-accessed reduction over
    the einsum/conv chains is a checked DP301 number, not a claim."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.models import registry

    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    dummy = jax.ShapeDtypeStruct(
        (1, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    spec = masks_lib.geometry(AUDIT_IMG_SIZE, 0.06)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = np.concatenate([masks_lib.pad_rects(singles, k),
                            masks_lib.pad_rects(doubles, k)], axis=0)
    for arch, kname in (("cifar_resnet18", "stem"), ("cifar_vit", "token")):
        model = registry.build_bare_model(arch, AUDIT_CLASSES)
        engine = registry.incremental_engine(arch, model, AUDIT_IMG_SIZE)
        params_abs = abstractify(jax.eval_shape(model.init, key, dummy))
        for mode in ("interpret", "off"):
            fam = engine.build_family(rects, singles.shape[0], 64, 0.5,
                                      use_pallas=mode)
            tag = "kernel" if mode == "interpret" else "xla"
            # noqa-reason: audit-only probe programs, never executed —
            # there is no run for their compile time to be accounted
            # against
            register_entrypoint(
                jax.jit(fam.phase1),  # noqa: DP105
                (params_abs, imgs),
                name=f"ops.kernel_tier.{kname}.phase1.{tag}")


def _enumerate_kernel_tier_mesh() -> None:
    """The kernel-tier probes' meshed twins: the same stem/token phase-1
    programs with the gate forced to "interpret" AND a (2, n/2) mesh
    passed down, so the `pallas_call`s trace inside their `shard_map`
    wrappers — the exact programs the DP603 shard-local proof certifies,
    and the `.mesh`-tagged baseline entries whose comm_bytes vector pins
    the wrappers' zero-collective claim. Enumerated only on an even
    multi-device host, like `_enumerate_sharded_defense`."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.device_count() < 2 or jax.device_count() % 2:
        return
    from dorpatch_tpu import masks as masks_lib
    from dorpatch_tpu.models import registry
    from dorpatch_tpu.parallel import make_mesh

    mesh = make_mesh(2, jax.device_count() // 2)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    dummy = jax.ShapeDtypeStruct(
        (1, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    spec = masks_lib.geometry(AUDIT_IMG_SIZE, 0.06)
    singles, doubles = masks_lib.mask_sets(spec)
    k = max(singles.shape[1], doubles.shape[1])
    rects = np.concatenate([masks_lib.pad_rects(singles, k),
                            masks_lib.pad_rects(doubles, k)], axis=0)
    for arch, kname in (("cifar_resnet18", "stem"), ("cifar_vit", "token")):
        model = registry.build_bare_model(arch, AUDIT_CLASSES)
        engine = registry.incremental_engine(arch, model, AUDIT_IMG_SIZE)
        params_abs = abstractify(jax.eval_shape(model.init, key, dummy))
        fam = engine.build_family(rects, singles.shape[0], 64, 0.5,
                                  use_pallas="interpret", mesh=mesh)
        # noqa-reason: audit-only probe programs, never executed — there
        # is no run for their compile time to be accounted against
        register_entrypoint(
            jax.jit(fam.phase1),  # noqa: DP105
            (params_abs, imgs),
            name=f"ops.kernel_tier.{kname}.phase1.kernel.mesh")


def _enumerate_sharded_ops() -> None:
    """The multichip dry-run path: the Pallas masked-fill gradient under
    `shard_map`, whose backward `psum`s over the mask axis — the one
    collective the production mesh path emits (DP205's clean case).
    Enumerated only when the host exposes multiple devices (the test gate
    forces an 8-device virtual CPU mesh)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.device_count() < 2:
        return
    from jax.sharding import Mesh

    from dorpatch_tpu import ops

    mesh = Mesh(np.asarray(jax.devices()).reshape(1, -1), ("data", "mask"))
    n_masks = int(mesh.shape["mask"])

    # noqa-reason: an audit-only probe program, never executed — there is
    # no run for its compile time to be accounted against
    @jax.jit  # noqa: DP105
    def sharded_fill_grad(imgs, rects):
        def total(im):
            return ops.masked_fill(im, rects, 0.5, "interpret",
                                   mesh=mesh).sum()

        # value_and_grad, both returned: a bare grad() would leave the
        # primal shard_map dead in the jaxpr (DP204 flags exactly that)
        return jax.value_and_grad(total)(imgs)

    imgs = jax.ShapeDtypeStruct(
        (AUDIT_BATCH, AUDIT_IMG_SIZE, AUDIT_IMG_SIZE, 3), jnp.float32)
    rects = jax.ShapeDtypeStruct((n_masks, 1, 4), jnp.int32)
    register_entrypoint(sharded_fill_grad, (imgs, rects),
                        name="ops.masked_fill.sharded_grad")


def production_entrypoints(clear: bool = True) -> List[EntryPoint]:
    """Construct — never execute — every registered production jit entry
    point with abstract example args: the `--trace` audit's work list."""
    if clear:
        clear_entrypoints()
    apply_fn, params = _audit_victim()
    with capture_entrypoints():
        _enumerate_attack(apply_fn, params)
        _enumerate_defense(apply_fn, params)
        _enumerate_bf16_defense(apply_fn, params)
        _enumerate_incremental()
        _enumerate_bf16_incremental()
        _enumerate_train()
        _enumerate_model_init()
        _enumerate_serve(apply_fn, params)
        _enumerate_kernel_tier()
        _enumerate_kernel_tier_mesh()
        _enumerate_sharded_ops()
        _enumerate_sharded_defense(apply_fn, params)
    return registered_entrypoints()
