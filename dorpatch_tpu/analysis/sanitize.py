"""Runtime sanitizers: what static rules cannot prove, checked live.

The static wing (`rules_jax.py`) catches source-provable invariant breaks;
this module catches the rest at runtime, behind the pipeline's `--sanitize`
flag (`ExperimentConfig.sanitize`):

- **NaN debugging** — `jax_debug_nans`: any NaN produced inside a jitted
  program re-runs op-by-op and raises `FloatingPointError` at the producing
  primitive, instead of silently poisoning the carry state for the rest of
  a 5000-iteration attack.
- **Compile logging** — `jax_log_compiles` routed into observe events:
  every trace+compile jax performs lands in `events.jsonl` as a
  `jax.log_compiles` event, so the report CLI can show *unexpected*
  recompiles next to the declared `compile` records the
  `timed_first_call` wrappers emit.
- **Recompile-budget watchdog** — every jitted entry point wrapped by
  `observe.timed_first_call(..., recompile_budget=N)` declares how many
  traces (shape/dtype buckets) it is allowed. The watchdog reads the jit's
  `_cache_size()` after each call and FAILS THE RUN (`RecompileBudgetExceeded`)
  when the cache outgrows the budget — a shape-unstable call pattern
  (e.g. an unpadded dynamic batch) otherwise re-traces every step and
  turns a TPU run into a compile loop.
- **Lock sanitizer** — the concurrency tier's runtime wing
  (`lockwatch.py`): arms a process-wide `LockWatch` (seeded with the
  static DP501 nested-`with` graph) so locks built through
  `lockwatch.watched_lock` record their acquisition order and held
  durations; an order inversion raises `LockOrderViolation`, a blown
  hold budget raises `LockHoldBudgetExceeded` — same event-then-raise
  contract as the recompile watchdog.

Unlike the rest of the analysis package this module imports jax; only the
runtime pipeline (and tests) load it.

Usage:

    with Sanitizer():          # or: python -m dorpatch_tpu.cli --sanitize
        run_experiment(cfg)
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax

from dorpatch_tpu import observe
from dorpatch_tpu.analysis import lockwatch as _lockwatch
from dorpatch_tpu.observe import events as _events


class RecompileBudgetExceeded(RuntimeError):
    """A jitted entry point re-traced past its declared budget."""


class RecompileWatchdog:
    """Per-entry-point trace accounting against declared budgets.

    Installed via `observe.set_recompile_guard`; `observe.timed_first_call`
    wrappers call `after_call` after every invocation. `_cache_size()` is
    the number of (shape, dtype, static-arg) buckets the jit has compiled —
    exactly "traces so far", with no log parsing. Jits that don't expose it
    (plain callables, mocks) are skipped.
    """

    def __init__(self):
        self._last_seen: Dict[str, int] = {}

    def after_call(self, name: str, wrapped, budget: Optional[int]) -> None:
        try:
            traces = int(wrapped._cache_size())
        except (AttributeError, TypeError):
            return
        prev = self._last_seen.get(name, 0)
        if traces > prev:
            self._last_seen[name] = traces
            if prev >= 1:
                # growth past the first trace is a re-trace: always recorded,
                # only fatal past the budget
                observe.record_event("sanitize.retrace", entry=name,
                                     traces=traces,
                                     budget=-1 if budget is None else budget)
        if budget is not None and traces > budget:
            observe.record_event("sanitize.recompile_budget_exceeded",
                                 entry=name, traces=traces, budget=budget)
            raise RecompileBudgetExceeded(
                f"jitted entry point {name!r} traced {traces} times, over "
                f"its declared budget of {budget} (shape-unstable call "
                "pattern? every distinct input shape/dtype is a fresh XLA "
                "compile)")


class _CompileLogHandler(logging.Handler):
    """Forwards jax's log_compiles records into the active EventLog."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        # log_compiles emits ~4 records per compile (trace, MLIR, XLA,
        # dispatch); keep exactly the one-per-compile "Compiling <fn> with
        # global shapes..." line so events.jsonl stays readable
        if not msg.startswith("Compiling"):
            return
        observe.record_event("jax.log_compiles", logger=record.name,
                             message=msg[:500])


class Sanitizer:
    """Context manager arming the runtime sanitizers; restores every global
    it touched (jax config flags, the log handler, the recompile guard) on
    exit, so tests and nested runs never leak sanitizer state."""

    def __init__(self, debug_nans: bool = True, log_compiles: bool = True,
                 recompile_budgets: bool = True, lock_order: bool = True,
                 lock_hold_budget_s: Optional[float] = None):
        self.debug_nans = debug_nans
        self.log_compiles = log_compiles
        self.recompile_budgets = recompile_budgets
        self.lock_order = lock_order
        self.watchdog = RecompileWatchdog() if recompile_budgets else None
        self.lock_watch = None
        if lock_order or lock_hold_budget_s is not None:
            # seed with the static DP501 graph so a runtime acquisition
            # that inverts a source-committed order is caught on its very
            # first execution; a broken static scan must not break arming
            try:
                from dorpatch_tpu.analysis.concurrency import \
                    static_lock_graph
                static = static_lock_graph()
            except Exception:
                static = None
            self.lock_watch = _lockwatch.LockWatch(
                hold_budget_s=lock_hold_budget_s, static_graph=static)
        self._handler: Optional[_CompileLogHandler] = None
        self._prev_flags: Dict[str, bool] = {}
        self._prev_guard = None
        self._prev_watch = None

    def __enter__(self) -> "Sanitizer":
        if self.debug_nans:
            self._set_flag("jax_debug_nans", True)
        if self.log_compiles:
            self._set_flag("jax_log_compiles", True)
            self._handler = _CompileLogHandler(level=logging.WARNING)
            # log_compiles messages are emitted at WARNING on the jax.*
            # loggers (pjit tracing, dispatch); one handler on the parent
            # catches them all
            logging.getLogger("jax").addHandler(self._handler)
        if self.watchdog is not None:
            self._prev_guard = _events.recompile_guard()
            _events.set_recompile_guard(self.watchdog)
        if self.lock_watch is not None:
            self._prev_watch = _lockwatch.set_active_watch(self.lock_watch)
        observe.record_event(
            "sanitize.enabled", debug_nans=self.debug_nans,
            log_compiles=self.log_compiles,
            recompile_budgets=self.recompile_budgets,
            lock_order=self.lock_watch is not None)
        return self

    def __exit__(self, *exc) -> None:
        if self.lock_watch is not None:
            _lockwatch.set_active_watch(self._prev_watch)
        if self.watchdog is not None:
            _events.set_recompile_guard(self._prev_guard)
        if self._handler is not None:
            logging.getLogger("jax").removeHandler(self._handler)
            self._handler = None
        for flag, prev in self._prev_flags.items():
            jax.config.update(flag, prev)
        self._prev_flags.clear()

    def _set_flag(self, flag: str, value: bool) -> None:
        self._prev_flags[flag] = bool(getattr(jax.config, flag))
        jax.config.update(flag, value)
