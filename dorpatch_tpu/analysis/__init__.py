"""Static analysis + program auditing + runtime sanitizers.

Five wings, one invariant set:

- **AST** (`engine.py`, `rules_output.py`, `rules_jax.py`, `cli.py`):
  rules DP101-DP108 with stable IDs, `# noqa: DPxxx` suppressions, a
  mechanical DP106 fixer (`fix.py`, `--fix`), and a CLI gate
  (`python -m dorpatch_tpu.analysis`, wired into `run_tests.sh`). Catches
  what is provable from source: bare prints, host syncs under trace, PRNG
  key reuse, literal seeds, unwrapped jits, unused imports.
- **Trace** (`entrypoints.py`, `program.py`, `--trace`): rules
  DP200-DP206 over the jaxpr of every registered production jit entry
  point, abstractly traced on CPU — carry instability, precision/weak-type
  leaks, baked-in host constants, dead compute, collective-axis
  mismatches, dead donations. Catches what source cannot show but a
  device never needs to run.
- **Baseline** (`baseline.py`, `--baseline check|update`): rules
  DP300-DP304 comparing every entry point's canonical jaxpr fingerprint
  and static cost vector (XLA `cost_analysis` + a jaxpr-walk estimator)
  against the checked-in `baselines.json` — fingerprint drift, cost
  regressions past tolerance, program-set and interface drift, and
  recompile-budget/bucket-ladder inconsistency. Catches what only a
  *cross-version* diff can show, without a bench.
- **Concurrency** (`concurrency.py`, `--concurrency`): rules
  DP500-DP504 over the threaded packages (serve/farm/observe/recert,
  backoff, chaos) — `# guarded-by:` lock-discipline violations, nested
  lock-order (ABBA) cycles, blocking calls under a held lock, thread
  lifecycle hygiene, and wall-clock liveness comparisons. Catches the
  deadlock/race shapes that took PRs 11 and 16 to debug post-hoc.
- **Runtime** (`sanitize.py`, `lockwatch.py`): the `--sanitize` pipeline
  flag — NaN debugging, `jax.log_compiles` routed into observe events, a
  recompile-budget watchdog that fails the run when a jitted entry point
  re-traces past its declared budget, and a lock sanitizer that records
  real acquisition orders/held durations and fails on an inversion of
  the static DP501 graph. Catches the remainder, live.

The AST engine and rules are stdlib-only logic — ast + tokenize, no jax
API calls — so linting never initializes (and on shared accelerators,
claims) a backend. The trace wing calls jax tracing APIs (CPU, no device
FLOPs) and only loads under `--trace` / the auditor tests. Importing the
package pulls jax into the process transitively via the parent package;
import alone is backend-neutral.
"""

from dorpatch_tpu.analysis.engine import (  # noqa: F401
    ALL_CODES,
    FileContext,
    Finding,
    Rule,
    all_rules,
    analyze_file,
    analyze_paths,
    analyze_source,
    get_rule,
    iter_python_files,
    register,
)

__all__ = [
    "ALL_CODES",
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "get_rule",
    "iter_python_files",
    "register",
    "register_entrypoint",
]


def register_entrypoint(fn, args=(), kwargs=None, name=None):
    """Register a (non-timed) jit entry point for the `--trace` audit —
    the public front door of `analysis.entrypoints.register_entrypoint`,
    re-exported lazily so merely importing `dorpatch_tpu.analysis` stays
    free of jax tracing machinery."""
    from dorpatch_tpu.analysis.entrypoints import register_entrypoint as reg

    return reg(fn, args=args, kwargs=kwargs, name=name)
