"""Static analysis + runtime sanitizers for the dorpatch-tpu framework.

Two wings, one invariant set:

- **Static** (`engine.py`, `rules_output.py`, `rules_jax.py`, `cli.py`):
  an AST rule engine with stable `DPxxx` IDs, `# noqa: DPxxx` suppressions,
  and a CLI gate (`python -m dorpatch_tpu.analysis`, wired into
  `run_tests.sh`). Catches what is provable from source: bare prints,
  host syncs under trace, PRNG key reuse, literal seeds, unwrapped jits,
  unused imports.
- **Runtime** (`sanitize.py`): the `--sanitize` pipeline flag — NaN
  debugging, `jax.log_compiles` routed into observe events, and a
  recompile-budget watchdog that fails the run when a jitted entry point
  re-traces past its declared budget. Catches what only shows at runtime.

The engine and rules (everything but `sanitize`) are stdlib-only logic —
ast + tokenize, no jax API calls — so linting never initializes (and on
shared accelerators, claims) a backend. Importing the package does pull
jax into the process transitively via the parent package; import alone is
backend-neutral.
"""

from dorpatch_tpu.analysis.engine import (  # noqa: F401
    ALL_CODES,
    FileContext,
    Finding,
    Rule,
    all_rules,
    analyze_file,
    analyze_paths,
    analyze_source,
    get_rule,
    iter_python_files,
    register,
)

__all__ = [
    "ALL_CODES",
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "get_rule",
    "iter_python_files",
    "register",
]
