"""Sharding & collectives audit tier: the DP6xx comm-cost rule family.

The trace tier (DP2xx) proves single-program invariants and the baseline
tier (DP3xx) catches cost drift — but neither *prices communication*. On a
mesh the dominant regression mode is not flops, it is a collective that
quietly grows (an `all_gather` that used to be a `reduce_scatter`, a
`psum` whose operand doubled) or a kernel that silently stops being
shard-local. This module closes that hole at the jaxpr level, over the
same registered production entry points the other wings audit:

- **DP600 unpriced-collective** — the static comm pricer walks every
  collective (`psum` family, `all_gather`, `reduce_scatter`,
  `all_to_all`, `ppermute`, ...) including inside `shard_map` / pmap /
  scan bodies (trip-count-scaled like the DP301 estimator) and prices
  bytes as `operand-aval bytes x participant count` (the product of the
  enclosing bound mesh-axis sizes). A collective the pricer *cannot*
  price — an axis no enclosing mesh binds, or an `axis_index_groups`
  partition whose group sizes the mesh product does not describe — is a
  hole in the comm baseline and fires this rule. The priced inventory
  itself is not a finding: it is the `comm_bytes` vector the baseline
  tier folds into every entry's cost record, so DP301 catches comm
  regressions exactly like flop regressions (naming the dominant
  collective).
- **DP601 accidental-replication** — a `shard_map` operand or result
  above the byte threshold whose `in_names`/`out_names` entry is empty
  (fully replicated) while a size>1 mesh axis divides its leading dim:
  the tensor *could* shard but every device holds all of it. Replicated
  small operands (weights, rect tables) are the intended idiom and stay
  quiet.
- **DP602 boundary-reshard** — conflicting placement constraints on one
  value: a `sharding_constraint` whose input is itself a
  `sharding_constraint` with a different spec (a chained re-pin), or one
  value consumed under two different constraint specs — either way the
  runtime inserts an implicit reshard at dispatch.
- **DP603 shard-unsafe-kernel** — the shard-local kernel proof. In a
  mesh program (one that contains a `shard_map`, or a `.mesh`-tagged
  entry point), a `pallas_call` is mesh-safe iff it sits under a
  `shard_map` whose body feeds it no collective results: the per-shard
  trace then guarantees the grid derives only from shard-local shapes,
  and GSPMD never sees the kernel. A *bare* `pallas_call` reachable
  under a mesh is a custom call GSPMD cannot partition (it runs
  replicated or fails to lower on device); a collective result flowing
  into kernel *operands* means the kernel consumes cross-shard data and
  the shard-local claim is false. Collectives consuming kernel *outputs*
  (the masked-fill backward `psum`) are the clean pattern and pass.

Findings flow through the engine types (`engine.Finding`, `# noqa:` on
the entry point's `def` line, a reasoned `comms.ALLOWLIST` for offenses
no source comment can reach) and the shared exit contract.
"""

from __future__ import annotations

import dataclasses
from typing import (Any, Dict, Iterable, Iterator, List, Optional, Sequence,
                    Set, Tuple)

from dorpatch_tpu.analysis.engine import Finding
from dorpatch_tpu.analysis.entrypoints import EntryPoint
from dorpatch_tpu.analysis import program as program_mod
from dorpatch_tpu.analysis.program import (ProgramContext, TraceRule,
                                           _COLLECTIVE_PRIMS,
                                           _collective_axes, _eqn_subjaxprs,
                                           _raw)

#: Entry-point-name glob -> {rule_id: reason} — the comms tier's analog of
#: `program.ALLOWLIST`. Shipped entries must carry their reason.
ALLOWLIST: Dict[str, Dict[str, str]] = {}

#: Collectives that move payload; `axis_index` reads a mesh coordinate and
#: transfers nothing.
_PRICED_PRIMS = frozenset(_COLLECTIVE_PRIMS) - {"axis_index"}

#: DP601 default: a replicated shard_map operand/result this large, with a
#: shardable leading dim, is memory the mesh buys nothing for. Weight/table
#: replication (small, deliberate) stays under it.
REPLICATION_BYTES_THRESHOLD = 256 * 1024


# -------------------------------------------------------------- comm pricer

@dataclasses.dataclass
class CommCost:
    """Static comm vector for one program: total priced bytes, the
    per-collective breakdown (the baseline's `comm` record), and the
    (primitive, reason) list of collectives the pricer could not price."""

    comm_bytes: float = 0.0
    by_collective: Dict[str, float] = dataclasses.field(default_factory=dict)
    unpriced: List[Tuple[str, str]] = dataclasses.field(default_factory=list)


def _operand_bytes(eqn) -> float:
    """Bytes of every non-literal operand aval, once each — the per-shard
    payload one participant contributes to the collective."""
    import jax

    total = 0
    for v in eqn.invars:
        if isinstance(v, jax.core.Literal):
            continue
        a = getattr(v, "aval", None)
        if a is None or not hasattr(a, "shape"):
            continue
        n = 1
        for d in a.shape:
            n *= int(d)
        total += n * int(getattr(a.dtype, "itemsize", 4))
    return float(total)


def comm_cost(closed_or_raw) -> Dict[str, Any]:
    """Walk a jaxpr and price every collective: `operand bytes x the
    product of the bound sizes of its axes` (every participant contributes
    its shard once — one uniform model across psum/all_gather/
    reduce_scatter/all_to_all/ppermute, deliberately coarse: the vector
    exists to rank collectives and catch step-function regressions, not to
    model a ring schedule). Scan bodies multiply by trip count, mirroring
    the DP301 flop estimator; `while` bodies count once. Axis sizes come
    from the enclosing `shard_map` mesh / `pmap` axis_size; GSPMD-inserted
    collectives live only in post-SPMD HLO and are out of scope by
    construction — a meshed-jit program with zero explicit collectives
    correctly prices to zero."""
    acc = CommCost()
    _walk_comm(closed_or_raw, 1.0, {}, acc)
    acc.by_collective = dict(sorted(acc.by_collective.items(),
                                    key=lambda kv: (-kv[1], kv[0])))
    return {"comm_bytes": acc.comm_bytes,
            "by_collective": acc.by_collective,
            "unpriced": list(acc.unpriced)}


def _walk_comm(j, mult: float, bound: Dict[str, int], acc: CommCost) -> None:
    for eqn in _raw(j).eqns:
        prim = eqn.primitive.name
        if prim in _PRICED_PRIMS:
            axes = _collective_axes(eqn)
            groups = eqn.params.get("axis_index_groups")
            if groups is not None:
                acc.unpriced.append(
                    (prim, "axis_index_groups partition the axis into "
                           "groups the mesh-axis product does not price"))
            else:
                participants = 1.0
                missing = [ax for ax in axes if ax not in bound]
                if missing:
                    acc.unpriced.append(
                        (prim, f"axis {missing[0]!r} is not bound by any "
                               "enclosing shard_map/pmap mesh"))
                else:
                    for ax in axes:
                        participants *= float(bound[ax])
                    priced = _operand_bytes(eqn) * participants * mult
                    acc.comm_bytes += priced
                    acc.by_collective[prim] = \
                        acc.by_collective.get(prim, 0.0) + priced
        inner_bound = bound
        if prim == "shard_map":
            mesh = eqn.params.get("mesh")
            names = tuple(getattr(mesh, "axis_names", ()) or ())
            if names:
                inner_bound = dict(bound)
                for n in names:
                    try:
                        inner_bound[n] = int(mesh.shape[n])
                    except Exception:
                        pass
        elif prim == "xla_pmap":
            name = eqn.params.get("axis_name")
            size = eqn.params.get("axis_size")
            if isinstance(name, str) and size:
                inner_bound = dict(bound)
                inner_bound[name] = int(size)
        sub_mult = mult
        if prim == "scan":
            sub_mult = mult * float(eqn.params.get("length", 1) or 1)
        for sub in _eqn_subjaxprs(eqn):
            _walk_comm(sub, sub_mult, inner_bound, acc)


# ----------------------------------------------------------------- registry

_COMMS_REGISTRY: Dict[str, TraceRule] = {}


def register_comms(cls):
    if not cls.id:
        raise ValueError(f"comms rule {cls.__name__} has no id")
    if cls.id in _COMMS_REGISTRY:
        raise ValueError(f"duplicate comms rule id {cls.id}")
    _COMMS_REGISTRY[cls.id] = cls()
    return cls


def all_comms_rules() -> List[TraceRule]:
    return [_COMMS_REGISTRY[k] for k in sorted(_COMMS_REGISTRY)]


# -------------------------------------------------------------------- DP600

@register_comms
class UnpricedCollectiveRule(TraceRule):
    id = "DP600"
    name = "unpriced-collective"
    description = ("collective the static comm pricer cannot price (axis "
                   "bound by no enclosing mesh, or an axis_index_groups "
                   "partition) — a hole in the comm_bytes baseline vector")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        cost = comm_cost(ctx.jaxpr)
        for prim, why in cost["unpriced"]:
            yield self.finding(
                ctx, f"`{prim}` cannot be statically priced: {why} — the "
                "entry's comm_bytes baseline vector under-counts this "
                "collective, so DP301 cannot gate its regressions")


# -------------------------------------------------------------------- DP601

def _leading_divisible(aval, mesh) -> Optional[str]:
    """The name of a size>1 mesh axis that divides the aval's leading dim
    (preferring the conventional data axis), or None."""
    shape = getattr(aval, "shape", ())
    if not shape:
        return None
    lead = int(shape[0])
    names = tuple(getattr(mesh, "axis_names", ()) or ())
    ordered = sorted(names, key=lambda n: (n != "data", n))
    for n in ordered:
        try:
            size = int(mesh.shape[n])
        except Exception:
            continue
        if size > 1 and lead >= size and lead % size == 0:
            return n
    return None


def _aval_nbytes(a) -> int:
    n = 1
    for d in getattr(a, "shape", ()):
        n *= int(d)
    return n * int(getattr(getattr(a, "dtype", None), "itemsize", 4) or 4)


@register_comms
class AccidentalReplicationRule(TraceRule):
    id = "DP601"
    name = "accidental-replication"
    description = ("large shard_map operand/result fully replicated "
                   "(empty in_names/out_names entry) while a size>1 mesh "
                   "axis divides its leading dim — every device holds all "
                   "of a tensor that could shard")

    threshold = REPLICATION_BYTES_THRESHOLD

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        import jax

        for j in program_mod.iter_jaxprs(ctx.jaxpr):
            for eqn in _raw(j).eqns:
                if eqn.primitive.name != "shard_map":
                    continue
                mesh = eqn.params.get("mesh")
                if mesh is None:
                    continue
                for side, vs, names in (
                        ("operand", eqn.invars,
                         eqn.params.get("in_names", ())),
                        ("result", eqn.outvars,
                         eqn.params.get("out_names", ()))):
                    for i, (v, nm) in enumerate(zip(vs, names)):
                        if nm:  # any dim mapped to an axis: not replicated
                            continue
                        a = getattr(v, "aval", None)
                        if a is None or not hasattr(a, "shape"):
                            continue
                        nbytes = _aval_nbytes(a)
                        if nbytes < self.threshold:
                            continue
                        axis = _leading_divisible(a, mesh)
                        if axis is None:
                            continue
                        yield self.finding(
                            ctx, f"shard_map {side} {i} "
                            f"({program_mod._aval_str(a)}, "
                            f"{nbytes / 1024:.0f} KiB) is fully replicated "
                            f"but mesh axis {axis!r} divides its leading "
                            f"dim — shard it (P({axis!r})) or shrink it "
                            "below the replication threshold")


# -------------------------------------------------------------------- DP602

def _spec_str(sharding) -> str:
    spec = getattr(sharding, "spec", None)
    return str(spec if spec is not None else sharding)


@register_comms
class BoundaryReshardRule(TraceRule):
    id = "DP602"
    name = "boundary-reshard"
    description = ("conflicting sharding_constraint specs pinned on one "
                   "value (chained re-pin, or one value consumed under "
                   "two placements) — the runtime inserts an implicit "
                   "reshard at dispatch")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        for j in program_mod.iter_jaxprs(ctx.jaxpr):
            yield from self._check_body(ctx, _raw(j))

    def _check_body(self, ctx: ProgramContext, j) -> Iterator[Finding]:
        import jax

        producer: Dict[Any, Any] = {}
        pinned: Dict[Any, str] = {}
        for eqn in j.eqns:
            if eqn.primitive.name == "sharding_constraint":
                spec = _spec_str(eqn.params.get("sharding"))
                src = eqn.invars[0]
                if not isinstance(src, jax.core.Literal):
                    prev = producer.get(src)
                    if prev is not None and \
                            prev.primitive.name == "sharding_constraint":
                        prev_spec = _spec_str(prev.params.get("sharding"))
                        if prev_spec != spec:
                            yield self.finding(
                                ctx, "chained sharding constraints re-pin "
                                f"one value from {prev_spec} to {spec} — "
                                "an implicit reshard at dispatch; keep one "
                                "placement per value")
                    seen = pinned.get(src)
                    if seen is not None and seen != spec:
                        yield self.finding(
                            ctx, "one value is consumed under two "
                            f"placements ({seen} and {spec}) — the "
                            "runtime resolves the conflict with an "
                            "implicit reshard; pick one spec")
                    pinned.setdefault(src, spec)
            for v in eqn.outvars:
                if not isinstance(v, jax.core.DropVar):
                    producer[v] = eqn


# -------------------------------------------------------------------- DP603

def _has_shard_map(closed_or_raw) -> bool:
    for j in program_mod.iter_jaxprs(closed_or_raw):
        for eqn in _raw(j).eqns:
            if eqn.primitive.name == "shard_map":
                return True
    return False


@register_comms
class ShardLocalKernelRule(TraceRule):
    id = "DP603"
    name = "shard-unsafe-kernel"
    description = ("pallas_call in a mesh program outside any shard_map "
                   "(a custom call GSPMD cannot partition), or fed a "
                   "collective result inside one (the kernel consumes "
                   "cross-shard data) — the shard-local proof fails")

    def check(self, ctx: ProgramContext) -> Iterator[Finding]:
        if ".mesh" not in ctx.name and not _has_shard_map(ctx.jaxpr):
            return  # single-chip program: kernels face no partitioner
        yield from self._walk(ctx, ctx.jaxpr)

    def _walk(self, ctx: ProgramContext, j) -> Iterator[Finding]:
        """Above any shard_map: a pallas_call here is bare under the mesh.
        At each shard_map: switch to the taint walk of its body."""
        for eqn in _raw(j).eqns:
            prim = eqn.primitive.name
            if prim == "pallas_call":
                yield self.finding(
                    ctx, f"bare pallas_call ({self._kernel_name(eqn)}) "
                    "reachable under a mesh outside any shard_map — GSPMD "
                    "cannot partition a custom call; wrap it in shard_map "
                    "over the data axis (the shard-local proof)")
            if prim == "shard_map":
                for sub in _eqn_subjaxprs(eqn):
                    fs, _ = self._taint_body(ctx, sub, False)
                    yield from fs
            else:
                for sub in _eqn_subjaxprs(eqn):
                    yield from self._walk(ctx, sub)

    def _taint_body(self, ctx: ProgramContext, j, in_taint: bool
                    ) -> Tuple[List[Finding], bool]:
        """Inside a shard_map body: taint = transitively derived from a
        collective result. A tainted pallas_call operand breaks the
        shard-local proof; a collective consuming kernel *output* (the
        masked-fill backward psum) never taints the kernel and passes."""
        import jax

        j = _raw(j)
        findings: List[Finding] = []
        tainted: Set[Any] = set()
        if in_taint:
            tainted.update(j.invars)
        for eqn in j.eqns:
            prim = eqn.primitive.name
            t_in = any(not isinstance(v, jax.core.Literal) and v in tainted
                       for v in eqn.invars)
            sub_taint = False
            if prim == "pallas_call":
                if t_in:
                    findings.append(self.finding(
                        ctx, f"pallas_call ({self._kernel_name(eqn)}) "
                        "inside shard_map consumes a collective result — "
                        "its operands are not shard-local; move the "
                        "collective after the kernel or split the body"))
            else:
                for sub in _eqn_subjaxprs(eqn):
                    fs, to = self._taint_body(ctx, sub, t_in)
                    findings.extend(fs)
                    sub_taint = sub_taint or to
            if prim in _COLLECTIVE_PRIMS or t_in or sub_taint:
                tainted.update(v for v in eqn.outvars
                               if not isinstance(v, jax.core.DropVar))
        out_taint = any(not isinstance(v, jax.core.Literal) and v in tainted
                        for v in j.outvars)
        return findings, out_taint

    @staticmethod
    def _kernel_name(eqn) -> str:
        info = eqn.params.get("name_and_src_info")
        name = getattr(info, "name", None) or eqn.params.get("name")
        return str(name) if name else "<kernel>"


# ------------------------------------------------------------------- driver

def audit_entrypoint(ep: EntryPoint,
                     select: Optional[Sequence[str]] = None,
                     allow: Optional[Dict[str, Dict[str, str]]] = None
                     ) -> List[Finding]:
    """Trace one entry point (shared with the DP2xx tier) and run the
    comms rules. An untraceable program is the trace wing's DP200 story —
    here it simply contributes nothing (the trace gate fails loudly)."""
    ctx, _ = program_mod.trace_entrypoint(ep)
    findings: List[Finding] = []
    if ctx is not None:
        for rule in all_comms_rules():
            if select is not None and rule.id not in select:
                continue
            findings.extend(rule.check(ctx))
    out = []
    for f in findings:
        if select is not None and f.rule_id not in select:
            continue
        if _allowed(ep.name, f.rule_id, allow):
            continue
        if program_mod._suppressed_in_source(f.path, f.line, f.rule_id):
            continue
        out.append(f)
    return sorted(out)


def _allowed(name: str, rule_id: str,
             allow: Optional[Dict[str, Dict[str, str]]] = None) -> bool:
    import fnmatch

    for table in (ALLOWLIST, allow or {}):
        for pattern, rules in table.items():
            if fnmatch.fnmatchcase(name, pattern) and rule_id in rules:
                return True
    return False


def audit_entrypoints(eps: Iterable[EntryPoint],
                      select: Optional[Sequence[str]] = None,
                      allow: Optional[Dict[str, Dict[str, str]]] = None
                      ) -> List[Finding]:
    findings: List[Finding] = []
    for ep in eps:
        findings.extend(audit_entrypoint(ep, select=select, allow=allow))
    return sorted(findings)


def audit_production(select: Optional[Sequence[str]] = None,
                     allow: Optional[Dict[str, Dict[str, str]]] = None
                     ) -> List[Finding]:
    """Enumerate + audit every registered production entry point — the
    `--comms` gate's whole job."""
    from dorpatch_tpu.analysis import entrypoints as ep_mod

    eps = ep_mod.production_entrypoints()
    return audit_entrypoints(eps, select=select, allow=allow)


#: Rule IDs the comms wing owns.
COMMS_RULE_IDS: Tuple[str, ...] = tuple(sorted(_COMMS_REGISTRY))
