"""`python -m dorpatch_tpu.serve` — stand up the certified-inference
service over the configured victim and serve HTTP until interrupted.

Reuses the experiment CLI surface (`dorpatch_tpu.cli.build_parser`): model/
dataset/defense flags select what is served, the `--serve-*` group sizes
the micro-batcher, replica pool (`--serve-replicas`, restart policy), and
front-end; `--chaos wedge_dispatch,raise_in_worker,wedge_heartbeat` arms
the serve-side fault injection (dorpatch_tpu.chaos) against replica 0 for
recovery drills. Telemetry lands in `<results_root>/serve/` (run.json +
events.jsonl); render it with
`python -m dorpatch_tpu.observe.report <results_root>/serve`.
"""

from __future__ import annotations

import time

from dorpatch_tpu import observe
from dorpatch_tpu.cli import build_parser, config_from_args
from dorpatch_tpu.serve.http import HttpFrontend
from dorpatch_tpu.serve.service import CertifiedInferenceService


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    service = CertifiedInferenceService.from_config(cfg)
    with service:
        observe.log(
            f"serve: warm ({service.trace_counts()}) — "
            f"replicas {cfg.serve.replicas}, "
            f"buckets {list(service.bucket_sizes)}, "
            f"queue depth {service.batcher.max_queue_depth}, "
            f"deadline {cfg.serve.deadline_ms:g} ms"
            + (f", chaos [{cfg.serve.chaos}]" if cfg.serve.chaos else ""))
        with HttpFrontend(service, cfg.serve.host, cfg.serve.port):
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                observe.log("serve: shutting down")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
