"""Online certified-inference service (the ROADMAP's "serves heavy traffic"
leg): micro-batched PatchCleanser serving with a shape-bucketed
zero-recompile hot path, bounded-queue backpressure, an `http.server` JSON
front-end, and full events.jsonl telemetry.

    service = CertifiedInferenceService.from_config(cfg)
    with service, HttpFrontend(service, port=cfg.serve.port):
        ...                      # or: python -m dorpatch_tpu.serve

    service.predict(image)       # direct Python client (no sockets)

See `service.py` for the request lifecycle, `batcher.py` for the
size-or-deadline flush rules, `types.py` for the typed responses, and
`pool.py` for the supervised replica pool (N worker loops, per-replica
health, failover re-dispatch, AOT-warm restarts).
"""

from dorpatch_tpu.serve.batcher import MicroBatcher, PendingRequest  # noqa: F401
from dorpatch_tpu.serve.http import HttpFrontend  # noqa: F401
from dorpatch_tpu.serve.pool import Replica, ReplicaPool  # noqa: F401
from dorpatch_tpu.serve.service import (  # noqa: F401
    CertifiedInferenceService,
    marshal_response,
    resolved_bucket_sizes,
)
from dorpatch_tpu.serve.types import (  # noqa: F401
    HTTP_STATUS,
    DeadlineExceeded,
    Overloaded,
    PredictResult,
    RadiusVerdict,
    ServeError,
)

__all__ = [
    "HTTP_STATUS",
    "CertifiedInferenceService",
    "DeadlineExceeded",
    "HttpFrontend",
    "MicroBatcher",
    "Overloaded",
    "PendingRequest",
    "PredictResult",
    "RadiusVerdict",
    "Replica",
    "ReplicaPool",
    "ServeError",
    "marshal_response",
    "resolved_bucket_sizes",
]
