"""Micro-batcher: bounded request queue with size-or-deadline flush.

Pure host-side queue logic (no jax): single-image requests accumulate in a
FIFO guarded by one condition variable; the worker blocks in `next_batch`
until either

- **size trigger** — a full top bucket's worth of requests is pending
  (`max(bucket_sizes)`), or
- **deadline trigger** — the OLDEST pending request has spent
  `flush_fraction` of its latency budget (default: half). Flushing at the
  half-budget point leaves the other half for the batched forward + certify
  sweep itself, so a lone request still answers inside its deadline instead
  of waiting forever for company.

Backpressure is a typed reject at submit time: past `max_queue_depth`
pending requests, `submit` refuses (the service maps that onto an
`Overloaded` response) — the queue never grows unboundedly and latency
stays bounded by design.

The batcher never pads — it hands the worker at most `max(bucket_sizes)`
real requests; rounding the batch up to a shape bucket is the worker's job
(`service._run_batch`), because padding is a device-layout concern, not a
queueing concern.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence


class PendingRequest:
    """One queued request: the image, its absolute deadline (perf-clock
    seconds), and the event/result slot the submitting thread waits on.

    Resolution is FIRST-WINS: with replica failover a request can briefly be
    visible to two resolvers (the stale replica that was holding it and the
    healthy one it was re-dispatched to), and the contract is exactly one
    answer — the loser's response is shed, never delivered. `claim()` is the
    atomic arbiter; callers that need to account for the outcome *before*
    waking the waiter claim first, then `deliver()`."""

    __slots__ = ("image", "enqueued", "deadline", "done", "result",
                 "redispatched", "trace_id", "_claim_lock", "_claimed")

    def __init__(self, image, enqueued: float, deadline: float,
                 trace_id: str = ""):
        self.image = image
        self.enqueued = enqueued
        self.deadline = deadline
        # ingress correlation id: minted once in `predict()` and carried
        # through dispatch, failover re-dispatch, and every telemetry
        # record this request touches — the SAME id survives a re-enqueue
        # because the request object itself does
        self.trace_id = trace_id
        self.done = threading.Event()
        self.result = None
        # set by the supervisor on failover: at most ONE re-enqueue per
        # request — a second replica failure resolves it as an error
        self.redispatched = False
        self._claim_lock = threading.Lock()
        self._claimed = False  # guarded-by: self._claim_lock

    def budget_s(self) -> float:
        return self.deadline - self.enqueued

    def claim(self) -> bool:
        """Atomically win the exclusive right to answer this request.
        Exactly one caller ever sees True."""
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def deliver(self, result) -> None:
        """Publish the result and wake the waiter. Only the `claim()`
        winner may call this."""
        self.result = result
        self.done.set()

    def resolve(self, result) -> bool:
        """claim + deliver in one step; True if this call won."""
        if not self.claim():
            return False
        self.deliver(result)
        return True


class MicroBatcher:
    """Bounded FIFO with size-or-deadline flush (see module docstring)."""

    def __init__(self, bucket_sizes: Sequence[int], max_queue_depth: int,
                 flush_fraction: float = 0.5, clock=time.perf_counter):
        if not bucket_sizes:
            raise ValueError("bucket_sizes must be non-empty")
        if not 0.0 < flush_fraction <= 1.0:
            raise ValueError(f"flush_fraction must be in (0, 1], got "
                             f"{flush_fraction}")
        self.bucket_sizes = tuple(sorted(int(b) for b in bucket_sizes))
        self.max_batch = self.bucket_sizes[-1]
        self.max_queue_depth = int(max_queue_depth)  # guarded-by: self._cond
        self.flush_fraction = float(flush_fraction)
        self._clock = clock
        self._cond = threading.Condition()
        self._pending = collections.deque()  # guarded-by: self._cond
        self._closed = False  # guarded-by: self._cond

    # ---------------- producer side ----------------

    def submit(self, req: PendingRequest) -> bool:
        """Enqueue; False = backpressure reject (queue at max_queue_depth)
        or batcher closed. Nothing is ever queued on a False return."""
        with self._cond:
            if self._closed or len(self._pending) >= self.max_queue_depth:
                return False
            self._pending.append(req)
            self._cond.notify_all()
            return True

    def requeue(self, reqs: Sequence[PendingRequest]) -> bool:
        """Failover re-enqueue: put a failed replica's in-flight requests at
        the FRONT of the queue (they have already burned queue time) in
        their original arrival order. Deliberately exempt from the depth
        bound — these requests were admitted once and backpressure must not
        turn a replica failure into silent loss. False only when the
        batcher is closed (the caller resolves them as errors instead)."""
        with self._cond:
            if self._closed:
                return False
            self._pending.extendleft(reversed(list(reqs)))
            self._cond.notify_all()
            return True

    def qsize(self) -> int:
        with self._cond:
            return len(self._pending)

    def set_max_queue_depth(self, depth: int) -> None:
        """Degraded-capacity backpressure: when replicas retire, the pool
        shrinks the admission bound so the service rejects with
        `Overloaded` sooner instead of queueing work it can no longer
        answer inside a deadline. Already-queued requests are unaffected."""
        with self._cond:
            self.max_queue_depth = max(0, int(depth))

    def drain(self) -> List[PendingRequest]:
        """Remove and return every queued request (terminal degradation:
        nobody is left to serve them; the pool resolves them as typed
        errors so no waiter hangs)."""
        with self._cond:
            out = list(self._pending)
            self._pending.clear()
            return out

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def close(self) -> None:
        """Stop admitting; the worker drains what is queued, then
        `next_batch` returns None."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # ---------------- consumer side ----------------

    def _flush_at(self, req: PendingRequest) -> float:
        """Perf-clock instant at which `req` forces a flush."""
        return req.enqueued + self.flush_fraction * req.budget_s()

    def _next_flush(self) -> float:
        """Earliest flush instant over EVERY pending request — not just the
        head's: a short-deadline request queued behind a long-deadline one
        must still flush inside its own budget (head-of-line starvation)."""
        return min(self._flush_at(r) for r in self._pending)

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[PendingRequest]]:
        """Block until a flush triggers; returns up to `max_batch` requests
        in arrival order, or None when closed and fully drained.

        With `timeout`, returns an EMPTY list once that many seconds pass
        with no flush — the replica worker's idle heartbeat tick: the
        supervisor's missed-beat staleness detection needs workers to prove
        liveness on a bounded cadence even when no traffic arrives, and a
        worker parked forever inside this wait could not."""
        with self._cond:
            give_up = None if timeout is None else self._clock() + timeout
            while True:
                now = self._clock()
                if self._pending:
                    if (len(self._pending) >= self.max_batch
                            or self._closed
                            or now >= self._next_flush()):
                        return [self._pending.popleft()
                                for _ in range(min(len(self._pending),
                                                   self.max_batch))]
                    if give_up is not None and now >= give_up:
                        return []
                    # sleep until the earliest flush instant; a submit that
                    # fills the bucket (or carries a tighter deadline)
                    # notifies us and we recompute. The wait is clamped:
                    # a pathological deadline (inf/NaN slipping past
                    # validation) must degrade to a slow poll, never an
                    # OverflowError or an unbounded sleep in the worker
                    wait_s = self._next_flush() - now
                    if not (wait_s > 0.0):  # also catches NaN
                        wait_s = 0.05
                    wait_s = min(wait_s, 60.0)
                    if give_up is not None:
                        wait_s = min(wait_s, max(give_up - now, 0.0))
                    self._cond.wait(wait_s)
                elif self._closed:
                    return None
                elif give_up is not None:
                    if now >= give_up:
                        return []
                    self._cond.wait(give_up - now)
                else:
                    # deliberate untimed idle park: every producer path
                    # (submit/requeue/close) notifies under this same
                    # cond, and production workers always pass `timeout`
                    # (the heartbeat tick) — only timeout-less callers
                    # (tests, drains) can reach this branch
                    self._cond.wait()  # noqa: DP502 — producers always notify
