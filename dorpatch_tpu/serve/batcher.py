"""Micro-batcher: bounded request queue with size-or-deadline flush.

Pure host-side queue logic (no jax): single-image requests accumulate in a
FIFO guarded by one condition variable; the worker blocks in `next_batch`
until either

- **size trigger** — a full top bucket's worth of requests is pending
  (`max(bucket_sizes)`), or
- **deadline trigger** — the OLDEST pending request has spent
  `flush_fraction` of its latency budget (default: half). Flushing at the
  half-budget point leaves the other half for the batched forward + certify
  sweep itself, so a lone request still answers inside its deadline instead
  of waiting forever for company.

Backpressure is a typed reject at submit time: past `max_queue_depth`
pending requests, `submit` refuses (the service maps that onto an
`Overloaded` response) — the queue never grows unboundedly and latency
stays bounded by design.

The batcher never pads — it hands the worker at most `max(bucket_sizes)`
real requests; rounding the batch up to a shape bucket is the worker's job
(`service._run_batch`), because padding is a device-layout concern, not a
queueing concern.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence


class PendingRequest:
    """One queued request: the image, its absolute deadline (perf-clock
    seconds), and the event/result slot the submitting thread waits on."""

    __slots__ = ("image", "enqueued", "deadline", "done", "result")

    def __init__(self, image, enqueued: float, deadline: float):
        self.image = image
        self.enqueued = enqueued
        self.deadline = deadline
        self.done = threading.Event()
        self.result = None

    def budget_s(self) -> float:
        return self.deadline - self.enqueued

    def resolve(self, result) -> None:
        self.result = result
        self.done.set()


class MicroBatcher:
    """Bounded FIFO with size-or-deadline flush (see module docstring)."""

    def __init__(self, bucket_sizes: Sequence[int], max_queue_depth: int,
                 flush_fraction: float = 0.5, clock=time.perf_counter):
        if not bucket_sizes:
            raise ValueError("bucket_sizes must be non-empty")
        if not 0.0 < flush_fraction <= 1.0:
            raise ValueError(f"flush_fraction must be in (0, 1], got "
                             f"{flush_fraction}")
        self.bucket_sizes = tuple(sorted(int(b) for b in bucket_sizes))
        self.max_batch = self.bucket_sizes[-1]
        self.max_queue_depth = int(max_queue_depth)
        self.flush_fraction = float(flush_fraction)
        self._clock = clock
        self._cond = threading.Condition()
        self._pending = collections.deque()
        self._closed = False

    # ---------------- producer side ----------------

    def submit(self, req: PendingRequest) -> bool:
        """Enqueue; False = backpressure reject (queue at max_queue_depth)
        or batcher closed. Nothing is ever queued on a False return."""
        with self._cond:
            if self._closed or len(self._pending) >= self.max_queue_depth:
                return False
            self._pending.append(req)
            self._cond.notify_all()
            return True

    def qsize(self) -> int:
        with self._cond:
            return len(self._pending)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def close(self) -> None:
        """Stop admitting; the worker drains what is queued, then
        `next_batch` returns None."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # ---------------- consumer side ----------------

    def _flush_at(self, req: PendingRequest) -> float:
        """Perf-clock instant at which `req` forces a flush."""
        return req.enqueued + self.flush_fraction * req.budget_s()

    def _next_flush(self) -> float:
        """Earliest flush instant over EVERY pending request — not just the
        head's: a short-deadline request queued behind a long-deadline one
        must still flush inside its own budget (head-of-line starvation)."""
        return min(self._flush_at(r) for r in self._pending)

    def next_batch(self) -> Optional[List[PendingRequest]]:
        """Block until a flush triggers; returns up to `max_batch` requests
        in arrival order, or None when closed and fully drained."""
        with self._cond:
            while True:
                if self._pending:
                    now = self._clock()
                    if (len(self._pending) >= self.max_batch
                            or self._closed
                            or now >= self._next_flush()):
                        return [self._pending.popleft()
                                for _ in range(min(len(self._pending),
                                                   self.max_batch))]
                    # sleep until the earliest flush instant; a submit that
                    # fills the bucket (or carries a tighter deadline)
                    # notifies us and we recompute. The wait is clamped:
                    # a pathological deadline (inf/NaN slipping past
                    # validation) must degrade to a slow poll, never an
                    # OverflowError or an unbounded sleep in the worker
                    wait_s = self._next_flush() - now
                    if not (wait_s > 0.0):  # also catches NaN
                        wait_s = 0.05
                    self._cond.wait(min(wait_s, 60.0))
                elif self._closed:
                    return None
                else:
                    self._cond.wait()
