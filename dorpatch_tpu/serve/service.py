"""In-process certified-inference service: micro-batched PatchCleanser
serving with a shape-bucketed zero-recompile hot path.

Request lifecycle:

1. `predict()` (the Python client API; the HTTP front-end calls the same
   method) validates the image, stamps its deadline, and submits it to the
   `MicroBatcher`'s bounded queue — or returns a typed `Overloaded` reject
   when the queue is at depth (backpressure, never unbounded queueing).
2. A replica worker thread (one of `serve_cfg.replicas`, all sharing the
   one queue — see `serve/pool.py` for the supervisor, health, and
   failover story) pops a batch on a size-or-deadline trigger, pads it up
   to the nearest shape bucket (`data.pad_to_bucket` /
   `data.batch_buckets`), and drives ITS OWN jitted programs: one
   undefended forward plus the full PatchCleanser defense bank, built from
   a per-replica closure so trace caches stay independent. Every program
   was compiled for every bucket at startup warmup and is registered with
   the PR 2 recompile watchdog (`timed_first_call(..., recompile_budget=
   n_buckets)`), so live traffic NEVER retraces — a shape leak raises
   `RecompileBudgetExceeded` instead of silently turning the service into
   a compile loop.
3. `marshal_response` — the one designated device-to-host sync point in
   this package (lint rule DP107) — materializes the verdicts, checks each
   request's deadline, and resolves the waiters.

Observability: when built with a `result_dir`, the service writes the
standard telemetry contract (`run.json`, `events.jsonl`) — a `serve.batch`
span per flush (bucket, occupancy), a `serve.request` event per answered or
rejected request (status, latency), and queue-depth samples — which
`python -m dorpatch_tpu.observe.report` renders as the "serve" section
(p50/p95/p99 latency, throughput, occupancy, reject rate).
"""

from __future__ import annotations

import contextlib
import math
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from dorpatch_tpu import data as data_lib
from dorpatch_tpu import defense as defense_lib
from dorpatch_tpu import observe
from dorpatch_tpu.config import DefenseConfig, ExperimentConfig, ServeConfig
from dorpatch_tpu.defense import build_defenses
from dorpatch_tpu.serve.batcher import MicroBatcher, PendingRequest
from dorpatch_tpu.serve.pool import ReplicaPool
from dorpatch_tpu.serve.types import (
    DeadlineExceeded,
    Overloaded,
    PredictResult,
    RadiusVerdict,
    ServeError,
)


def resolved_bucket_sizes(cfg: ServeConfig) -> Sequence[int]:
    """cfg.bucket_sizes, or the shared `data.batch_buckets` ladder."""
    if cfg.bucket_sizes:
        return tuple(sorted(int(b) for b in cfg.bucket_sizes))
    return data_lib.batch_buckets(cfg.max_batch)


def marshal_response(reqs: List[PendingRequest], clean_logits,
                     per_defense: List[Any], ratios: Sequence[float],
                     bucket: int, clock=time.perf_counter) -> List[Any]:
    """THE designated response-marshalling function: the only place in
    `serve/` allowed to synchronize device results to the host (lint rule
    DP107 flags `.item()`/`device_get`/`block_until_ready` anywhere else in
    this package). By the time this runs, every program in the batch has
    been DISPATCHED (`per_defense` holds either device-resident
    `PatchCleanser.predict_tables` tuples or scheduled pruned pendings),
    so the transfers here — including the pruned finalize inside
    `defense.materialize_verdicts` — are the batch's only blocking points.
    Slices the real rows out of the padded-bucket results, enforces each
    request's deadline, and builds the typed responses."""
    clean = np.asarray(clean_logits).argmax(axis=-1)
    tables = [defense_lib.materialize_verdicts(entry)
              for entry in per_defense]
    now = clock()
    out: List[Any] = []
    for i, r in enumerate(reqs):
        latency_ms = (now - r.enqueued) * 1e3
        if now > r.deadline:
            out.append(DeadlineExceeded(latency_ms=latency_ms,
                                        deadline_ms=r.budget_s() * 1e3))
            continue
        verdicts = tuple(
            RadiusVerdict(ratio=float(ratio), prediction=int(pred[i]),
                          certified=bool(cert[i]))
            for ratio, (pred, cert, _fwd, _fe) in zip(ratios, tables)
        )
        out.append(PredictResult(
            prediction=verdicts[0].prediction,
            certified=all(v.certified for v in verdicts),
            clean_prediction=int(clean[i]),
            verdicts=verdicts,
            latency_ms=latency_ms,
            bucket=int(bucket),
            batch_images=len(reqs),
            certify_forwards=sum(int(fwd[i])
                                 for _p, _c, fwd, _fe in tables),
            certify_forward_equivalents=float(
                sum(fe[i] for _p, _c, _fwd, fe in tables)),
        ))
    return out


class CertifiedInferenceService:
    """Micro-batching front door over a victim + PatchCleanser defense bank.

    Construct directly with an `apply_fn` (tests, stub victims) or via
    `from_config` (real models through `models.get_model`). `start()`
    warms every bucket's programs and launches the worker; `predict()` is
    the client API; `stop()` drains and restores global state. Usable as a
    context manager."""

    def __init__(
        self,
        apply_fn: Callable[[Any, jax.Array], jax.Array],
        params: Any,
        num_classes: int,
        img_size: int,
        serve_cfg: ServeConfig = ServeConfig(),
        defense_cfg: DefenseConfig = DefenseConfig(),
        result_dir: Optional[str] = None,
        run_cfg: Optional[ExperimentConfig] = None,
        enforce_budgets: bool = True,
        clock=time.perf_counter,
        incremental_engine: Any = None,
        aot_cfg: Any = None,
        recert_cfg: Any = None,
    ):
        self.apply_fn = apply_fn
        self.params = params
        self.num_classes = int(num_classes)
        self.img_size = int(img_size)
        self.serve_cfg = serve_cfg
        self.defense_cfg = defense_cfg
        self.result_dir = result_dir
        self.run_cfg = run_cfg
        self.enforce_budgets = enforce_budgets
        self._clock = clock
        # AotConfig (or None): warm-boot the serving programs from the AOT
        # executable store instead of tracing — see _start_inner
        self.aot_cfg = aot_cfg
        self._aot_stats: Optional[Dict[str, Any]] = None
        # RecertConfig (or None): the robustness boot gate + the snapshot
        # behind `GET /robustness` — see _start_inner
        self.recert_cfg = recert_cfg
        self._robustness: Optional[Dict[str, Any]] = None

        self.bucket_sizes = tuple(resolved_bucket_sizes(serve_cfg))
        n_buckets = len(self.bucket_sizes)
        self.batcher = MicroBatcher(self.bucket_sizes,
                                    serve_cfg.max_queue_depth,
                                    serve_cfg.flush_fraction, clock=clock)
        # one clean-forward program + one certifier per radius, each allowed
        # exactly one trace per shape bucket — warmup compiles them all, so
        # live traffic runs at _cache_size() == n_buckets forever. The bank
        # wraps a FRESH closure, not `apply_fn` itself: jax.jit shares its
        # trace cache across wrappers of the same function object, so two
        # services over one victim function would otherwise pool their
        # trace counts and trip each other's recompile budgets (replica
        # banks get the same isolation in `_build_bank`)
        def _bank_apply(p, x, _apply=apply_fn):
            return _apply(p, x)

        self._clean = observe.timed_first_call(
            jax.jit(_bank_apply), "serve.clean_predict",
            recompile_budget=n_buckets)
        self._incremental_engine = incremental_engine
        self.defenses = build_defenses(_bank_apply, img_size, defense_cfg,
                                       recompile_budget=n_buckets,
                                       incremental=incremental_engine)
        self.ratios = tuple(defense_cfg.ratios)
        # effective double-masking schedule ("off" | "exact" | "consensus",
        # resolved once — n_patch!=1 families force "off"): pruned modes
        # schedule only the second-round work each verdict actually reads
        # ("exact" prunes disagreeing images to their minority rows;
        # "consensus" additionally answers first-round-unanimous traffic
        # from the 36-mask table alone, with round-1-only certificates)
        self.prune = (self.defenses[0].resolved_prune()
                      if self.defenses else "off")
        # effective incremental mode (off | token | token-exact | mixer
        # | mixer-exact | stem):
        # with an engine attached the pruned-path programs are the
        # engine-backed twins, and the per-request certify cost lands in
        # `certify_forward_equivalents` as fractional full forwards
        self.incremental = (self.defenses[0].resolved_incremental()
                            if self.defenses else "off")

        self._lock = threading.Lock()
        # ONE typed registry for every piece of serving accounting: the
        # `/stats` block, `GET /metrics`, the report CLI, bench rows, and
        # the loadgen reconciliation all render from these series — there
        # is no second ledger to drift from (DP108 enforces this).
        self.metrics = observe.MetricRegistry()
        m = self.metrics
        self._m_requests = m.counter(
            "serve_requests_total",
            "terminal request outcomes by status")
        self._m_received = m.counter(
            "serve_received_total", "requests admitted to the queue")
        self._m_batches = m.counter(
            "serve_batches_total", "dispatched micro-batches")
        self._m_batch_images = m.counter(
            "serve_batch_images_total", "images across dispatched batches")
        self._m_batch_slots = m.counter(
            "serve_batch_slots_total",
            "padded bucket slots across dispatched batches")
        self._m_certify_fwd = m.counter(
            "serve_certify_forwards_total",
            "model forwards spent on certification")
        self._m_certify_exh = m.counter(
            "serve_certify_forwards_exhaustive_total",
            "forwards an exhaustive double-masking pass would have spent")
        self._m_certify_fe = m.counter(
            "serve_certify_forward_equivalents_total",
            "fractional full-forward equivalents (incremental engines)")
        self._m_latency = m.histogram(
            "serve_latency_ms", "end-to-end latency of ok requests (ms)")
        self._m_replica_latency = m.histogram(
            "serve_replica_latency_ms",
            "per-replica batch-completion latency of ok requests (ms)")
        self._m_replica_events = m.counter(
            "serve_replica_events_total",
            "replica lifecycle transitions by event")
        # computed gauge: reads the batcher at exposition time, so the
        # admit path pays zero extra bookkeeping
        m.gauge("serve_queue_depth", "live batcher queue depth"
                ).set_function(lambda: float(self.batcher.qsize()))
        self._pool: Optional[ReplicaPool] = None
        self._stack: Optional[contextlib.ExitStack] = None
        self._elog: Optional[observe.EventLog] = None
        self._warm = False
        self._started_at: Optional[float] = None

    @classmethod
    def from_config(cls, cfg: ExperimentConfig,
                    result_dir: Optional[str] = None
                    ) -> "CertifiedInferenceService":
        """Real-model service: the victim `models.get_model` resolves for
        `cfg`, the defense bank from `cfg.defense`, serving knobs from
        `cfg.serve`. `result_dir` defaults to `<results_root>/serve`."""
        from dorpatch_tpu.models import get_model

        victim = get_model(cfg.dataset, cfg.base_arch, cfg.model_dir,
                           cfg.img_size, gn_impl=cfg.gn_impl)
        if result_dir is None:
            result_dir = os.path.join(cfg.results_root, "serve")
        return cls(victim.apply, victim.params, victim.num_classes,
                   cfg.img_size, serve_cfg=cfg.serve,
                   defense_cfg=cfg.defense,
                   result_dir=result_dir if cfg.metrics_log else None,
                   run_cfg=cfg,
                   incremental_engine=victim.incremental,
                   aot_cfg=getattr(cfg, "aot", None),
                   recert_cfg=getattr(cfg, "recert", None))

    # ---------------- lifecycle ----------------

    def start(self) -> "CertifiedInferenceService":
        if self._pool is not None:
            raise RuntimeError("service already started")
        self._stack = contextlib.ExitStack()
        try:
            self._start_inner()
        except BaseException:
            # a failed start (warmup OOM, budget trip) must unwind every
            # global it installed: active EventLog, run span, recompile
            # guard — otherwise the NEXT run in this process inherits them
            if self._pool is not None:
                self._pool.begin_stop()
                self.batcher.close()
                self._pool = None
            self._stack.close()
            self._stack = None
            self._elog = None
            raise
        return self

    def _start_inner(self) -> None:
        if self.batcher.closed:
            # a stopped service restarts cleanly: the old batcher was
            # closed (and drained) by stop(), so admit through a fresh one
            self.batcher = MicroBatcher(
                self.bucket_sizes, self.serve_cfg.max_queue_depth,
                self.serve_cfg.flush_fraction, clock=self._clock)
        if self.result_dir:
            run_id = observe.new_run_id()
            observe.write_run_manifest(
                self.result_dir, self.run_cfg, run_id=run_id,
                extra={**observe.jax_environment(), "service": "serve"})
            self._elog = observe.EventLog(
                os.path.join(self.result_dir, observe.events_filename(0)),
                run_id=run_id)
            self._stack.enter_context(self._elog)
            self._stack.enter_context(observe.active(self._elog))
            # the service's lifetime IS the run: the report's wall-clock,
            # phase, and open-span accounting all hang off this span (a
            # crashed service leaves it open — the hang signature)
            self._stack.enter_context(observe.span("run", service="serve"))
        if self.recert_cfg is not None and (
                getattr(self.recert_cfg, "require", "off") != "off"
                or getattr(self.recert_cfg, "dir", "")):
            # robustness boot gate, deliberately BEFORE any compile work:
            # under `--require-recert strict` a failing/stale/absent recert
            # verdict refuses serving-ready here with a typed
            # RecertGateError (mirroring AOT strict boot); `warn` records
            # the degraded status and serves, `GET /robustness` renders it
            from dorpatch_tpu.recert.gate import boot_gate

            self._robustness = boot_gate(
                getattr(self.recert_cfg, "dir", ""),
                getattr(self.recert_cfg, "require", "off"))
            if self._robustness is not None:
                observe.record_event(
                    "serve.recert_gate",
                    require=self._robustness["require"],
                    status=self._robustness["status"],
                    generation=self._robustness.get("generation"),
                    worst_margin=self._robustness.get("worst_margin"))
        if self.enforce_budgets:
            # arm the PR 2 recompile watchdog for the serving process: any
            # program re-tracing past its per-bucket budget fails the batch
            # loudly instead of degrading into a silent compile loop
            from dorpatch_tpu.analysis.sanitize import RecompileWatchdog

            prev = observe.recompile_guard()
            observe.set_recompile_guard(RecompileWatchdog())
            self._stack.callback(observe.set_recompile_guard, prev)
        if (self.aot_cfg is not None
                and getattr(self.aot_cfg, "mode", "off") != "off"
                and getattr(self.aot_cfg, "cache_dir", "")):
            # AOT warm boot, deliberately AFTER the watchdog is armed and
            # BEFORE warmup: every program's executable is deserialized
            # from the store and installed behind its timer, so the warmup
            # loop below runs it without tracing — the zero-trace contract
            # is enforced by the same watchdog live traffic runs under.
            # Misses compile-and-rewrite ("auto") or fail boot ("strict");
            # a stale executable is never installed either way.
            from dorpatch_tpu.aot.boot import warm_boot

            self._aot_stats = warm_boot(self.trace_entrypoints(),
                                        self.aot_cfg, clock=self._clock)
        if self.serve_cfg.warmup:
            self.warmup()
        self._started_at = self._clock()
        observe.record_event(
            "serve.started", buckets=list(self.bucket_sizes),
            ratios=[float(r) for r in self.ratios],
            max_queue_depth=self.batcher.max_queue_depth,
            deadline_ms=float(self.serve_cfg.deadline_ms),
            replicas=max(1, int(getattr(self.serve_cfg, "replicas", 1))))
        chaos = None
        if getattr(self.serve_cfg, "chaos", ""):
            # serve-side fault injection (shared harness with the farm):
            # the state dir holds the O_EXCL fired-markers, so each fault
            # fires exactly once per service run
            import tempfile

            from dorpatch_tpu.chaos import Chaos, parse_faults

            state_dir = self.result_dir or tempfile.mkdtemp(
                prefix="dorpatch_serve_chaos_")
            chaos = Chaos(parse_faults(self.serve_cfg.chaos),
                          job_id="serve", state_dir=state_dir,
                          crash_mode="raise")
            if self.result_dir:
                # kill_backend's flush-before-SIGKILL contract: the fleet
                # cross-check needs the victim's committed counters on disk
                # even though stop() never runs
                chaos.bind(metrics_flush=lambda: self.metrics.dump(
                    os.path.join(self.result_dir, "metrics.json")))
        # the pool builds replicas 1..N-1 (fresh per-replica program banks,
        # AOT-booted and warmed through _build_bank), adopts replica 0's
        # bank from this service, launches every worker loop, and starts
        # the supervisor
        self._pool = ReplicaPool(self, chaos=chaos)
        self._pool.start()

    def _drain_timeout_s(self) -> float:
        """How long stop() waits for in-flight work: twice the request
        deadline (a draining batch can hold a full deadline of queue wait
        plus the batched forward itself), floored so sub-second test
        deadlines still tolerate a slow compile straggler."""
        return max(2.0 * float(self.serve_cfg.deadline_ms) / 1e3, 5.0)

    def stopping(self) -> bool:
        """True inside stop()'s drain window (begin_stop() fired, pool not
        yet released): the HTTP frontend answers /stats and /metrics with
        a typed 503 for its duration instead of racing the teardown."""
        pool = self._pool
        return pool is not None and pool.stopping()

    def stop(self) -> None:
        if self._pool is None:
            return
        self._pool.begin_stop()
        self.batcher.close()
        drain_s = self._drain_timeout_s()
        if not self._pool.join(drain_s):
            # a wedged device call: keep the pool reference (so waiting
            # clients don't misreport a dead worker) and leave the
            # EventLog open for its late writes; the daemon threads die
            # with the process. A later stop() retries the join.
            observe.record_event("serve.drain_timeout",
                                 timeout_s=round(drain_s, 3),
                                 replicas=self._pool.still_draining())
            observe.log(f"WARNING: serve workers still draining after "
                        f"{drain_s:.1f}s; telemetry stays open",
                        file=sys.stderr)
            if self.result_dir:
                # the books still land on disk: a wedged shutdown must not
                # cost the fleet cross-check its server snapshot (the
                # clean-join path below overwrites with the final dump)
                self.metrics.dump(
                    os.path.join(self.result_dir, "metrics.json"))
            return
        self._pool = None
        observe.record_event("serve.stopped", **self._snapshot())
        if self.result_dir:
            # final atomic snapshot next to events.jsonl: the offline
            # report and the fleet cross-check read this file
            self.metrics.dump(os.path.join(self.result_dir, "metrics.json"))
        if self._stack is not None:
            self._stack.close()
            self._stack = None
            self._elog = None

    def capture_profile(self, duration_ms: float = 500.0) -> Optional[str]:
        """On-demand bounded `jax.profiler` capture into the run dir (the
        `POST /profile` hook). None when no result_dir is configured or a
        capture is already running."""
        return observe.capture_profile(self.result_dir,
                                       duration_s=float(duration_ms) / 1e3)

    def __enter__(self) -> "CertifiedInferenceService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------- warmup / trace accounting ----------------

    def warmup(self) -> Dict[str, int]:
        """Compile every program for every shape bucket (the whole cost of
        serving happens HERE, before traffic). Returns the per-program trace
        counts — the baseline the zero-recompile contract is checked
        against. Under a pruned schedule the bucket loop warms the clean
        forward only and `PatchCleanser.warm_pruned` compiles the certify
        programs exactly once per shape — phase 1 + pair audit per image
        bucket, the row program per row bucket (dispatching the certifiers
        here too would re-execute the same sweeps a second time): live
        traffic decides per batch which verdict classes — and therefore
        which ragged second-round shapes — occur, and all of them must
        already be compiled."""
        self._warm_bank(self._clean, self.defenses, replica=0)
        self._warm = True
        return self.trace_counts()

    def _warm_bank(self, clean, defenses, replica: int = 0) -> None:
        """Warm ONE replica's program bank (see `warmup`); replica 0's bank
        is the service's own, the pool warms the others through here."""
        # warmup dummies ride the streaming prefetcher
        # (data.prefetch_to_device): the host->device transfer for bucket
        # N+1 is issued while bucket N compiles and dispatches, and warm
        # placements go through the same placement rule as live traffic
        def dummies():
            for b in self.bucket_sizes:
                yield (np.full((b, self.img_size, self.img_size, 3), 0.5,
                               np.float32), np.zeros((b,), np.int64))

        placed_stream = data_lib.prefetch_to_device(dummies(), depth=2)
        for b, (placed, _) in zip(self.bucket_sizes, placed_stream):
            t0 = self._clock()
            if self.prune == "off":
                logits, per_defense = self._dispatch(
                    placed, b, clean=clean, defenses=defenses)
            else:
                logits, per_defense = clean(self.params, placed), []
            # marshalling doubles as the completion sync for the warmup call
            marshal_response([], logits, per_defense, self.ratios, b,
                             clock=self._clock)
            observe.record_event("serve.warmup", bucket=int(b),
                                 replica=int(replica),
                                 dur_s=round(self._clock() - t0, 6))
        if self.prune != "off":
            t0 = self._clock()
            for d in defenses:
                d.warm_pruned(self.params, self.bucket_sizes,
                              num_classes=self.num_classes)
            observe.record_event("serve.warmup_pruned",
                                 incremental=self.incremental,
                                 replica=int(replica),
                                 row_buckets=[int(w) for w in
                                              defenses[0].row_bucket_sizes],
                                 dur_s=round(self._clock() - t0, 6))

    def _build_bank(self, slot: int):
        """Build one replica's complete program bank from a FRESH closure
        over `apply_fn` — jit caches live on the wrapper object, so a fresh
        closure per replica keeps every replica's trace caches (and
        therefore its warmup, AOT boot, and recompile budgets) fully
        independent. AOT-boots from the executable store when configured
        (the store is keyed on program name + interface + signature, so all
        replicas share the same entries — a restart after the first boot is
        all hits, i.e. zero traces), then warms. Returns
        `(clean, defenses, aot_stats)`."""
        apply_fn = self.apply_fn

        def replica_apply(p, x, _apply=apply_fn):
            return _apply(p, x)

        n_buckets = len(self.bucket_sizes)
        clean = observe.timed_first_call(
            jax.jit(replica_apply), "serve.clean_predict",
            recompile_budget=n_buckets)
        defenses = build_defenses(replica_apply, self.img_size,
                                  self.defense_cfg,
                                  recompile_budget=n_buckets,
                                  incremental=self._incremental_engine)
        aot_stats = None
        if (self.aot_cfg is not None
                and getattr(self.aot_cfg, "mode", "off") != "off"
                and getattr(self.aot_cfg, "cache_dir", "")):
            from dorpatch_tpu.aot.boot import warm_boot

            aot_stats = warm_boot(self._bank_entrypoints(clean, defenses),
                                  self.aot_cfg, clock=self._clock)
        if self.serve_cfg.warmup:
            self._warm_bank(clean, defenses, replica=slot)
        return clean, defenses, aot_stats

    def trace_entrypoints(self) -> List[tuple]:
        """`(name, program, abstract example args)` for every serving
        program at every shape bucket — the program auditor's enumeration
        hook (`analysis/entrypoints.py`). Bucket-suffixed names (e.g.
        `serve.clean_predict[b8]`) keep one registry entry per compiled
        shape bucket; nothing is executed. Always replica 0's bank — every
        replica runs the same programs with the same names, so the
        registry, baseline, and AOT store see ONE program set."""
        return self._bank_entrypoints(self._clean, self.defenses)

    def _bank_entrypoints(self, clean, defenses) -> List[tuple]:
        out: List[tuple] = []
        for b in self.bucket_sizes:
            imgs = jax.ShapeDtypeStruct(
                (b, self.img_size, self.img_size, 3), np.dtype(np.float32))
            out.append((f"serve.clean_predict[b{b}]", clean,
                        (self.params, imgs)))
            for d in defenses:
                r = d.spec.patch_ratio
                out.append((f"defense.predict.r{r}[b{b}]", d._predict,
                            (self.params, imgs, self.num_classes)))
                if self.prune != "off":
                    # the programs the resolved pruned(+incremental) path
                    # actually dispatches — engine-backed twins included
                    for name, fn, kind in d.pruned_programs():
                        if kind == "imgs":
                            out.append((f"{name}[b{b}]", fn,
                                        (self.params, imgs)))
        if self.prune != "off":
            for d in defenses:
                for name, fn, kind in d.pruned_programs():
                    if kind not in ("rows", "rows_sets"):
                        continue
                    for w in d.row_bucket_sizes:
                        imgs_g = jax.ShapeDtypeStruct(
                            (int(w), self.img_size, self.img_size, 3),
                            np.dtype(np.float32))
                        arg = (jax.ShapeDtypeStruct(
                            (int(w),), np.dtype(np.int32))
                            if kind == "rows" else jax.ShapeDtypeStruct(
                                (int(w), d.num_first), np.dtype(np.int32)))
                        out.append((f"{name}[w{w}]", fn,
                                    (self.params, imgs_g, arg)))
        return out

    def trace_counts(self) -> Dict[str, int]:
        """Compiled-trace count per jitted program (shape buckets seen so
        far). After warmup the clean forward (and, pruned: phase 1 + pair
        audit) sit at `len(bucket_sizes)` and the row program at
        `len(row_bucket_sizes)`; the serve e2e asserts this dict is
        IDENTICAL before and after traffic. Reads replica 0's live bank;
        per-replica totals are in `stats()["replicas"]`."""
        return self._bank_trace_counts(self._clean, self.defenses)

    def _bank_trace_counts(self, clean, defenses) -> Dict[str, int]:
        out = {"serve.clean_predict": int(clean._cache_size())}
        for d in defenses:
            name = f"defense.predict.r{d.spec.patch_ratio}"
            out[name] = int(d._predict._cache_size())
            if self.prune != "off":
                out.update(d.pruned_trace_counts())
        return out

    # ---------------- client API ----------------

    def predict(self, image, deadline_ms: Optional[float] = None,
                trace_id: str = ""):
        """Certified prediction for ONE image (HWC float in [0, 1]).
        Returns a typed response: `PredictResult`, `Overloaded`,
        `DeadlineExceeded`, or `ServeError`. Thread-safe; this is the same
        path the HTTP front-end drives. `trace_id` correlates this request
        across processes (minted here when the ingress didn't)."""
        tid = str(trace_id) if trace_id else observe.new_trace_id()
        try:
            # noqa-reason: parses the client's HOST-side nested list/array;
            # no device value can reach this path
            arr = np.asarray(image, dtype=np.float32)  # noqa: DP107
        except (ValueError, TypeError) as e:  # ragged / non-numeric input
            self._m_requests.inc(status="error")
            observe.record_event("serve.request", status="error",
                                 reason="bad_image", trace=tid)
            return ServeError(reason=f"image does not parse: {e}")
        want = (self.img_size, self.img_size, 3)
        if arr.shape != want:
            self._m_requests.inc(status="error")
            observe.record_event("serve.request", status="error",
                                 reason="bad_shape", trace=tid)
            return ServeError(reason=f"image shape {arr.shape} != {want}")
        if deadline_ms is not None and not (
                isinstance(deadline_ms, (int, float))
                and math.isfinite(deadline_ms) and deadline_ms > 0):
            # Infinity/NaN parse as legal JSON floats but would poison the
            # batcher's flush-instant arithmetic (inf wait / NaN min) —
            # one bad request must never wedge the worker
            self._m_requests.inc(status="error")
            observe.record_event("serve.request", status="error",
                                 reason="bad_deadline", trace=tid)
            return ServeError(
                reason=f"deadline_ms must be a finite positive number, "
                       f"got {deadline_ms!r}")
        now = self._clock()
        budget_s = (deadline_ms if deadline_ms is not None
                    else self.serve_cfg.deadline_ms) / 1e3
        req = PendingRequest(arr, enqueued=now, deadline=now + budget_s,
                             trace_id=tid)
        if not self.batcher.submit(req):
            depth = self.batcher.qsize()
            self._m_requests.inc(status="overloaded")
            # event status matches the client-visible response status, so
            # loadgen's by_status and the report's agree on the same run
            observe.record_event("serve.request", status="overloaded",
                                 queue_depth=depth, trace=tid)
            return Overloaded(queue_depth=depth,
                              limit=self.batcher.max_queue_depth)
        self._m_received.inc()
        # `opens_trace`: the fleet report joins on these — an admitted
        # trace with no later terminal record is an orphaned request
        observe.record_event("serve.admit", trace=tid, opens_trace=True,
                             queue_depth=self.batcher.qsize())
        # every admitted request IS resolved (the worker sheds expired ones
        # with DeadlineExceeded, the supervisor re-dispatches a failed
        # replica's in-flight work), so wait for the answer and poll only
        # for the one failure the pool cannot recover from: no replica left
        # that could ever serve again. A fixed timeout here would misfire
        # on a backlogged-but-healthy pool and double-count the request
        # once a worker answers; the claim() arbitration keeps this path
        # and a racing resolver from ever double-answering. The
        # deadline+grace backstop exists for the failure NOBODY resolves
        # (a request dropped by a bug in the failover bookkeeping): a
        # worker would have shed it typed at the deadline, so waiting out
        # the deadline plus the supervisor's whole detection window means
        # it is lost — abandon typed rather than hang the client.
        while not req.done.wait(timeout=1.0):
            pool = self._pool
            if pool is None or not pool.serving_possible():
                if req.claim():
                    self._m_requests.inc(status="internal_error")
                    observe.record_event(
                        "serve.request", status="internal_error",
                        reason="worker thread died", trace=tid)
                    req.deliver(ServeError(reason="worker thread died",
                                           status="internal_error"))
                    return req.result
            elif self._clock() > req.deadline + max(
                    2.0 * pool.stale_after_s, 5.0):
                if req.claim():
                    now2 = self._clock()
                    self._m_requests.inc(status="deadline_exceeded")
                    observe.record_event(
                        "serve.request", status="deadline_exceeded",
                        latency_s=round(now2 - req.enqueued, 6),
                        abandoned=True, trace=tid)
                    req.deliver(DeadlineExceeded(
                        latency_ms=(now2 - req.enqueued) * 1e3,
                        deadline_ms=req.budget_s() * 1e3))
                    return req.result
        return req.result

    def healthz(self) -> dict:
        """Liveness the load balancer can act on: "ok" only while at least
        one healthy replica thread is actually serving (the front-end maps
        anything else to 503, so a dead-pool instance drains instead of
        burning every routed request's poll interval). `worker_alive` stays
        the single-worker-era name: any replica thread alive."""
        pool = self._pool
        alive = pool is not None and pool.worker_alive()
        healthy = pool.healthy_count() if pool is not None else 0
        out = {"status": "ok" if healthy > 0 else "unhealthy",
               "worker_alive": alive, "warm": self._warm,
               "queue_depth": self.batcher.qsize()}
        if pool is not None:
            out["replicas"] = {
                "total": len(pool.replicas), "healthy": healthy,
                "retired": sum(1 for r in pool.replicas
                               if r.state == "retired")}
        return out

    def robustness(self) -> dict:
        """The recert verdict snapshot loaded at boot (`GET /robustness`):
        gate mode, verdict status, generation, worst margin, per-cell
        grid. Reflects the verdict AS OF BOOT — the gate is a boot gate
        (mirroring AOT strict boot), so a fresh generation's verdict takes
        effect at the next restart."""
        if self._robustness is None:
            return {"require": "off", "status": "unconfigured"}
        return dict(self._robustness)

    def stats(self) -> dict:
        s = self._snapshot()
        s["queue_depth"] = self.batcher.qsize()
        s["buckets"] = list(self.bucket_sizes)
        s["trace_counts"] = self.trace_counts()
        s["warm"] = self._warm
        if self._aot_stats is not None:
            s["aot"] = self._aot_stats
        if self._robustness is not None:
            s["robustness"] = {
                k: self._robustness.get(k)
                for k in ("require", "status", "generation", "worst_margin")}
        if self._started_at is not None:
            s["uptime_s"] = round(self._clock() - self._started_at, 3)
        pool = self._pool
        if pool is not None:
            s["replicas"] = pool.snapshot()
            s["failover"] = {"redispatched": pool.redispatched,
                             "duplicates_shed": pool.duplicates_shed}
        return s

    def _snapshot(self) -> dict:
        # every number here is a registry read — /stats is a VIEW over the
        # same series `GET /metrics` exposes, never a second ledger
        v = self.metrics.value
        completed = int(v("serve_requests_total", status="ok"))
        # "errors" folds both error classes the old ledger lumped together:
        # client-fault `error` and service-fault `internal_error`
        s = {
            "received": int(v("serve_received_total")),
            "completed": completed,
            "rejected": int(v("serve_requests_total", status="overloaded")),
            "deadline_exceeded": int(
                v("serve_requests_total", status="deadline_exceeded")),
            "errors": int(v("serve_requests_total", status="error")
                          + v("serve_requests_total",
                              status="internal_error")),
            "batches": int(v("serve_batches_total")),
            "batch_images": int(v("serve_batch_images_total")),
            "batch_slots": int(v("serve_batch_slots_total")),
        }
        s["occupancy"] = (round(s["batch_images"] / s["batch_slots"], 4)
                          if s["batch_slots"] else 0.0)
        # certification-cost summary: mean evaluated masked-table entries
        # per answered request, their fractional full-forward cost
        # (incremental paths), and the fraction of the exhaustive sweep the
        # scheduler skipped (0.0 when prune=off)
        s["prune"] = self.prune
        s["incremental"] = self.incremental
        # the certify sweep precision this service's program bank runs at
        # (DefenseConfig.compute_dtype: "float32" | "bfloat16")
        s["compute_dtype"] = self.defense_cfg.compute_dtype
        fwd = int(v("serve_certify_forwards_total"))
        exh = int(v("serve_certify_forwards_exhaustive_total"))
        fe = float(v("serve_certify_forward_equivalents_total"))
        s["certify_forwards"] = {
            "total": fwd,
            "per_request": round(fwd / completed, 1)
            if completed else None,
            "forward_equivalents": round(fe, 2),
            "forward_equivalents_per_request": round(fe / completed, 2)
            if completed else None,
            "prune_rate": round(1.0 - fwd / exh, 4) if exh else None,
            "speedup_equivalent": round(exh / fe, 2) if fe else None,
        }
        # denominator = every terminal outcome, matching the report CLI's
        # all-serve.request-events accounting, so /stats and the offline
        # report agree on the same run
        total = (s["completed"] + s["rejected"] + s["deadline_exceeded"]
                 + s["errors"])
        s["reject_rate"] = round(s["rejected"] / total, 4) if total else 0.0

        def pct(q):
            p = self._m_latency.percentile(q)
            return None if p is None else round(p, 3)

        s["latency_ms"] = {"count": self._m_latency.count(),
                           "p50": pct(0.50), "p95": pct(0.95),
                           "p99": pct(0.99)}
        return s

    # ---------------- worker ----------------

    def _dispatch(self, x, n_real: int, clean=None, defenses=None):
        """Launch the clean forward and EVERY certifier before materializing
        any result, so the programs overlap on device instead of serializing
        on per-radius host transfers. Exhaustive mode is dispatch-only (the
        syncs all happen later, in `marshal_response`); a pruned schedule
        launches phase 1 for every radius first, then lets each certifier's
        `schedule()` read its tiny `[B, 36]` first-round table (the pruned
        path's one designed sync, inside defense.py) and dispatch only the
        phase-2 work the batch's verdicts actually need — on benign,
        first-round-unanimous traffic that is the 630-pair audit alone, and
        under "consensus" nothing at all. `clean`/`defenses` select a
        replica's bank; default is replica 0's (the service's own)."""
        clean = self._clean if clean is None else clean
        defenses = self.defenses if defenses is None else defenses
        logits = clean(self.params, x)
        if self.prune == "off":
            per_defense = [d.predict_tables(self.params, x, self.num_classes)
                           for d in defenses]
            return logits, per_defense
        pendings = [d.begin_pruned(self.params, x, self.num_classes,
                                   n=n_real, bucket_sizes=self.bucket_sizes)
                    for d in defenses]
        for p in pendings:
            p.schedule()
        return logits, pendings

    def _note_duplicate(self, replica=None) -> None:
        """A resolver lost the claim race: the request was already answered
        elsewhere (failover re-dispatch landed first, or vice versa). The
        late answer is shed, counted, and never delivered."""
        self.metrics.counter("serve_duplicates_shed_total").inc()
        if replica is not None:
            self.metrics.counter("serve_replica_duplicates_shed_total").inc(
                replica=str(replica.slot))

    def _fail_batch(self, batch: List[PendingRequest], e: Exception,
                    replica=None) -> None:
        """A failed batch must resolve its unanswered waiters (the worker
        stays serving for ordinary errors — the pool escalates only the
        structural ones); events and counts land before the waiters wake,
        as on the success path. Requests already answered (shed as expired
        before dispatch, or won by a failover resolver) are skipped via
        the claim arbiter, never re-resolved or re-counted."""
        now = self._clock()
        pending = [r for r in batch if r.claim()]
        for r in pending:
            observe.record_event(
                "serve.request", status="internal_error",
                latency_s=round(now - r.enqueued, 6), trace=r.trace_id)
        self._m_requests.inc(len(pending), status="internal_error")
        observe.record_event(
            "serve.batch_error", error=repr(e), images=len(pending),
            replica=replica.slot if replica is not None else 0)
        for r in pending:
            r.deliver(ServeError(reason=repr(e),
                                 latency_ms=(now - r.enqueued) * 1e3,
                                 status="internal_error"))

    def _run_batch(self, reqs: List[PendingRequest], replica=None) -> None:
        clean = self._clean if replica is None else replica.clean
        defenses = self.defenses if replica is None else replica.defenses
        slot = 0 if replica is None else replica.slot
        # shed already-expired requests BEFORE dispatch: under sustained
        # overload the deadline contract forces their answers to be
        # withheld anyway, so spending a certify sweep on them would drive
        # goodput to zero exactly when capacity matters most
        now = self._clock()
        live = [r for r in reqs if now <= r.deadline]
        expired = [r for r in reqs if now > r.deadline]
        if expired:
            won = [r for r in expired if r.claim()]
            self._note_duplicates(len(expired) - len(won), replica)
            for r in won:
                observe.record_event("serve.request",
                                     status="deadline_exceeded",
                                     latency_s=round(now - r.enqueued, 6),
                                     shed=True, trace=r.trace_id)
            self._m_requests.inc(len(won), status="deadline_exceeded")
            for r in won:
                r.deliver(DeadlineExceeded(
                    latency_ms=(now - r.enqueued) * 1e3,
                    deadline_ms=r.budget_s() * 1e3))
        if not live:
            return
        reqs = live
        n = len(reqs)
        bucket = data_lib.bucket_batch(n, self.bucket_sizes)
        with observe.span("serve.batch", bucket=int(bucket), images=n,
                          replica=slot,
                          queue_depth=self.batcher.qsize(),
                          compute_dtype=(
                              "bf16"
                              if self.defense_cfg.compute_dtype == "bfloat16"
                              else "f32"),
                          traces=[r.trace_id for r in reqs]) as sp:
            # pad on the host so exactly ONE host->device transfer
            # happens per batch, always bucket-shaped
            imgs = data_lib.pad_to_bucket(np.stack([r.image for r in reqs]),
                                          bucket)
            logits, per_defense = self._dispatch(jax.device_put(imgs), n,
                                                 clean=clean,
                                                 defenses=defenses)
            responses = marshal_response(reqs, logits, per_defense,
                                         self.ratios, bucket,
                                         clock=self._clock)
            # stats and telemetry land BEFORE the waiters wake: a client
            # that returns from predict() must observe its own completion
            # in stats(). claim() first: a request the failover path
            # already answered is a shed duplicate, not a second answer.
            ok = 0
            deliver: List[tuple] = []
            exhaustive = sum(d.num_forwards_exhaustive
                             for d in defenses)
            for r, resp in zip(reqs, responses):
                if not r.claim():
                    self._note_duplicate(replica)
                    continue
                deliver.append((r, resp))
                status = resp.status
                lat = getattr(resp, "latency_ms", None)
                fwd = getattr(resp, "certify_forwards", None)
                fe = getattr(resp, "certify_forward_equivalents", None)
                extra = {}
                if status == "ok" and fwd is not None:
                    # per-request certify cost, for the report CLI's serve
                    # prune-rate column (exhaustive = the bank's fixed
                    # 666-per-radius forward count)
                    extra = {"forwards": int(fwd),
                             "forwards_exhaustive": exhaustive}
                    if fe is not None:
                        extra["forward_equivalents"] = round(float(fe), 2)
                observe.record_event("serve.request", status=status,
                                     latency_s=round((lat or 0.0) / 1e3, 6),
                                     bucket=int(bucket), trace=r.trace_id,
                                     **extra)
                if status == "ok":
                    ok += 1
                    self._m_requests.inc(status="ok")
                    if fwd is not None:
                        self._m_certify_fwd.inc(int(fwd))
                        self._m_certify_exh.inc(exhaustive)
                    if fe is not None:
                        self._m_certify_fe.inc(float(fe))
                    self._m_latency.observe(lat)
                else:
                    # deadline_exceeded / error / internal_error: count
                    # under the SAME status string the client response and
                    # the event carry, so all three surfaces reconcile
                    self._m_requests.inc(status=status)
            self._m_batches.inc()
            self._m_batch_images.inc(n)
            self._m_batch_slots.inc(bucket)
            if replica is not None:
                rl = str(replica.slot)
                self.metrics.counter("serve_replica_batches_total").inc(
                    replica=rl)
                self.metrics.counter("serve_replica_batch_images_total").inc(
                    n, replica=rl)
                self.metrics.counter("serve_replica_batch_slots_total").inc(
                    bucket, replica=rl)
                self.metrics.counter("serve_replica_completed_total").inc(
                    ok, replica=rl)
                for _r, resp in deliver:
                    if resp.status == "ok":
                        self._m_replica_latency.observe(resp.latency_ms,
                                                        replica=rl)
            sp["ok"] = ok
            for r, resp in deliver:
                r.deliver(resp)

    def _note_duplicates(self, count: int, replica=None) -> None:
        for _ in range(count):
            self._note_duplicate(replica)
