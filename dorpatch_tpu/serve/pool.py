"""Supervised replica pool: N worker loops, health, failover, restarts.

The single `serve-worker` thread of the original service becomes a pool of
replicas sharing ONE micro-batcher queue:

- **Replica** — one worker loop plus everything it exclusively owns: a
  fresh `apply_fn` closure's jitted program bank (jit caches are per
  wrapper object, so a fresh closure per replica keeps trace caches — and
  therefore warmup, AOT boot, and the zero-recompile contract — fully
  independent), a manual-beat heartbeat (`observe.Heartbeat` semantics:
  what proves liveness is the *beat*, not the thread object), and the
  in-flight batch it is currently answering.
- **Supervisor** — a thread that classifies sick replicas by TYPE:
  `wedged` (thread alive, beats stale — a stuck device call),
  `raised` (thread died on an escaped exception), `recompile_budget`
  (the PR 2 watchdog tripped: the replica is structurally retracing and
  must be rebuilt, ideally from the AOT store). A sick replica's in-flight
  requests are re-dispatched to the healthy replicas at most once each,
  inside their original deadlines — `PendingRequest.claim()` makes a late
  answer from the sick replica a shed duplicate, never a double answer.
- **Restarts** — quarantined replicas come back through the PR 10 AOT warm
  boot (zero traces under the armed watchdog when the store has the
  programs) after the shared `backoff.retry_delay` wait; a replica that
  exhausts `max_restarts` retires and the pool degrades gracefully:
  admission (`max_queue_depth`) shrinks with the healthy fraction so
  clients see `Overloaded` sooner, and the LAST retirement drains the
  queue with typed errors — the service never hangs, it only shrinks.

Replica state machine: healthy -> sick -> quarantined -> (restarting ->
healthy)* -> retired. Telemetry: `serve.replica.{start,sick,quarantine,
restart,retire}` events (rendered by `observe.report` as `-- replicas --`),
per-replica occupancy/latency/trace counts in `/stats`.
"""

from __future__ import annotations

import os
import threading
from typing import Any, List, Optional

from dorpatch_tpu import observe
from dorpatch_tpu.backoff import retry_delay
from dorpatch_tpu.serve.types import DeadlineExceeded, ServeError

# replica lifecycle states (see module docstring)
STATES = ("healthy", "sick", "quarantined", "restarting", "retired")


class ReplicaHeartbeat(observe.Heartbeat):
    """Manual-beat heartbeat for one replica worker thread.

    No daemon thread: the worker loop itself beats at batch boundaries and
    idle wakeups, so a wedged dispatch stops the beats — exactly the
    missed-beat staleness signal `farm/queue.py` uses for lease expiry.
    Keeps the last beat on the service's monotonic clock for the
    supervisor's cheap in-process staleness reads; the optional JSONL file
    (`heartbeat_r<slot>.jsonl` under the results dir) is the post-mortem
    artifact, same format as every other heartbeat in the system."""

    def __init__(self, path: Optional[str], slot: int, clock):
        super().__init__(path, interval=3600.0, process_index=slot)
        self._mono = clock
        self.last = clock()
        self.last_phase = "init"

    def mark(self, phase: str) -> None:
        if self._wedged:  # a wedged heartbeat freezes; the thread may live
            return
        self.last = self._mono()
        self.last_phase = phase
        self.beat(phase)

    def stale_s(self, now: float) -> float:
        return now - self.last


class Replica:
    """One worker loop's exclusive state. The lifecycle fields below carry
    `# guarded-by: self.lock` contracts (enforced by DP500): every mutation
    — worker batch bookkeeping AND the supervisor's state transitions —
    holds `lock`, so a `/stats` snapshot mid-transition reads a consistent
    (state, generation, restarts) triple instead of a torn one."""

    def __init__(self, slot: int, clean, defenses, heartbeat: ReplicaHeartbeat,
                 aot_stats: Optional[dict] = None):
        self.slot = int(slot)
        self.generation = 0  # guarded-by: self.lock
        self.state = "healthy"  # guarded-by: self.lock
        self.restarts = 0  # guarded-by: self.lock
        self.clean = clean
        self.defenses = defenses
        self.hb = heartbeat
        self.aot_stats = aot_stats
        self.thread: Optional[threading.Thread] = None
        self.lock = threading.Lock()
        self.inflight: List[Any] = []  # guarded-by: self.lock
        self.fail_kind: Optional[str] = None
        self.fail_error: Optional[str] = None
        self.restart_at: Optional[float] = None
        # per-replica accounting lives in the service's metric registry
        # (`serve_replica_*_total{replica=...}` series), not here: one
        # registry feeds /stats, /metrics, and the report CLI identically

    def begin_batch(self, reqs: List[Any]) -> None:
        with self.lock:
            self.inflight = list(reqs)

    def end_batch(self) -> None:
        with self.lock:
            self.inflight = []

    def take_inflight(self) -> List[Any]:
        with self.lock:
            reqs, self.inflight = self.inflight, []
            return reqs

    def thread_alive(self) -> bool:
        t = self.thread
        return t is not None and t.is_alive()


class ReplicaPool:
    """Owns the replicas, their worker threads, and the supervisor; the
    `CertifiedInferenceService` delegates dispatch/health/stats here and
    keeps the client API, program building, and telemetry contract."""

    def __init__(self, service, chaos=None):
        self.svc = service
        self.cfg = service.serve_cfg
        self.batcher = service.batcher
        self._clock = service._clock
        self._chaos = chaos
        self.replicas: List[Replica] = []  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self._base_depth = service.batcher.max_queue_depth
        # staleness threshold: a healthy batch must finish well inside the
        # request deadline (the batcher flushes at flush_fraction of it),
        # so a replica silent for a full deadline is stuck, not slow
        stale = float(getattr(self.cfg, "replica_stale_s", 0.0) or 0.0)
        self.stale_after_s = (stale if stale > 0.0
                              else max(self.cfg.deadline_ms / 1e3, 0.5))
        self.poll_s = max(0.05, self.stale_after_s / 4.0)

    # failover totals live in the service registry (single source of truth
    # for /stats, /metrics, and the report CLI); these properties keep the
    # pool's historical read surface
    @property
    def redispatched(self) -> int:
        return int(self.svc.metrics.value("serve_failover_redispatched_total"))

    @property
    def duplicates_shed(self) -> int:
        return int(self.svc.metrics.value("serve_duplicates_shed_total"))

    def _replica_event(self, event: str, r: Replica) -> None:
        """Lifecycle tally: `serve_replica_events_total{event,replica}` —
        the counted twin of the `serve.replica.*` event-log records."""
        self.svc.metrics.counter(
            "serve_replica_events_total",
            help="replica lifecycle transitions by kind",
        ).inc(event=event, replica=str(r.slot))

    # ---------------- lifecycle ----------------

    def _hb_path(self, slot: int) -> Optional[str]:
        if not self.svc.result_dir:
            return None
        return os.path.join(self.svc.result_dir, f"heartbeat_r{slot}.jsonl")

    def start(self) -> "ReplicaPool":
        n = max(1, int(getattr(self.cfg, "replicas", 1)))
        # replica 0 adopts the service's own bank — the one `start()`
        # already AOT-booted and warmed, and the one `trace_entrypoints`
        # / the baseline gate enumerate
        r0 = Replica(0, self.svc._clean, self.svc.defenses,
                     ReplicaHeartbeat(self._hb_path(0), 0, self._clock),
                     aot_stats=self.svc._aot_stats)
        # build the full roster locally, publish once under the lock: the
        # supervisor and /stats iterate a complete, never-mutated list
        replicas = [r0]
        for slot in range(1, n):
            clean, defenses, aot_stats = self.svc._build_bank(slot)
            replicas.append(
                Replica(slot, clean, defenses,
                        ReplicaHeartbeat(self._hb_path(slot), slot,
                                         self._clock),
                        aot_stats=aot_stats))
        with self._lock:
            self.replicas = replicas
        for r in self.replicas:
            self._launch(r)
            self._replica_event("start", r)
            observe.record_event("serve.replica.start", replica=r.slot,
                                 generation=r.generation,
                                 aot=bool(r.aot_stats))
        self._supervisor = threading.Thread(target=self._supervise,
                                            name="serve-supervisor",
                                            daemon=True)
        self._supervisor.start()
        return self

    def _launch(self, replica: Replica) -> None:
        replica.thread = threading.Thread(
            target=self._worker_main, args=(replica,),
            name=f"serve-worker-r{replica.slot}g{replica.generation}",
            daemon=True)
        replica.thread.start()

    def begin_stop(self) -> None:
        """Stop supervising BEFORE the batcher closes: draining workers
        exit their loops naturally and must not be classified as failures."""
        self._stop_evt.set()

    def stopping(self) -> bool:
        """True from `begin_stop()` on — the drain window where the HTTP
        frontend answers `/stats` and `/metrics` with a typed 503 instead
        of racing a half-stopped service."""
        return self._stop_evt.is_set()

    def join(self, timeout_s: float) -> bool:
        """Join the current-generation worker threads (abandoned wedged
        generations died to the supervisor long ago and are daemon
        threads); True when every live worker drained in time."""
        sup = self._supervisor
        if sup is not None:
            sup.join(timeout=5.0)
        deadline = self._clock() + max(timeout_s, 0.0)
        for r in self.replicas:
            t = r.thread
            if t is None or not t.is_alive():
                continue
            t.join(timeout=max(deadline - self._clock(), 0.0))
        return not any(r.thread_alive() for r in self.replicas)

    def still_draining(self) -> List[int]:
        return [r.slot for r in self.replicas if r.thread_alive()]

    # ---------------- health ----------------

    def worker_alive(self) -> bool:
        return any(r.thread_alive() for r in self.replicas)

    def healthy_count(self) -> int:
        return sum(1 for r in self.replicas
                   if r.state == "healthy" and r.thread_alive())

    def serving_possible(self) -> bool:
        """False only when NO replica could ever answer again: everything
        alive is gone and no restart is pending — the client's wait loop
        fails fast instead of sleeping out its deadline."""
        if self._stop_evt.is_set():
            return self.worker_alive()
        for r in self.replicas:
            if r.state == "healthy" and r.thread_alive():
                return True
            if r.state in ("sick", "restarting"):
                return True
            if r.state == "quarantined":
                return True
        return False

    # ---------------- worker ----------------

    def _worker_main(self, replica: Replica) -> None:
        gen = replica.generation
        try:
            self._worker_loop(replica, gen)
        except BaseException as e:  # thread is dying: record WHY for triage
            if replica.generation == gen:  # zombies don't smear the fresh one
                replica.fail_error = repr(e)
                replica.fail_kind = (
                    "recompile_budget"
                    if type(e).__name__ == "RecompileBudgetExceeded"
                    else "raised")

    def _worker_loop(self, replica: Replica, gen: int) -> None:
        while True:
            if replica.generation == gen:
                replica.hb.mark("idle")
            batch = self.batcher.next_batch(timeout=self.poll_s)
            if batch is None:
                return  # closed and drained
            if not batch:
                continue  # idle tick: beat and re-wait
            if replica.generation != gen or replica.state != "healthy":
                # a stale generation waking up, or a replica the supervisor
                # already declared sick, must not keep taking work — hand
                # the batch straight back to the healthy pool
                if not self.batcher.requeue(batch):
                    self._reject_all(batch, "replica quarantined")
                return
            replica.begin_batch(batch)
            replica.hb.mark("batch")
            if self._chaos is not None:
                # chaos sits OUTSIDE the per-batch guard: `raise_in_worker`
                # must escape and kill the thread, `wedge_dispatch` freezes
                # right here with the batch in-flight and unresolved
                self._chaos.on_serve_batch(replica.slot, replica.hb)
            try:
                self.svc._run_batch(batch, replica)
            except Exception as e:
                self.svc._fail_batch(batch, e, replica)
                if type(e).__name__ == "RecompileBudgetExceeded":
                    # a budget trip is structural (shape leak / lost AOT
                    # executables) — rebuilding the program bank is the
                    # remedy, so the replica dies for the supervisor to
                    # classify and restart rather than looping on it
                    raise
            finally:
                replica.end_batch()
            replica.hb.mark("idle")

    def _reject_all(self, reqs: List[Any], reason: str) -> None:
        now = self._clock()
        won = [r for r in reqs if r.claim()]
        for r in won:
            observe.record_event("serve.request", status="internal_error",
                                 latency_s=round(now - r.enqueued, 6),
                                 trace=r.trace_id)
        self.svc._m_requests.inc(len(won), status="internal_error")
        for r in won:
            r.deliver(ServeError(reason=reason,
                                 latency_ms=(now - r.enqueued) * 1e3,
                                 status="internal_error"))

    # ---------------- supervisor ----------------

    def _supervise(self) -> None:
        interval = max(0.05, self.stale_after_s / 5.0)
        while not self._stop_evt.wait(interval):
            now = self._clock()
            for r in self.replicas:
                try:
                    if r.state == "healthy":
                        self._check_replica(r, now)
                    elif (r.state == "quarantined"
                            and r.restart_at is not None
                            and now >= r.restart_at):
                        with r.lock:
                            r.state = "restarting"
                        threading.Thread(
                            target=self._restart, args=(r,),
                            name=f"serve-restart-r{r.slot}",
                            daemon=True).start()
                except Exception as e:
                    # the supervisor must never die to one replica's
                    # bookkeeping; telemetry the failure and keep watching
                    observe.record_event("serve.supervisor_error",
                                         replica=r.slot, error=repr(e))

    def _check_replica(self, r: Replica, now: float) -> None:
        if not r.thread_alive():
            kind = r.fail_kind or "raised"
            self._mark_sick(r, kind, now, error=r.fail_error)
        elif r.hb.stale_s(now) > self.stale_after_s:
            self._mark_sick(r, "wedged", now,
                            stale_s=round(r.hb.stale_s(now), 3))

    def _mark_sick(self, r: Replica, cause: str, now: float, **info) -> None:
        # the state transition and failover run to completion BEFORE any
        # telemetry: a throwing event sink must never strand a replica in
        # "sick" (a state this method owns) or lose its in-flight requests.
        # Each transition holds r.lock (the DP500 contract on Replica
        # state) in a short, non-nested scope — take_inflight() acquires
        # the same non-reentrant lock, so it must never run inside one
        with r.lock:
            r.state = "sick"
        self._replica_event("sick", r)
        inflight = r.take_inflight()
        self._failover(inflight, now)
        with r.lock:
            r.restarts += 1  # noqa: DP108 — control state, not a metric
        retire = r.restarts > int(getattr(self.cfg, "max_restarts", 0))
        delay = 0.0
        if not retire:
            delay = retry_delay(
                f"serve-r{r.slot}", r.restarts,
                base=float(getattr(self.cfg, "restart_backoff_base", 0.5)),
                cap=float(getattr(self.cfg, "restart_backoff_cap", 30.0)))
            with r.lock:
                r.restart_at = now + delay
                r.state = "quarantined"
        observe.record_event("serve.replica.sick", replica=r.slot,
                             generation=r.generation, cause=cause,
                             inflight=len(inflight), **info)
        if retire:
            self._retire(r)
            return
        self._replica_event("quarantine", r)
        observe.record_event("serve.replica.quarantine", replica=r.slot,
                             generation=r.generation, cause=cause,
                             restarts=r.restarts,
                             retry_in_s=round(delay, 3))

    def _failover(self, inflight: List[Any], now: float) -> None:
        """Re-dispatch a failed replica's unanswered in-flight requests to
        the healthy replicas: at most ONE re-enqueue per request, original
        deadline preserved (already-expired ones are shed typed right
        here). A request whose second replica also fails resolves as an
        internal error — never a third try, never a hang."""
        requeue: List[Any] = []
        for req in inflight:
            if req.done.is_set():
                continue
            if req.redispatched:
                if req.claim():
                    self.svc._m_requests.inc(status="internal_error")
                    observe.record_event(
                        "serve.request", status="internal_error",
                        latency_s=round(now - req.enqueued, 6),
                        redispatched=True, trace=req.trace_id)
                    req.deliver(ServeError(
                        reason="replica failed twice",
                        latency_ms=(now - req.enqueued) * 1e3,
                        status="internal_error"))
                continue
            if now > req.deadline:
                if req.claim():
                    self.svc._m_requests.inc(status="deadline_exceeded")
                    observe.record_event(
                        "serve.request", status="deadline_exceeded",
                        latency_s=round(now - req.enqueued, 6), shed=True,
                        trace=req.trace_id)
                    req.deliver(DeadlineExceeded(
                        latency_ms=(now - req.enqueued) * 1e3,
                        deadline_ms=req.budget_s() * 1e3))
                continue
            req.redispatched = True
            requeue.append(req)
        if requeue:
            self.svc.metrics.counter(
                "serve_failover_redispatched_total",
                help="in-flight requests re-enqueued after replica failure",
            ).inc(len(requeue))
            if not self.batcher.requeue(requeue):
                self._reject_all(requeue, "service stopping")

    def _retire(self, r: Replica) -> None:
        with r.lock:
            r.state = "retired"
            r.restart_at = None
        healthy = max(self.healthy_count(), 0)
        total = len(self.replicas)
        retired = sum(1 for x in self.replicas if x.state == "retired")
        live = total - retired
        new_depth = (max(1, self._base_depth * live // total)
                     if live else 0)
        self.batcher.set_max_queue_depth(new_depth)
        self._replica_event("retire", r)
        observe.record_event("serve.replica.retire", replica=r.slot,
                             generation=r.generation, restarts=r.restarts,
                             healthy_left=healthy,
                             max_queue_depth=new_depth)
        if live == 0:
            # terminal degradation: nothing will ever serve again — answer
            # every queued waiter with a typed error instead of hanging
            self._reject_all(self.batcher.drain(), "no healthy replicas")

    def _restart(self, r: Replica) -> None:
        t0 = self._clock()
        try:
            clean, defenses, aot_stats = self.svc._build_bank(r.slot)
        except Exception as e:
            observe.record_event("serve.replica.quarantine", replica=r.slot,
                                 generation=r.generation,
                                 cause="restart_failed", error=repr(e),
                                 restarts=r.restarts)
            self._replica_event("quarantine", r)
            with r.lock:
                r.restarts += 1  # noqa: DP108 — control state, not a metric
            if r.restarts > int(getattr(self.cfg, "max_restarts", 0)):
                self._retire(r)
            else:
                delay = retry_delay(
                    f"serve-r{r.slot}", r.restarts,
                    base=float(getattr(self.cfg, "restart_backoff_base",
                                       0.5)),
                    cap=float(getattr(self.cfg, "restart_backoff_cap",
                                      30.0)))
                restart_at = self._clock() + delay
                with r.lock:
                    r.restart_at = restart_at
                    r.state = "quarantined"
            return
        # the fresh heartbeat opens its JSONL file: build it BEFORE taking
        # the lock so the hold stays a handful of pure assignments
        hb = ReplicaHeartbeat(self._hb_path(r.slot), r.slot, self._clock)
        with r.lock:
            r.generation += 1  # noqa: DP108 — control state, not a metric
            r.clean, r.defenses = clean, defenses
            r.aot_stats = aot_stats
            r.hb = hb
            r.fail_kind = r.fail_error = None
            r.state = "healthy"
        if r.slot == 0:
            # replica 0's bank IS the service's bank: trace_entrypoints,
            # trace_counts, and the defenses attribute must reflect the
            # programs that are actually serving
            self.svc._clean, self.svc.defenses = clean, defenses
        self._launch(r)
        self._replica_event("restart", r)
        observe.record_event(
            "serve.replica.restart", replica=r.slot,
            generation=r.generation, restarts=r.restarts,
            dur_s=round(self._clock() - t0, 6),
            aot_hits=(aot_stats or {}).get("hits"),
            aot_misses=(aot_stats or {}).get("misses"),
            trace_counts=sum(
                self.svc._bank_trace_counts(clean, defenses).values()))

    # ---------------- stats ----------------

    def snapshot(self) -> List[dict]:
        now = self._clock()
        m = self.svc.metrics
        out = []
        for r in self.replicas:
            rl = str(r.slot)

            def pct(q, rl=rl):
                v = m.percentile("serve_replica_latency_ms", q, replica=rl)
                return None if v is None else round(v, 3)

            images = m.value("serve_replica_batch_images_total", replica=rl)
            slots = m.value("serve_replica_batch_slots_total", replica=rl)
            # read the guarded lifecycle triple (and the hb reference)
            # under the replica lock: a supervisor transition mid-snapshot
            # must not produce a torn (state, generation, restarts) row
            with r.lock:
                state, generation = r.state, r.generation
                restarts, hb = r.restarts, r.hb
            out.append({
                "replica": r.slot,
                "state": state,
                "generation": generation,
                "restarts": restarts,
                "thread_alive": r.thread_alive(),
                "last_phase": hb.last_phase,
                "stale_s": round(hb.stale_s(now), 3),
                "batches": int(m.value("serve_replica_batches_total",
                                       replica=rl)),
                "completed": int(m.value("serve_replica_completed_total",
                                         replica=rl)),
                "duplicates_shed": int(m.value(
                    "serve_replica_duplicates_shed_total", replica=rl)),
                "occupancy": (round(images / slots, 4) if slots else 0.0),
                "latency_ms": {"p50": pct(0.50), "p95": pct(0.95)},
                "trace_counts": sum(self.svc._bank_trace_counts(
                    r.clean, r.defenses).values()),
            })
        return out
