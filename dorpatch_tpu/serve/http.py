"""Thin HTTP front-end on stdlib `http.server` (JSON in/out).

Endpoints:

- `POST /predict` — body `{"image": [[[...]]], "deadline_ms": 250}` (HWC
  float nested lists in [0, 1]; `deadline_ms` optional). Answers the typed
  response as JSON with the status-code mapping in `types.HTTP_STATUS`
  (200 ok / 503 overloaded / 504 deadline_exceeded / 400 error).
- `GET /healthz` — liveness + warmup state.
- `GET /stats`   — the service's live counters, latency percentiles,
  queue depth, and per-program trace counts.
- `GET /robustness` — the recert verdict snapshot loaded at boot
  (gate mode, per-cell status, generation, worst margin); status 200
  when the verdict is `ok`, 503 when failing/stale/absent so a canary
  gate can probe it like a health check.

One handler thread per connection (`ThreadingHTTPServer`); every thread
funnels into the same `service.predict`, so the micro-batcher — not the
socket layer — decides batching and backpressure. Tests and the load
generator can skip sockets entirely and call `service.predict` directly.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dorpatch_tpu import observe
from dorpatch_tpu.serve.types import HTTP_STATUS


class _Handler(BaseHTTPRequestHandler):
    # set per-server via the factory in HttpFrontend
    service = None

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        if self.path == "/healthz":
            h = self.service.healthz()
            self._send_json(200 if h["status"] == "ok" else 503, h)
        elif self.path == "/stats":
            self._send_json(200, self.service.stats())
        elif self.path == "/robustness":
            r = self.service.robustness()
            # canary-probe contract: 200 only on a clean verdict, 503 on
            # failing/stale/absent/unconfigured — a deploy gate can treat
            # this exactly like /healthz
            self._send_json(200 if r.get("status") == "ok" else 503, r)
        else:
            self._send_json(404, {"status": "error",
                                  "reason": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        if self.path != "/predict":
            self._send_json(404, {"status": "error",
                                  "reason": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            image = payload["image"]
            deadline_ms = payload.get("deadline_ms")
            if deadline_ms is not None \
                    and not isinstance(deadline_ms, (int, float)):
                raise ValueError("deadline_ms must be a number")
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"status": "error",
                                  "reason": f"bad request body: {e!r}"})
            return
        resp = self.service.predict(image, deadline_ms=deadline_ms)
        self._send_json(HTTP_STATUS.get(resp.status, 500), resp.to_dict())

    def log_message(self, fmt: str, *args) -> None:
        # route through observe (rule DP101: no bare prints); request-level
        # telemetry already lands in events.jsonl, so keep this quiet
        pass


class HttpFrontend:
    """Owns the listening socket + serve_forever thread; `port` reports the
    bound port (pass 0 to bind an ephemeral one for tests)."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundHandler", (_Handler,), {"service": service})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serve-http", daemon=True)
        self._thread.start()
        observe.log(f"serve: http front-end on {self.host}:{self.port} "
                    f"(/predict /healthz /stats /robustness)")
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "HttpFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
