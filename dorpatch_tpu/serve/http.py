"""Thin HTTP front-end on stdlib `http.server` (JSON in/out).

Endpoints:

- `POST /predict` — body `{"image": [[[...]]], "deadline_ms": 250}` (HWC
  float nested lists in [0, 1]; `deadline_ms` optional). Answers the typed
  response as JSON with the status-code mapping in `types.HTTP_STATUS`
  (200 ok / 503 overloaded / 504 deadline_exceeded / 400 error). A caller
  may pin the request's correlation id via the `X-Trace-Id` header (or a
  `trace_id` body field); otherwise one is minted here at ingress. Either
  way the id comes back in the JSON payload and the `X-Trace-Id` response
  header, and every telemetry record the request touches carries it.
- `GET /healthz` — liveness + warmup state.
- `GET /stats`   — the service's live counters, latency percentiles,
  queue depth, and per-program trace counts.
- `GET /metrics` — Prometheus text exposition of the service's metric
  registry (the same registry `/stats` summarizes — one source of truth).
- `GET /robustness` — the recert verdict snapshot loaded at boot
  (gate mode, per-cell status, generation, worst margin); status 200
  when the verdict is `ok`, 503 when failing/stale/absent so a canary
  gate can probe it like a health check.
- `POST /profile` — on-demand bounded `jax.profiler` capture into the run
  dir (body `{"duration_ms": 500}` optional); 200 with the trace dir on
  success, 409 while another capture is in flight, 400 when the service
  has no results dir to write into. Serving keeps answering throughout.

One handler thread per connection (`ThreadingHTTPServer`); every thread
funnels into the same `service.predict`, so the micro-batcher — not the
socket layer — decides batching and backpressure. Tests and the load
generator can skip sockets entirely and call `service.predict` directly.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dorpatch_tpu import observe
from dorpatch_tpu.serve.types import HTTP_STATUS


class _Handler(BaseHTTPRequestHandler):
    # set per-server via the factory in HttpFrontend
    service = None

    def _send_json(self, code: int, payload: dict, headers=()) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in headers:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _stopping(self) -> bool:
        """True during the service's stop() drain window: snapshot paths
        race replica teardown there (the pool reference can go away mid-
        handler), so observability endpoints answer a typed 503 instead of
        a 500 — or a connection left hanging on a torn snapshot."""
        stopping = getattr(self.service, "stopping", None)
        return bool(stopping()) if callable(stopping) else False

    @staticmethod
    def _stopping_body() -> dict:
        return {"status": "stopping",
                "reason": "service is draining (stop() in progress)"}

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        if self.path == "/healthz":
            if self._stopping():
                # the drain window answers a typed 503 here too: a fleet
                # gateway's health probe must see a clean "stopping" signal
                # (and start draining the backend) instead of racing the
                # pool teardown into a torn snapshot
                self._send_json(503, self._stopping_body())
            else:
                h = self.service.healthz()
                self._send_json(200 if h["status"] == "ok" else 503, h)
        elif self.path == "/stats":
            if self._stopping():
                self._send_json(503, self._stopping_body())
            else:
                self._send_json(200, self.service.stats())
        elif self.path == "/metrics":
            if self._stopping():
                # typed refusal for the scrape too: Prometheus records the
                # 503 as a failed scrape instead of a half-torn exposition
                self._send_text(503, "# service stopping (drain window)\n")
            else:
                self._send_text(200, self.service.metrics.render_text())
        elif self.path == "/robustness":
            r = self.service.robustness()
            # canary-probe contract: 200 only on a clean verdict, 503 on
            # failing/stale/absent/unconfigured — a deploy gate can treat
            # this exactly like /healthz
            self._send_json(200 if r.get("status") == "ok" else 503, r)
        else:
            self._send_json(404, {"status": "error",
                                  "reason": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        if self.path == "/profile":
            self._do_profile()
            return
        if self.path != "/predict":
            self._send_json(404, {"status": "error",
                                  "reason": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            image = payload["image"]
            deadline_ms = payload.get("deadline_ms")
            if deadline_ms is not None \
                    and not isinstance(deadline_ms, (int, float)):
                raise ValueError("deadline_ms must be a number")
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(400, {"status": "error",
                                  "reason": f"bad request body: {e!r}"})
            return
        # correlation id: caller-pinned (header wins over body field) or
        # minted HERE — ingress is where a trace id is born, so a socket
        # client can join its own logs against the server's telemetry
        trace_id = str(self.headers.get("X-Trace-Id", "")
                       or payload.get("trace_id", "")
                       or observe.new_trace_id())
        resp = self.service.predict(image, deadline_ms=deadline_ms,
                                    trace_id=trace_id)
        body = resp.to_dict()
        body["trace_id"] = trace_id
        self._send_json(HTTP_STATUS.get(resp.status, 500), body,
                        headers=(("X-Trace-Id", trace_id),))

    def _do_profile(self) -> None:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            duration_ms = float(payload.get("duration_ms", 500.0)) \
                if isinstance(payload, dict) else 500.0
        except (ValueError, TypeError) as e:
            self._send_json(400, {"status": "error",
                                  "reason": f"bad request body: {e!r}"})
            return
        if not getattr(self.service, "result_dir", None):
            self._send_json(400, {
                "status": "error",
                "reason": "service has no results dir to capture into"})
            return
        trace_dir = self.service.capture_profile(duration_ms=duration_ms)
        if trace_dir is None:
            # the profiler is a process-global toggle: one at a time
            self._send_json(409, {"status": "busy",
                                  "reason": "a capture is already running"})
            return
        self._send_json(200, {"status": "ok", "dir": trace_dir,
                              "duration_ms": duration_ms})

    def log_message(self, fmt: str, *args) -> None:
        # route through observe (rule DP101: no bare prints); request-level
        # telemetry already lands in events.jsonl, so keep this quiet
        pass


class HttpFrontend:
    """Owns the listening socket + serve_forever thread; `port` reports the
    bound port (pass 0 to bind an ephemeral one for tests)."""

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundHandler", (_Handler,), {"service": service})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpFrontend":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serve-http", daemon=True)
        self._thread.start()
        observe.log(f"serve: http front-end on {self.host}:{self.port} "
                    f"(/predict /profile /healthz /stats /metrics "
                    f"/robustness)")
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "HttpFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
