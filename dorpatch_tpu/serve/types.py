"""Typed request/response messages of the certified-inference service.

Every answer the service gives is one of these frozen dataclasses — the
Python client returns them directly, the HTTP front-end maps them onto
status codes + JSON via `to_dict`. A rejected request is DATA
(`Overloaded`), not an exception: backpressure is part of the serving
contract (bounded queue, typed reject) rather than an error path.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class RadiusVerdict:
    """One PatchCleanser certifier's answer (one mask family / patch ratio)."""

    ratio: float
    prediction: int
    certified: bool


@dataclasses.dataclass(frozen=True)
class PredictResult:
    """Successful certified prediction for one image.

    `prediction`/`certified` are the headline answer: the smallest-radius
    defense's double-masking prediction, certified iff EVERY radius in the
    bank certifies (the conservative join the pipeline's certified-accuracy
    metric uses). `verdicts` carries the full per-radius breakdown,
    `clean_prediction` the undefended model argmax."""

    status = "ok"
    prediction: int
    certified: bool
    clean_prediction: int
    verdicts: Tuple[RadiusVerdict, ...]
    latency_ms: float
    bucket: int          # padded batch size the request rode in
    batch_images: int    # real (unpadded) images in that batch
    certify_forwards: Optional[int] = None
    # ^ masked-table entries this image's certification evaluated across
    #   the whole defense bank (the pruned scheduler's per-image cost; None
    #   only for responses predating forward accounting)
    certify_forward_equivalents: Optional[float] = None
    # ^ the same cost in fractional full-forward units: incremental
    #   entries (token-pruned ViT / stem-folded conv) credited at their
    #   true fraction of a forward — == certify_forwards when the
    #   incremental path is off

    def to_dict(self) -> dict:
        out = {
            "status": self.status,
            "prediction": self.prediction,
            "certified": self.certified,
            "clean_prediction": self.clean_prediction,
            "verdicts": [dataclasses.asdict(v) for v in self.verdicts],
            "latency_ms": round(self.latency_ms, 3),
            "bucket": self.bucket,
            "batch_images": self.batch_images,
        }
        if self.certify_forwards is not None:
            out["certify_forwards"] = self.certify_forwards
        if self.certify_forward_equivalents is not None:
            out["certify_forward_equivalents"] = round(
                self.certify_forward_equivalents, 2)
        return out


@dataclasses.dataclass(frozen=True)
class Overloaded:
    """Typed backpressure reject: the bounded queue is full. Clients should
    back off and retry; nothing was enqueued."""

    status = "overloaded"
    queue_depth: int
    limit: int

    def to_dict(self) -> dict:
        return {"status": self.status, "queue_depth": self.queue_depth,
                "limit": self.limit}


@dataclasses.dataclass(frozen=True)
class DeadlineExceeded:
    """The request's latency budget elapsed before its batch finished; the
    (stale) result is withheld so callers never act on an expired answer."""

    status = "deadline_exceeded"
    latency_ms: float
    deadline_ms: float

    def to_dict(self) -> dict:
        return {"status": self.status,
                "latency_ms": round(self.latency_ms, 3),
                "deadline_ms": round(self.deadline_ms, 3)}


@dataclasses.dataclass(frozen=True)
class ServeError:
    """Malformed input (`status="error"` -> 400) or a server-side failure
    (`status="internal_error"` -> 500, so clients and load balancers retry
    and alert on the right side of the contract)."""

    reason: str
    latency_ms: Optional[float] = None
    status: str = "error"

    def to_dict(self) -> dict:
        out = {"status": self.status, "reason": self.reason}
        if self.latency_ms is not None:
            out["latency_ms"] = round(self.latency_ms, 3)
        return out


#: HTTP status code per response type (the front-end's mapping).
HTTP_STATUS = {
    "ok": 200,
    "overloaded": 503,
    "deadline_exceeded": 504,
    "error": 400,
    "internal_error": 500,
}
