"""Fleet-level aggregation across a farm directory's per-job telemetry.

Joins every job's `job.json` (state machine, attempts, failures) with its
results dir's `run.json` manifest (attempt chain), `events.jsonl` (block
timing), and `rows.jsonl` (the actual sweep results) into one summary dict,
rendered by `observe.report` (a farm dir handed to the report CLI is
auto-detected via `farm.json`) and `python -m dorpatch_tpu.farm report`.

Wasted-vs-useful accounting: each `block` event carries its (stage, step)
coordinate. A coordinate executed once is useful work; re-executions of a
coordinate already seen for that job are the work a crash/retry actually
repeated. Crash-resume from a block checkpoint re-runs at most the partial
block after the last snapshot, so its wasted time is near zero; a
from-scratch retry re-runs everything, all of it counted wasted — the
metric measures exactly what checkpointing buys.

Host-only: reads files, never touches a jax backend.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from dorpatch_tpu.checkpoint import load_json
from dorpatch_tpu.farm.queue import FARM_NAME, JobQueue
from dorpatch_tpu.observe.heartbeat import heartbeat_filename, last_beat

ROW_KEYS = ("patch_budget", "density", "structured",
            "robust_accuracy", "certified_asr_pc")


def is_farm_dir(path: str) -> bool:
    return os.path.exists(os.path.join(path, FARM_NAME))


def _read_jsonl(path: str, stats: Optional[Dict[str, int]] = None
                ) -> List[dict]:
    out: List[dict] = []
    try:
        with open(path, errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    if stats is not None:
                        stats["torn"] = stats.get("torn", 0) + 1
                    continue
    except OSError:
        pass
    return out


def read_result_rows(result_dir: str,
                     stats: Optional[Dict[str, int]] = None) -> List[dict]:
    """The rows a sweep actually recorded, hardened for live readers: the
    recert scheduler and this fleet report read `rows.jsonl` while workers
    append to it, so a torn final line, a half-flushed fragment, or a
    parseable-but-non-dict JSON value must read as a missing cell (a
    hole), never raise. `stats['torn']` counts what was skipped."""
    rows = _read_jsonl(os.path.join(result_dir, "rows.jsonl"), stats=stats)
    good = [r for r in rows if isinstance(r, dict)]
    if stats is not None and len(good) != len(rows):
        stats["torn"] = stats.get("torn", 0) + (len(rows) - len(good))
    return good


def _job_step_time(result_dir: str) -> Dict[str, float]:
    """Useful vs re-executed block seconds for one job, across all its
    attempts (events.jsonl is append-mode, so file order is chronological
    across attempts)."""
    useful = wasted = 0.0
    reexecuted = 0
    seen = set()
    for record in _read_jsonl(os.path.join(result_dir, "events.jsonl")):
        if record.get("kind") != "block":
            continue
        coord = (record.get("stage"), record.get("step"))
        dur = float(record.get("dur_s", 0.0))
        if coord in seen:
            wasted += dur
            reexecuted += 1
        else:
            seen.add(coord)
            useful += dur
    return {"useful_s": useful, "wasted_s": wasted,
            "reexecuted_blocks": reexecuted}


def summarize_fleet(farm_dir: str) -> Optional[dict]:
    """The whole farm as one dict; None when `farm_dir` is not a farm."""
    if not is_farm_dir(farm_dir):
        return None
    jq = JobQueue(farm_dir)
    farm = load_json(os.path.join(farm_dir, FARM_NAME), {})
    jobs: List[dict] = []
    points: List[dict] = []
    attempts_histogram: Dict[str, int] = {}
    failures_by_kind: Dict[str, int] = {}
    quarantined: List[dict] = []
    retries = reclaims = 0
    useful_s = wasted_s = 0.0
    reexecuted_blocks = 0
    for job_id in jq.job_ids():
        job = jq.read_job(job_id)
        if job is None:
            jobs.append({"id": job_id, "state": "unreadable"})
            continue
        attempts = int(job.get("attempts", 0))
        attempts_histogram[str(attempts)] = (
            attempts_histogram.get(str(attempts), 0) + 1)
        retries += max(0, attempts - 1)
        reclaims += int(job.get("reclaims", 0))
        for failure in job.get("failures", []):
            kind = failure.get("kind", "unknown")
            failures_by_kind[kind] = failures_by_kind.get(kind, 0) + 1
        result_dir = os.path.join(jq.job_dir(job_id), "results")
        manifest = load_json(os.path.join(result_dir, "run.json"))
        attempt_chain = []
        if manifest:
            attempt_chain = ([manifest.get("run_id", "")]
                             + list(manifest.get("previous_run_ids", [])))
        step_time = _job_step_time(result_dir)
        useful_s += step_time["useful_s"]
        wasted_s += step_time["wasted_s"]
        reexecuted_blocks += step_time["reexecuted_blocks"]
        row_stats: Dict[str, int] = {}
        rows = read_result_rows(result_dir, stats=row_stats)
        for row in rows:
            point = {"job": job_id}
            point.update({k: row[k] for k in ROW_KEYS if k in row})
            if "resumed_from_iteration" in row:
                point["resumed_from_iteration"] = row["resumed_from_iteration"]
            points.append(point)
        if job.get("state") == "quarantined" and job.get("failures"):
            last = job["failures"][-1]
            quarantined.append({"id": job_id,
                               "kind": last.get("kind", "unknown"),
                               "error": last.get("error", "")})
        jobs.append({
            "id": job_id,
            "state": ("failed_exhausted"
                      if job.get("state") == "failed" and job.get("exhausted")
                      else job.get("state", "")),
            "attempts": attempts,
            "reclaims": int(job.get("reclaims", 0)),
            "run_ids": attempt_chain,
            "rows": len(rows),
            "torn_rows": row_stats.get("torn", 0),
            "resumed_points": sum(
                1 for r in rows if "resumed_from_iteration" in r),
            **step_time,
        })
    # per-worker AOT warm-boot accounting (workers/<id>/aot.json, written
    # by FarmWorker.run when booting against a shared executable store),
    # live job counters from the newest heartbeat beat (present while the
    # worker is still running — the beats carry them), and the final
    # metric-registry snapshot (workers/<id>/metrics.json)
    aot_by_worker: Dict[str, dict] = {}
    workers: Dict[str, dict] = {}
    metrics_by_worker: Dict[str, dict] = {}
    workers_dir = os.path.join(farm_dir, "workers")
    if os.path.isdir(workers_dir):
        for wid in sorted(os.listdir(workers_dir)):
            wdir = os.path.join(workers_dir, wid)
            rec = load_json(os.path.join(wdir, "aot.json"))
            if isinstance(rec, dict):
                aot_by_worker[wid] = {
                    "hits": int(rec.get("hits", 0)),
                    "misses": int(rec.get("misses", 0)),
                    "load_s": float(rec.get("load_s", 0.0)),
                }
            beat = last_beat(os.path.join(wdir, heartbeat_filename(0)))
            if beat is not None:
                workers[wid] = {
                    k: beat[k] for k in (
                        "phase", "seq", "ts", "jobs_done", "jobs_failed",
                        "jobs_quarantined", "jobs_abandoned",
                        "jobs_claimed", "jobs_reclaimed") if k in beat}
            snap = load_json(os.path.join(wdir, "metrics.json"))
            if isinstance(snap, dict):
                totals = {}
                for name, m in sorted(
                        (snap.get("metrics") or {}).items()):
                    if m.get("type") != "counter":
                        continue
                    totals[name] = sum(
                        float(s.get("value", 0.0))
                        for s in m.get("series", []))
                metrics_by_worker[wid] = totals
    return {
        "farm_dir": os.path.abspath(farm_dir),
        "spec_jobs": int(farm.get("jobs", 0)),
        "counts": jq.counts(),
        "attempts_histogram": dict(sorted(attempts_histogram.items())),
        "retries": retries,
        "reclaims": reclaims,
        "failures_by_kind": dict(sorted(failures_by_kind.items())),
        "quarantined": quarantined,
        "step_time": {"useful_s": round(useful_s, 3),
                      "wasted_s": round(wasted_s, 3),
                      "reexecuted_blocks": reexecuted_blocks},
        "aot_by_worker": aot_by_worker,
        "workers": workers,
        "metrics_by_worker": metrics_by_worker,
        "points": points,
        "jobs": jobs,
    }


def format_fleet_report(s: dict) -> str:
    """Human rendering of a `summarize_fleet()` dict, in the same visual
    dialect as `observe.report.format_report`."""
    lines: List[str] = []
    add = lines.append
    add("= DorPatch attack-sweep farm report =")
    add(f"farm dir: {s['farm_dir']}")
    c = s["counts"]
    add("-- farm --")
    add(f"  jobs: {c['total']} total — {c['done']} done, "
        f"{c['quarantined']} quarantined, "
        f"{c['failed_retryable']} retryable, "
        f"{c['failed_exhausted']} exhausted, {c['pending']} pending, "
        f"{c['leased'] + c['running']} in flight, "
        f"{c['unreadable']} unreadable")
    hist = ", ".join(f"{k}: {v}"
                     for k, v in s["attempts_histogram"].items())
    add(f"  attempts histogram: {hist or '(none)'}  "
        f"(retries {s['retries']}, reclaims {s['reclaims']})")
    if s["failures_by_kind"]:
        add("  failures: " + ", ".join(
            f"{k}: {v}" for k, v in s["failures_by_kind"].items()))
    st = s["step_time"]
    total = st["useful_s"] + st["wasted_s"]
    pct = (100.0 * st["wasted_s"] / total) if total else 0.0
    add(f"  step time: {st['useful_s']:.3f}s useful, "
        f"{st['wasted_s']:.3f}s re-executed ({pct:.1f}% waste, "
        f"{st['reexecuted_blocks']} re-run block(s))")
    if s.get("aot_by_worker"):
        add("  aot warm boot: " + ", ".join(
            f"{w}: {a['hits']} hit(s)/{a['misses']} miss(es)"
            for w, a in sorted(s["aot_by_worker"].items())))
    for wid, w in sorted(s.get("workers", {}).items()):
        add(f"  worker {wid}: phase {w.get('phase', '')!r} "
            f"(beat seq {w.get('seq', '?')}) — "
            f"claimed {w.get('jobs_claimed', 0)}, "
            f"done {w.get('jobs_done', 0)}, "
            f"failed {w.get('jobs_failed', 0)}, "
            f"quarantined {w.get('jobs_quarantined', 0)}, "
            f"abandoned {w.get('jobs_abandoned', 0)}, "
            f"reclaimed {w.get('jobs_reclaimed', 0)}")
    for q in s["quarantined"]:
        add(f"  quarantined {q['id']}: [{q['kind']}] {q['error'][:90]}")
    add("-- jobs --")
    for j in s["jobs"]:
        if j.get("state") == "unreadable":
            add(f"  {j['id']}: UNREADABLE job.json")
            continue
        resumed = (f", {j['resumed_points']} resumed"
                   if j.get("resumed_points") else "")
        torn = (f", {j['torn_rows']} torn"
                if j.get("torn_rows") else "")
        add(f"  {j['id']:<28} {j['state']:<12} "
            f"attempts {j['attempts']}"
            f" ({len(j.get('run_ids', []))} run id(s))"
            f", rows {j.get('rows', 0)}{torn}{resumed}")
    holes = [j for j in s["jobs"]
             if j.get("torn_rows")
             or (j.get("state") == "done" and not j.get("rows"))]
    if s["points"] or holes:
        add("-- robust accuracy --")
        for p in s["points"]:
            ra = p.get("robust_accuracy", "?")
            ca = p.get("certified_asr_pc", "?")
            resumed = (f"  [resumed @ {p['resumed_from_iteration']}]"
                       if "resumed_from_iteration" in p else "")
            add(f"  {p['job']:<28} budget {p.get('patch_budget', '?')} "
                f"density {p.get('density', '?')} "
                f"structured {p.get('structured', '?')}: "
                f"robust acc {ra}%, certified ASR {ca}%{resumed}")
        # a done job with torn or absent rows is a measurement HOLE, not a
        # pass — render it explicitly so the grid never looks complete
        for j in holes:
            add(f"  {j['id']:<28} HOLE — {j.get('rows', 0)} recorded, "
                f"{j.get('torn_rows', 0)} torn row(s)")
    return "\n".join(lines)
