"""File-backed job queue: the farm's coordination substrate.

A farm directory is the whole database — no daemon, no sockets, no locks
held by live processes:

    <farm_dir>/
      farm.json                  # the submitted spec (marks the dir a farm)
      jobs/<job_id>/
        job.json                 # the job's state machine (single source of truth)
        lease.json               # present while a worker owns the job
        chaos_<fault>.fired      # chaos markers (fault fired exactly once)
        checkpoints/carry_<i>/   # orbax carry snapshots (crash-resume)
        results/                 # run.json, events.jsonl, rows.jsonl, patches
      workers/<worker_id>/
        heartbeat_0.jsonl        # the worker's liveness signal (observe.Heartbeat)

`job.json` states: ``pending -> leased -> running -> done | failed |
quarantined``. ``failed`` is retryable while ``attempts < max_attempts`` and
the clock has passed ``next_retry_ts``; ``done``/``quarantined`` (and
exhausted ``failed``) are terminal. Every transition is one
`checkpoint.atomic_write_json` — a reader never sees a half-written state.

The lease protocol needs no coordinator:

- *claim*: `os.open(lease.json, O_CREAT|O_EXCL)` — the filesystem picks the
  single winner among racing workers.
- *liveness*: a lease is fresh while the owning worker's heartbeat file
  (`observe.heartbeat`) keeps advancing within the TTL; a SIGKILL'd or
  wedged worker stops beating and its leases go stale with no cleanup code
  running anywhere. Belt-and-suspenders, the lease also carries an
  `expires_ts` renewed (tmp + `os.replace`) at block boundaries, covering
  workers whose heartbeat file was never created.
- *reclaim*: a contender renames the stale lease aside (`os.rename` — only
  one renamer wins) and then claims fresh via O_EXCL. The renewal/takeover
  race window is a few milliseconds against a TTL of seconds, and every
  job-state commit re-checks `owns_lease` — acceptable for a cooperative
  single-filesystem farm (the design point of this queue).

Host-only logic throughout: nothing here touches a jax backend, so the
status/report CLIs stay cheap.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

# Backoff math lives in the shared module (serve replica restarts use the
# same formula); re-exported here because the farm grew it first.
from dorpatch_tpu.backoff import retry_delay  # noqa: F401
from dorpatch_tpu.checkpoint import atomic_write_json, load_json
from dorpatch_tpu.observe.heartbeat import last_beat

FARM_NAME = "farm.json"
JOB_NAME = "job.json"
LEASE_NAME = "lease.json"

STATES = ("pending", "leased", "running", "done", "failed", "quarantined")
TERMINAL_STATES = ("done", "quarantined")


def expand_grid(axes: Dict[str, List]) -> List[Dict]:
    """Cartesian product of ``{param: [values]}`` into one override dict per
    job, in sorted-key order — the same spec always expands to the same job
    list in the same order (job ids, chaos seeds, and retry jitter all hang
    off that determinism)."""
    keys = sorted(axes)
    if not keys:
        return [{}]
    return [dict(zip(keys, combo))
            for combo in itertools.product(*(axes[k] for k in keys))]


def job_slug(params: Dict) -> str:
    """Short filesystem-safe summary of a job's parameter point."""
    parts = []
    for k in sorted(params):
        v = params[k]
        tail = k.split(".")[-1]
        parts.append(f"{tail}={v}")
    return re.sub(r"[^A-Za-z0-9._=-]+", "_", "_".join(parts))[:80]




class JobQueue:
    """All reads/writes of one farm directory's job + lease state."""

    def __init__(self, farm_dir: str, clock=time.time, metrics=None):
        self.farm_dir = os.path.abspath(farm_dir)
        self.jobs_dir = os.path.join(self.farm_dir, "jobs")
        self._clock = clock
        # optional observe.MetricRegistry: claim/reclaim tallies land there
        # so the worker's heartbeat + /metrics surface them live
        self.metrics = metrics
        # seq-freshness cache: heartbeat path -> (last seen seq, OUR clock
        # when it was first seen). Lease liveness must survive wall-clock
        # skew between hosts, so advancement of the writer's monotonic
        # `seq` — timed on the READER's clock — outranks the beat's `ts`.
        # A queue instance may be shared across supervisor threads (the
        # recert drainer polls while a reclaim sweep runs), so the cache
        # read-check-update is atomic under `_lock`.
        self._lock = threading.Lock()
        self._hb_seq: Dict[str, Tuple[int, float]] = {}  # guarded-by: self._lock

    # ---------------- submit ----------------

    def submit_spec(self, spec: Dict) -> List[str]:
        """Expand a spec into per-job directories.

        Spec shape: ``{"base": {partial ExperimentConfig dict}, "axes":
        {dotted param: [values]}, "sweep": {run_sweep kwargs},
        "max_attempts": N}``. Idempotent: resubmitting the same spec leaves
        existing job state untouched and only creates jobs that are missing
        — a farm can be topped up, never accidentally reset."""
        base = dict(spec.get("base", {}))
        axes = dict(spec.get("axes", {}))
        sweep = dict(spec.get("sweep", {}))
        max_attempts = int(spec.get("max_attempts", 3))
        os.makedirs(self.jobs_dir, exist_ok=True)
        ids: List[str] = []
        for idx, params in enumerate(expand_grid(axes)):
            slug = job_slug(params)
            job_id = f"{idx:04d}" + (f"-{slug}" if slug else "")
            jdir = self.job_dir(job_id)
            os.makedirs(jdir, exist_ok=True)
            jpath = os.path.join(jdir, JOB_NAME)
            if not os.path.exists(jpath):
                now = round(self._clock(), 3)
                atomic_write_json(jpath, {
                    "schema": 1,
                    "id": job_id,
                    "index": idx,
                    "state": "pending",
                    "params": params,
                    "base": base,
                    "sweep": sweep,
                    "attempts": 0,
                    "max_attempts": max_attempts,
                    "reclaims": 0,
                    "failures": [],
                    "next_retry_ts": 0.0,
                    "worker": "",
                    "created_ts": now,
                    "updated_ts": now,
                })
            ids.append(job_id)
        atomic_write_json(os.path.join(self.farm_dir, FARM_NAME),
                          {"schema": 1, "spec": spec, "jobs": len(ids)})
        return ids

    # ---------------- job state ----------------

    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, job_id)

    def job_ids(self) -> List[str]:
        try:
            return sorted(
                d for d in os.listdir(self.jobs_dir)
                if os.path.isdir(os.path.join(self.jobs_dir, d)))
        except OSError:
            return []

    def read_job(self, job_id: str) -> Optional[Dict]:
        """The job's state dict, or None when job.json is missing/corrupt
        (claimers skip it; `counts` surfaces it as `unreadable`)."""
        return load_json(os.path.join(self.job_dir(job_id), JOB_NAME))

    def _commit(self, job: Dict, **fields) -> Dict:
        job.update(fields)
        job["updated_ts"] = round(self._clock(), 3)
        atomic_write_json(os.path.join(self.job_dir(job["id"]), JOB_NAME), job)
        return job

    def mark_running(self, job: Dict, worker_id: str) -> Dict:
        """leased -> running; the attempt counter increments HERE, so a job
        reclaimed after a SIGKILL shows attempts == 2 on its second life."""
        return self._commit(job, state="running", worker=worker_id,
                            attempts=int(job.get("attempts", 0)) + 1,
                            started_ts=round(self._clock(), 3))

    def mark_done(self, job: Dict, result: Optional[Dict] = None) -> Dict:
        return self._commit(job, state="done", result=result or {},
                            completed_ts=round(self._clock(), 3))

    def mark_failed(self, job: Dict, failure: Dict,
                    next_retry_ts: Optional[float] = None) -> Dict:
        """Transient failure: retryable until attempts reach max_attempts,
        after which the job is exhausted (terminal `failed`)."""
        failures = list(job.get("failures", [])) + [failure]
        exhausted = int(job["attempts"]) >= int(job["max_attempts"])
        return self._commit(
            job, state="failed", failures=failures, exhausted=exhausted,
            next_retry_ts=0.0 if exhausted else float(next_retry_ts or 0.0))

    def mark_quarantined(self, job: Dict, failure: Dict) -> Dict:
        """Deterministic failure: retrying would fail identically, so the
        job leaves the queue immediately (traceback preserved in job.json)
        instead of burning retries or wedging the farm."""
        failures = list(job.get("failures", [])) + [failure]
        return self._commit(job, state="quarantined", failures=failures)

    def counts(self) -> Dict[str, int]:
        out = {"total": 0, "pending": 0, "leased": 0, "running": 0,
               "done": 0, "failed_retryable": 0, "failed_exhausted": 0,
               "quarantined": 0, "unreadable": 0}
        for job_id in self.job_ids():
            out["total"] += 1
            job = self.read_job(job_id)
            if job is None:
                out["unreadable"] += 1
                continue
            state = job.get("state", "")
            if state == "failed":
                key = ("failed_exhausted" if job.get("exhausted")
                       else "failed_retryable")
                out[key] += 1
            elif state in out:
                out[state] += 1
            else:
                out["unreadable"] += 1
        return out

    def drained(self, counts: Optional[Dict[str, int]] = None) -> bool:
        """True when no job can ever make progress again — every job is
        done, quarantined, exhausted, or unreadable."""
        c = counts if counts is not None else self.counts()
        live = (c["pending"] + c["leased"] + c["running"]
                + c["failed_retryable"])
        return c["total"] > 0 and live == 0

    # ---------------- leases ----------------

    def lease_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), LEASE_NAME)

    def read_lease(self, job_id: str) -> Optional[Dict]:
        return load_json(self.lease_path(job_id))

    def lease_fresh(self, lease: Dict) -> bool:
        """Heartbeat-driven liveness: the lease is fresh while the owner's
        heartbeat file advanced within the TTL. Workers without a readable
        heartbeat fall back to the renewed `expires_ts`.

        Freshness prefers the beat's monotonic ``seq`` over its wall-clock
        ``ts``: a live worker whose clock runs behind ours keeps its lease
        because its seq keeps advancing (measured on OUR clock), and a dead
        worker whose final beat carried a future ts still loses it once the
        seq has been frozen for a full TTL of local time. The ts comparison
        only decides when seq gives no verdict (first observation of a
        file, or a pre-seq beat record)."""
        ttl = float(lease.get("ttl", 60.0))
        now = self._clock()
        hb_path = lease.get("heartbeat") or ""
        if hb_path:
            beat = last_beat(hb_path)
            if beat is not None:
                seq = beat.get("seq")
                if isinstance(seq, int):
                    # the read-check-update of the seq cache is atomic; the
                    # heartbeat-file read above stays OUTSIDE the lock
                    with self._lock:
                        prev = self._hb_seq.get(hb_path)
                        if prev is not None and seq != prev[0]:
                            # advancement since our last look: alive
                            self._hb_seq[hb_path] = (seq, now)
                            return True
                        if prev is None:
                            self._hb_seq[hb_path] = (seq, now)
                        # deliberate wall clock (injectable via `clock=`):
                        # cross-process liveness cannot use a private
                        # monotonic epoch, and the skew hazard is exactly
                        # what the seq-preferred path above absorbs
                        elif now - prev[1] > ttl:  # noqa: DP504 — injectable cross-process clock
                            return False  # frozen a whole TTL: dead
                # ts fallback (pre-seq beats): same deliberate wall clock
                return (now - float(beat["ts"])) <= ttl  # noqa: DP504 — injectable cross-process clock
        return now <= float(lease.get("expires_ts", 0.0))  # noqa: DP504 — injectable cross-process clock

    def _lease_record(self, job_id: str, worker_id: str, ttl: float,
                      heartbeat_path: str) -> Dict:
        now = self._clock()
        return {"job": job_id, "worker": worker_id, "pid": os.getpid(),
                "ttl": float(ttl), "heartbeat": heartbeat_path,
                "acquired_ts": round(now, 3),
                "expires_ts": round(now + float(ttl), 3)}

    def _create_excl(self, path: str, payload: Dict) -> bool:
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
        except FileExistsError:
            return False
        with os.fdopen(fd, "w") as fh:
            json.dump(payload, fh)
        return True

    def try_claim_lease(self, job_id: str, worker_id: str, ttl: float,
                        heartbeat_path: str = "") -> bool:
        """One O_EXCL claim attempt; a stale (or corrupt) existing lease is
        renamed aside first — exactly one of N racing contenders wins the
        rename, and only that winner proceeds to the O_EXCL create."""
        path = self.lease_path(job_id)
        payload = self._lease_record(job_id, worker_id, ttl, heartbeat_path)
        if self._create_excl(path, payload):
            return True
        lease = load_json(path)
        if lease is not None and self.lease_fresh(lease):
            return False
        stale = f"{path}.stale.{worker_id}.{os.getpid()}"
        try:
            os.rename(path, stale)
        except OSError:
            return False  # another contender won the takeover race
        try:
            os.remove(stale)
        except OSError:
            pass
        return self._create_excl(path, payload)

    def renew_lease(self, job_id: str, worker_id: str, ttl: float) -> bool:
        """Refresh `expires_ts` via tmp + `os.replace`; False when the lease
        is no longer this worker's (it was reclaimed — the caller must stop
        touching the job)."""
        path = self.lease_path(job_id)
        lease = load_json(path)
        if not lease or lease.get("worker") != worker_id:
            return False
        lease["expires_ts"] = round(self._clock() + float(ttl), 3)
        lease["renewed_ts"] = round(self._clock(), 3)
        atomic_write_json(path, lease)
        return True

    def owns_lease(self, job_id: str, worker_id: str) -> bool:
        lease = self.read_lease(job_id)
        return lease is not None and lease.get("worker") == worker_id

    def release_lease(self, job_id: str, worker_id: str) -> None:
        if self.owns_lease(job_id, worker_id):
            try:
                os.remove(self.lease_path(job_id))
            except OSError:
                pass

    # ---------------- claiming ----------------

    def claimable(self, job: Dict) -> Tuple[bool, bool]:
        """(claimable now, is a reclaim of a leased/running job). Purely a
        job.json judgment — the lease race decides the actual winner."""
        state = job.get("state", "")
        if state in TERMINAL_STATES:
            return False, False
        if state == "failed":
            if (job.get("exhausted")
                    or int(job["attempts"]) >= int(job["max_attempts"])):
                return False, False
            return self._clock() >= float(job.get("next_retry_ts", 0.0)), False
        if state in ("leased", "running"):
            return True, True  # only wins if the owner's lease went stale
        return state == "pending", False

    def claim(self, worker_id: str, ttl: float,
              heartbeat_path: str = "") -> Optional[Dict]:
        """First claimable job (sorted id order) whose lease this worker
        wins; the job is committed to `leased` under this worker's name.
        None when nothing is currently claimable."""
        for job_id in self.job_ids():
            job = self.read_job(job_id)
            if job is None:
                continue
            ok, is_reclaim = self.claimable(job)
            if not ok:
                continue
            if not self.try_claim_lease(job_id, worker_id, ttl,
                                        heartbeat_path):
                continue
            fields = {"state": "leased", "worker": worker_id}
            if self.metrics is not None:
                self.metrics.counter(
                    "farm_jobs_claimed_total",
                    help="lease claims won by this worker").inc()
            if is_reclaim:
                fields["reclaims"] = int(job.get("reclaims", 0)) + 1
                if self.metrics is not None:
                    self.metrics.counter(
                        "farm_jobs_reclaimed_total",
                        help="claims that took over a stale lease").inc()
            return self._commit(job, **fields)
        return None
