"""`python -m dorpatch_tpu.farm` — the farm's operator surface.

- ``submit <farm_dir> --spec spec.json``  expand the grid into job dirs
- ``work   <farm_dir> [--chaos ...]``     run one worker until drained
- ``status <farm_dir>``                   one JSON line of queue counts
- ``report <farm_dir> [--json]``          fleet report (observe.report)

Every subcommand emits machine-parseable JSON via `observe.log` (the
report's human rendering lives in `observe/report.py`, the one place bare
stdout is in-contract).
"""

from __future__ import annotations

import argparse
import json
from typing import Optional, Sequence

from dorpatch_tpu import observe
from dorpatch_tpu.config import FarmConfig
from dorpatch_tpu.farm.queue import JobQueue


def build_parser() -> argparse.ArgumentParser:
    fc = FarmConfig()
    p = argparse.ArgumentParser(
        prog="python -m dorpatch_tpu.farm",
        description="Fault-tolerant attack-sweep farm over a shared "
                    "farm directory")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("submit", help="expand a grid spec into jobs")
    ps.add_argument("farm_dir")
    ps.add_argument("--spec", required=True,
                    help="JSON: {base: partial config dict, axes: {dotted "
                         "param: [values]}, sweep: {...}, max_attempts: N}")

    pw = sub.add_parser("work", help="claim and run jobs until drained")
    pw.add_argument("farm_dir")
    pw.add_argument("--worker-id", default=None)
    pw.add_argument("--lease-ttl", type=float, default=fc.lease_ttl)
    pw.add_argument("--poll-interval", type=float, default=fc.poll_interval)
    pw.add_argument("--heartbeat-interval", type=float,
                    default=fc.heartbeat_interval)
    pw.add_argument("--backoff-base", type=float, default=fc.backoff_base)
    pw.add_argument("--backoff-cap", type=float, default=fc.backoff_cap)
    pw.add_argument("--backoff-jitter", type=float,
                    default=fc.backoff_jitter)
    pw.add_argument("--max-jobs", type=int, default=None,
                    help="stop after handling this many jobs")
    pw.add_argument("--chaos", default=fc.chaos,
                    help="comma-joined fault list: crash_block, ckpt_raise, "
                         "wedge_heartbeat, enospc_events")
    pw.add_argument("--crash-mode", choices=["kill", "raise"],
                    default="kill",
                    help="crash_block dies by SIGKILL (kill) or by a "
                         "catchable SimulatedPreemption (raise)")
    pw.add_argument("--aot-store", default="",
                    help="shared AOT executable store (read-only): jitted "
                         "programs warm-boot from pre-compiled executables "
                         "on first call instead of tracing, so reclaimed "
                         "jobs resume without re-paying compile "
                         "('' = disabled)")
    pw.add_argument("--aot", choices=["off", "auto"], default="auto",
                    help="warm-boot mode with --aot-store (workers never "
                         "write the store; 'auto' here means "
                         "load-what-hits, compile the rest)")

    pst = sub.add_parser("status", help="queue counts as one JSON line")
    pst.add_argument("farm_dir")

    pr = sub.add_parser("report", help="fleet-level report")
    pr.add_argument("farm_dir")
    pr.add_argument("--json", action="store_true")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "submit":
        with open(args.spec) as fh:
            spec = json.load(fh)
        ids = JobQueue(args.farm_dir).submit_spec(spec)
        observe.log(json.dumps({"farm_dir": args.farm_dir,
                                "jobs": len(ids)}))
        return 0
    if args.cmd == "work":
        from dorpatch_tpu.farm.worker import FarmWorker  # lazy: model stack

        worker = FarmWorker(
            args.farm_dir, worker_id=args.worker_id,
            lease_ttl=args.lease_ttl, poll_interval=args.poll_interval,
            heartbeat_interval=args.heartbeat_interval,
            backoff_base=args.backoff_base, backoff_cap=args.backoff_cap,
            backoff_jitter=args.backoff_jitter, chaos=args.chaos,
            crash_mode=args.crash_mode,
            aot_store=args.aot_store, aot_mode=args.aot)
        summary = worker.run(max_jobs=args.max_jobs)
        observe.log(json.dumps(summary))
        return 0
    if args.cmd == "status":
        observe.log(json.dumps(JobQueue(args.farm_dir).counts()))
        return 0
    # report: observe.report owns all human rendering; it dispatches on
    # farm.json and renders the fleet section
    from dorpatch_tpu.observe import report as report_cli

    return report_cli.main([args.farm_dir]
                           + (["--json"] if args.json else []))


if __name__ == "__main__":
    raise SystemExit(main())
