"""Attack-sweep farm: a standing, fault-tolerant red-teaming service.

The paper's experiment grid (model family x patch budget x n_patch x dual
occlusion) is embarrassingly parallel, but one-process-per-invocation runs
lose the whole grid to a single crash. The farm turns a grid spec into a
file-backed job queue (`queue.py`) that N worker processes (`worker.py`)
drain cooperatively: atomic lease files with heartbeat-driven expiry make a
SIGKILL'd or wedged worker's jobs reclaimable by survivors with no
coordinator; per-job carry checkpoints make a reclaimed job *resume* rather
than restart; a typed failure taxonomy retries transient errors with
backoff and quarantines deterministic ones with their traceback. `chaos.py`
injects each failure mode deterministically so every recovery path is
provable, and `report.py` aggregates the fleet's accounting.

CLI: ``python -m dorpatch_tpu.farm submit|work|status|report``.

Import discipline: this module and `queue`/`report`/`chaos` stay host-only
cheap; the model/compile stack loads only inside a worker actually running
a job (`worker.default_runner`).
"""

from dorpatch_tpu.farm.queue import JobQueue, expand_grid, retry_delay  # noqa: F401

__all__ = ["JobQueue", "expand_grid", "retry_delay"]
