"""Re-export shim: the chaos harness graduated to `dorpatch_tpu.chaos`.

The serve replica pool needed the same exactly-once fault-injection
protocol the farm grew in PR 9, so the implementation moved up a level.
Farm code and tests keep importing from here unchanged; see
`dorpatch_tpu/chaos.py` for the fault catalogue and injection sites.
"""

from dorpatch_tpu.chaos import (  # noqa: F401
    FARM_FAULTS,
    FAULTS,
    Chaos,
    SimulatedPreemption,
    fault_seed,
    parse_faults,
)

__all__ = ["FARM_FAULTS", "FAULTS", "Chaos", "SimulatedPreemption",
           "fault_seed", "parse_faults"]
