"""Farm worker: claim -> run -> commit, with typed failure handling.

A worker is a plain process over the shared farm directory. Per claimed job
it wires the full observability/recovery stack the pipelines already use —
`run.json` manifest (attempt chaining via `previous_run_ids`), per-job
`events.jsonl`, carry checkpoints at block boundaries — then runs the job's
grid slice through `sweep.run_sweep`. The farm layer launches only the
already-registered jit programs; it adds no entry points of its own, so the
zero-recompile and audit guarantees carry over untouched.

Failure taxonomy (`classify_failure`):

- *transient* (OOM, IO/ENOSPC, preemption, unclassified runtime errors) —
  the job returns to `failed` with exponential backoff + deterministic
  jitter and is retried until `max_attempts`; its checkpoints survive, so a
  retry resumes rather than restarts.
- *deterministic* (trace/shape errors, NaN loss from the sanitizer,
  recompile-budget violations) — retrying would fail identically: the job
  is quarantined immediately with the traceback in `job.json`, so one bad
  grid point never poisons the queue or burns the fleet's time.

Lease discipline: the lease is renewed at every attack-block boundary, and
every commit re-checks ownership — a worker that lost its lease (wedged
heartbeat, reclaimed job) abandons silently; the reclaimer owns the state.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
import traceback
from typing import Callable, Dict, Optional, Tuple

from dorpatch_tpu import checkpoint, observe
from dorpatch_tpu.config import ExperimentConfig, config_from_dict
from dorpatch_tpu.farm import queue as farm_queue
from dorpatch_tpu.farm.chaos import Chaos, SimulatedPreemption, parse_faults


class LeaseLost(RuntimeError):
    """This worker's lease was reclaimed mid-run; the job is no longer ours
    to execute or to commit state for."""


def classify_failure(exc: BaseException) -> Tuple[str, bool]:
    """(kind, transient). Unclassified errors count as transient: wrongly
    retrying a deterministic bug costs `max_attempts - 1` wasted runs before
    the job parks as exhausted, while wrongly quarantining a transient blip
    silently loses a finishable job — the cheaper mistake wins."""
    if isinstance(exc, SimulatedPreemption):
        return "preemption", True
    if isinstance(exc, MemoryError):
        return "oom", True
    if isinstance(exc, OSError):
        return "io", True
    name = type(exc).__name__
    if name == "RecompileBudgetExceeded":
        return "recompile", False
    if name == "XlaRuntimeError":
        msg = str(exc).lower()
        if "resource exhausted" in msg or "out of memory" in msg:
            return "oom", True
        return "xla", True
    if isinstance(exc, FloatingPointError):
        return "nan", False  # jax_debug_nans sanitizer: NaN at the source
    if isinstance(exc, (TypeError, ValueError, KeyError, AttributeError,
                        IndexError)):
        return "trace", False  # shape/trace/config programming errors
    return "unknown", True


def apply_overrides(cfg: ExperimentConfig, params: Dict) -> ExperimentConfig:
    """Dotted job-axis overrides onto a config: ``"attack.patch_budget"``
    reaches into the nested dataclass, bare keys hit `ExperimentConfig`
    itself. Unknown fields raise (dataclasses.replace) -> deterministic
    quarantine, which is exactly right for a typo'd spec axis."""
    for key in sorted(params):
        value = params[key]
        if isinstance(value, list):
            value = tuple(value)
        if "." in key:
            head, field = key.split(".", 1)
            if "." in field:
                raise ValueError(f"axis {key!r}: at most one dot")
            sub = dataclasses.replace(getattr(cfg, head), **{field: value})
            cfg = dataclasses.replace(cfg, **{head: sub})
        else:
            cfg = dataclasses.replace(cfg, **{key: value})
    return cfg


def job_config(job: Dict) -> ExperimentConfig:
    """The job's resolved config: partial base dict (defaults fill the
    rest) + this job's grid-point overrides."""
    return apply_overrides(config_from_dict(dict(job.get("base", {}))),
                           job.get("params", {}))


@dataclasses.dataclass
class JobContext:
    """Everything a runner needs beyond the job dict — kept explicit so
    tests can substitute a stub runner with no model/compile cost."""

    result_dir: str
    checkpoint_root: str
    chaos: Optional[Chaos]
    on_block_end: Optional[Callable[[int, int, dict], None]]
    checkpointer_factory: Optional[Callable[[int, Dict], object]]


def default_runner(job: Dict, ctx: JobContext) -> Dict:
    """Run the job's grid slice via `sweep.run_sweep` with the crash-resume
    wiring attached (incremental rows, per-point carry checkpoints, the
    lease/chaos block hook)."""
    from dorpatch_tpu.sweep import run_sweep  # lazy: pulls the model stack

    cfg = job_config(job)
    sweep_kw = dict(job.get("sweep", {}))
    rows = run_sweep(
        cfg,
        patch_budgets=tuple(sweep_kw.get("patch_budgets",
                                         (cfg.attack.patch_budget,))),
        densities=tuple(sweep_kw.get("densities", (cfg.attack.density,))),
        structureds=tuple(sweep_kw.get("structureds",
                                       (cfg.attack.structured,))),
        defense_ratio=float(sweep_kw.get("defense_ratio", 0.06)),
        verbose=False,
        result_dir=ctx.result_dir,
        checkpointer_factory=ctx.checkpointer_factory,
        on_block_end=ctx.on_block_end,
    )
    return {
        "rows": len(rows),
        "resumed_points": sum(
            1 for r in rows if "resumed_from_iteration" in r),
    }


class FarmWorker:
    """One worker process's claim-and-run loop over a farm directory."""

    def __init__(self, farm_dir: str, worker_id: Optional[str] = None,
                 lease_ttl: float = 60.0,
                 backoff_base: float = 2.0, backoff_cap: float = 300.0,
                 backoff_jitter: float = 0.25, poll_interval: float = 1.0,
                 heartbeat_interval: float = 1.0, chaos: str = "",
                 crash_mode: str = "kill",
                 runner: Optional[Callable[[Dict, JobContext], Dict]] = None,
                 clock=time.time, sleep=time.sleep,
                 aot_store: str = "", aot_mode: str = "auto"):
        # one registry per worker process: the queue's claim/reclaim
        # tallies, the drain loop's outcome counters, and the heartbeat's
        # live `jobs_*` fields all read/write the same series
        self.metrics = observe.MetricRegistry()
        self.queue = farm_queue.JobQueue(farm_dir, clock=clock,
                                         metrics=self.metrics)
        self.worker_id = worker_id or f"w{os.getpid()}"
        self.lease_ttl = float(lease_ttl)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.backoff_jitter = float(backoff_jitter)
        self.poll_interval = float(poll_interval)
        self.heartbeat_interval = float(heartbeat_interval)
        self.chaos_faults = parse_faults(chaos) if chaos else ()
        self.crash_mode = crash_mode
        self.runner = runner if runner is not None else default_runner
        self._clock = clock
        self._sleep = sleep
        # AOT executable store (shared, opened READ-ONLY): jitted programs
        # whose fingerprint matches a store entry boot from pre-compiled
        # executables on their first call, so a reclaimed job's resume does
        # not re-pay compile. Read-only by design — N workers racing writes
        # into one store is the failure mode the build subcommand exists to
        # avoid.
        self.aot_store = aot_store
        self.aot_mode = aot_mode
        self.worker_dir = os.path.join(self.queue.farm_dir, "workers",
                                       self.worker_id)
        self.heartbeat_path = os.path.join(self.worker_dir,
                                           observe.heartbeat_filename(0))
        self._phase = "idle"
        self._heartbeat: Optional[observe.Heartbeat] = None
        self._m_jobs = self.metrics.counter(
            "farm_jobs_total", help="handled jobs by terminal outcome")
        self._m_retries = self.metrics.counter(
            "farm_job_retries_total",
            help="claims of a job that had already been attempted")

    # ---------------- the drain loop ----------------

    def _beat_extra(self) -> Dict:
        """Live job counters folded into every heartbeat beat, so `farm
        report` shows fleet throughput while workers are still running."""
        m = self.metrics
        return {
            "jobs_done": int(m.value("farm_jobs_total", outcome="done")),
            "jobs_failed": int(m.value("farm_jobs_total", outcome="failed")),
            "jobs_quarantined": int(
                m.value("farm_jobs_total", outcome="quarantined")),
            "jobs_abandoned": int(
                m.value("farm_jobs_total", outcome="abandoned")),
            "jobs_claimed": int(m.value("farm_jobs_claimed_total")),
            "jobs_reclaimed": int(m.value("farm_jobs_reclaimed_total")),
        }

    def _install_profile_signal(self):
        """SIGUSR2 -> bounded on-demand `jax.profiler` capture into the
        worker dir, without interrupting the job (the capture runs on its
        own thread; the signal handler only launches it). Returns the
        previous handler, or None when not installable (non-main thread,
        e.g. a worker driven from a test thread)."""
        def _handler(signum, frame):
            threading.Thread(
                target=observe.capture_profile, args=(self.worker_dir,),
                kwargs={"duration_s": 1.0},
                name="farm-profile", daemon=True).start()

        try:
            return signal.signal(signal.SIGUSR2, _handler)
        except ValueError:
            return None

    def run(self, max_jobs: Optional[int] = None) -> Dict:
        """Claim and run jobs until the queue is drained (or `max_jobs`
        handled). Polls while other workers hold live leases — their jobs
        become claimable here the moment their heartbeats go stale."""
        os.makedirs(self.worker_dir, exist_ok=True)
        summary = {"worker": self.worker_id, "done": 0, "failed": 0,
                   "quarantined": 0, "abandoned": 0}
        resolver = None
        prev_resolver = None
        if self.aot_store and self.aot_mode != "off":
            # install BEFORE claiming anything: the first jitted call of the
            # first job is already warm-boot eligible
            try:
                from dorpatch_tpu.aot.boot import FirstCallAotResolver
                from dorpatch_tpu.aot.store import open_readonly

                store = open_readonly(self.aot_store)
                if store is not None:
                    resolver = FirstCallAotResolver(store)
            except Exception:
                resolver = None  # warm boot is an optimization, never a gate
            if resolver is not None:
                prev_resolver = observe.aot_resolver()
                observe.set_aot_resolver(resolver)
        prev_sig = self._install_profile_signal()
        heartbeat = observe.Heartbeat(
            self.heartbeat_path, get_phase=lambda: self._phase,
            interval=self.heartbeat_interval, clock=self._clock,
            extra=self._beat_extra)
        with heartbeat:
            self._heartbeat = heartbeat
            try:
                while True:
                    if max_jobs is not None and sum(
                            summary[k] for k in
                            ("done", "failed", "quarantined", "abandoned")
                    ) >= max_jobs:
                        break
                    job = self.queue.claim(self.worker_id, self.lease_ttl,
                                           self.heartbeat_path)
                    if job is None:
                        counts = self.queue.counts()
                        if self.queue.drained(counts):
                            break
                        self._sleep(self.poll_interval)
                        continue
                    if int(job.get("attempts", 0)) > 0:
                        self._m_retries.inc()
                    outcome = self.run_one(job)
                    summary[outcome] += 1
                    self._m_jobs.inc(outcome=outcome)
                    if (outcome == "abandoned" and self.chaos_faults
                            and "wedge_heartbeat" in self.chaos_faults):
                        # our beats stopped: every lease we'd take is born
                        # stale — stop claiming instead of thrashing jobs
                        # back and forth with the healthy workers
                        summary["wedged"] = True
                        break
            finally:
                self._heartbeat = None
                if prev_sig is not None:
                    try:
                        signal.signal(signal.SIGUSR2, prev_sig)
                    except ValueError:
                        pass
                if resolver is not None:
                    observe.set_aot_resolver(prev_resolver)
                    summary["aot"] = dict(resolver.stats)
                    # per-worker hit counts for the fleet report
                    checkpoint.atomic_write_json(
                        os.path.join(self.worker_dir, "aot.json"),
                        {"worker": self.worker_id, **resolver.stats})
                self.metrics.dump(
                    os.path.join(self.worker_dir, "metrics.json"))
        summary["counts"] = self.queue.counts()
        return summary

    # ---------------- one job ----------------

    def run_one(self, job: Dict) -> str:
        """Execute one claimed job to a single outcome: ``done``,
        ``failed`` (transient, retryable), ``quarantined`` (deterministic),
        or ``abandoned`` (lease lost — the reclaimer owns the state)."""
        jq = self.queue
        job_id = job["id"]
        job_dir = jq.job_dir(job_id)
        result_dir = os.path.join(job_dir, "results")
        checkpoint_root = os.path.join(job_dir, "checkpoints")
        chaos = None
        if self.chaos_faults:
            chaos = Chaos(self.chaos_faults, job_id, job_dir,
                          crash_mode=self.crash_mode).bind(self._heartbeat)
        jq.mark_running(job, self.worker_id)
        self._phase = f"job/{job_id}"
        run_id = observe.new_run_id()
        # the job's cross-process correlation id, minted at ingress (the
        # claim): every record of this attempt carries it, so the fleet
        # report can join a serve/farm/recert trace end to end
        trace_id = observe.new_trace_id()
        try:
            os.makedirs(result_dir, exist_ok=True)
            cfg = job_config(job)
            observe.write_run_manifest(
                result_dir, cfg, run_id=run_id,
                extra={"farm": {"job": job_id, "worker": self.worker_id,
                                "attempt": job["attempts"],
                                "trace": trace_id}})

            def on_block(stage: int, iteration: int,
                         info: Optional[dict] = None) -> None:
                if chaos is not None:
                    chaos.on_block(stage, iteration, info)
                if not jq.renew_lease(job_id, self.worker_id,
                                      self.lease_ttl):
                    raise LeaseLost(
                        f"lease on {job_id} reclaimed mid-run")

            def checkpointer_factory(point: int, point_params: Dict):
                from dorpatch_tpu.checkpoint import CarryCheckpointer

                # fingerprint is attempt-INdependent: a retry must restore
                # the previous attempt's snapshots, that is the whole point
                ck = CarryCheckpointer(
                    os.path.join(checkpoint_root, f"carry_{point}"),
                    fingerprint={"job": job_id, "point": int(point),
                                 **{k: float(v)
                                    for k, v in point_params.items()}})
                return (chaos.wrap_checkpointer(ck) if chaos is not None
                        else ck)

            ctx = JobContext(result_dir=result_dir,
                             checkpoint_root=checkpoint_root, chaos=chaos,
                             on_block_end=on_block,
                             checkpointer_factory=checkpointer_factory)
            event_log = observe.EventLog(
                os.path.join(result_dir, observe.events_filename(0)),
                run_id=run_id)
            if chaos is not None:
                chaos.wrap_event_log(event_log)
            with event_log, observe.active(event_log):
                observe.record_event("farm.job.claim", job=job_id,
                                     worker=self.worker_id,
                                     attempt=job["attempts"],
                                     trace=trace_id, opens_trace=True)
                with observe.span("farm.job", job=job_id,
                                  attempt=job["attempts"], trace=trace_id):
                    result = self.runner(job, ctx)
        except LeaseLost:
            observe.log(f"worker {self.worker_id}: abandoned {job_id} "
                        "(lease reclaimed)")
            return "abandoned"
        except Exception as exc:
            return self._commit_failure(job, exc)
        finally:
            self._phase = "idle"
        if not jq.owns_lease(job_id, self.worker_id):
            observe.log(f"worker {self.worker_id}: finished {job_id} but "
                        "the lease moved on; abandoning the commit")
            return "abandoned"
        jq.mark_done(job, result if isinstance(result, dict) else {})
        jq.release_lease(job_id, self.worker_id)
        observe.log(f"worker {self.worker_id}: {job_id} done "
                    f"(attempt {job['attempts']})")
        return "done"

    def _commit_failure(self, job: Dict, exc: Exception) -> str:
        jq = self.queue
        job_id = job["id"]
        kind, transient = classify_failure(exc)
        if not jq.owns_lease(job_id, self.worker_id):
            return "abandoned"
        failure = {
            "attempt": int(job["attempts"]),
            "kind": kind,
            "transient": transient,
            "error": f"{type(exc).__name__}: {exc}",
            "traceback": traceback.format_exc(),
            "worker": self.worker_id,
            "ts": round(self._clock(), 3),
        }
        if not transient:
            jq.mark_quarantined(job, failure)
            outcome = "quarantined"
        else:
            delay = farm_queue.retry_delay(
                job_id, int(job["attempts"]), base=self.backoff_base,
                cap=self.backoff_cap, jitter=self.backoff_jitter)
            jq.mark_failed(job, failure,
                           next_retry_ts=self._clock() + delay)
            outcome = "failed"
        jq.release_lease(job_id, self.worker_id)
        observe.log(f"worker {self.worker_id}: {job_id} {outcome} "
                    f"({kind}: {exc})")
        return outcome
