"""`python -m dorpatch_tpu.gateway` — front a serve fleet until
interrupted.

Reuses the experiment CLI surface (`dorpatch_tpu.cli.build_parser`): the
`--gateway-*` group names the backends and tunes membership/routing/
deploy knobs; `--chaos wedge_probe,poison_canary` arms the gateway-side
fault injection (dorpatch_tpu.chaos) for recovery drills. Telemetry
lands in `<results_root>/gateway/` (run.json + events.jsonl +
metrics.json); render it together with the backends' dirs via
`python -m dorpatch_tpu.observe.report --fleet <dirs...>`.

The gateway process never imports jax — it boots in milliseconds and
routes certified-inference traffic with sockets and JSON only.
"""

from __future__ import annotations

import os
import time

from dorpatch_tpu import observe
from dorpatch_tpu.cli import build_parser, config_from_args
from dorpatch_tpu.gateway.http import GatewayFrontend
from dorpatch_tpu.gateway.service import Gateway


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if not cfg.gateway.backends:
        raise SystemExit("gateway: --gateway-backends is required "
                         "(comma-joined http://host:port list)")
    result_dir = os.path.join(cfg.results_root, "gateway")
    gateway = Gateway(cfg.gateway, result_dir=result_dir)
    with gateway:
        with GatewayFrontend(gateway, cfg.gateway.host, cfg.gateway.port):
            observe.log(
                f"gateway: fronting {len(cfg.gateway.backends)} backend(s) "
                f"{list(cfg.gateway.backends)} — probe every "
                f"{cfg.gateway.probe_interval_s:g}s, eject after "
                f"{cfg.gateway.fail_threshold}, re-admit after "
                f"{cfg.gateway.ok_threshold}"
                + (f", chaos [{cfg.gateway.chaos}]"
                   if cfg.gateway.chaos else ""))
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                observe.log("gateway: shutting down")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
