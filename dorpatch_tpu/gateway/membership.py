"""Health-probe-driven backend membership for the fleet gateway.

One `Backend` per serve process, one `BackendRegistry` owning the fleet
and a single prober thread. The prober polls every backend's `/healthz`
(+ `/stats` for load signals, + `/robustness` when configured) on a
jittered interval and drives the membership state machine:

    joining ──ok_threshold consecutive oks──▶ healthy ◀──▶ degraded
       ▲                                        │  (robustness verdict)
       │ first ok after ejection                │
       │                                        │ fail_threshold
    ejected ◀──consecutive probe failures───────┘ consecutive failures

plus `draining` — set only by the rolling deploy (`deploy.py`), never
left automatically: a draining backend takes no new traffic but keeps
being probed so its stats stay current for the report.

The hysteresis is the point: an ejected backend must first re-enter
`joining` (one good probe) and then string together `ok_threshold`
consecutive good probes before any traffic returns — a flapping backend
that alternates ok/fail never re-admits.

Lock discipline (DP5xx-audited): every mutable `Backend` field is
guarded by that backend's own `self.lock`; the registry's backend list
by `self._lock`. The two are NEVER nested — callers copy the list out
under the registry lock and then take per-backend locks one at a time —
and no HTTP call ever runs under any lock (probes collect their results
first, then apply them in one short critical section).
"""

from __future__ import annotations

import json
import random
import threading
import urllib.error
import urllib.request
from typing import Callable, List, Optional, Sequence, Tuple

from dorpatch_tpu import observe

JOINING = "joining"
HEALTHY = "healthy"
DEGRADED = "degraded"
EJECTED = "ejected"
DRAINING = "draining"
STATES = (JOINING, HEALTHY, DEGRADED, EJECTED, DRAINING)

#: States the router may dispatch to. `degraded` (health ok, robustness
#: verdict failing) is routable only as a last resort — the router prefers
#: healthy backends and falls back to degraded ones when none remain.
ROUTABLE_STATES = (HEALTHY, DEGRADED)


def backend_name(url: str) -> str:
    """Stable display/label name for a backend URL: host:port."""
    return url.split("://", 1)[-1].rstrip("/")


class Backend:
    """One serve process behind the gateway: its URL plus the probe-fed
    view of its health and load. All mutable state lives behind
    `self.lock`; readers take a `snapshot()` instead of poking fields."""

    def __init__(self, url: str, name: str = "", weight: float = 1.0):
        self.url = url.rstrip("/")
        self.name = name or backend_name(url)
        self.lock = threading.Lock()
        self.state = JOINING        # guarded-by: self.lock
        self.consec_fail = 0        # guarded-by: self.lock
        self.consec_ok = 0          # guarded-by: self.lock
        self.weight = float(weight)  # guarded-by: self.lock
        self.inflight = 0           # guarded-by: self.lock
        self.occupancy = 0.0        # guarded-by: self.lock
        self.reject_rate = 0.0      # guarded-by: self.lock
        self.queue_depth = 0        # guarded-by: self.lock
        self.warm = False           # guarded-by: self.lock
        self.robustness_ok = True   # guarded-by: self.lock
        self.last_error = ""        # guarded-by: self.lock

    def snapshot(self) -> dict:
        with self.lock:
            return {"name": self.name, "url": self.url, "state": self.state,
                    "weight": round(self.weight, 6),
                    "inflight": self.inflight,
                    "occupancy": round(self.occupancy, 4),
                    "reject_rate": round(self.reject_rate, 4),
                    "queue_depth": self.queue_depth, "warm": self.warm,
                    "robustness_ok": self.robustness_ok,
                    "consec_fail": self.consec_fail,
                    "consec_ok": self.consec_ok,
                    "last_error": self.last_error}

    def score(self, inflight_cap: int) -> float:
        """Load score for power-of-two-choices (lower = better): scraped
        occupancy, reject pressure, and the gateway's own inflight view."""
        with self.lock:
            return (self.occupancy + 2.0 * self.reject_rate
                    + self.inflight / max(1, inflight_cap))

    def begin_dispatch(self, inflight_cap: int) -> bool:
        """Reserve an inflight slot iff the backend is routable and under
        its cap — the router's one atomic admission decision."""
        with self.lock:
            if (self.state not in ROUTABLE_STATES or self.weight <= 0.0
                    or self.inflight >= inflight_cap):
                return False
            self.inflight += 1
            return True

    def end_dispatch(self) -> None:
        with self.lock:
            self.inflight = max(0, self.inflight - 1)


class BackendRegistry:
    """The fleet roster plus its single daemon prober thread.

    `on_transition(backend_name, prev, new, reason)` fires OUTSIDE all
    locks for every membership change (the gateway wires it into its
    event log and the `gateway_membership_transitions_total` counter);
    `on_cycle(snapshots)` fires once per full probe sweep (the gateway
    feeds it to the autoscaler and the fleet gauges).
    """

    def __init__(self, backends: Sequence[Backend], cfg, chaos=None,
                 on_transition: Optional[Callable] = None,
                 on_cycle: Optional[Callable] = None):
        self._cfg = cfg
        self._chaos = chaos
        self._on_transition = on_transition
        self._on_cycle = on_cycle
        self._lock = threading.Lock()
        self._backends = list(backends)  # guarded-by: self._lock
        self._stop = threading.Event()
        # deterministic jitter source (probe-thread confined)
        self._rng = random.Random(0xD0B9A7C4)
        self._thread: Optional[threading.Thread] = None

    # ---------------- roster ----------------

    def backends(self) -> List[Backend]:
        with self._lock:
            return list(self._backends)

    def get(self, name: str) -> Optional[Backend]:
        for b in self.backends():
            if b.name == name:
                return b
        return None

    def add(self, backend: Backend) -> Backend:
        """Register a new backend (rolling deploys add canaries live). It
        enters `joining` and earns traffic through the normal probe path."""
        with self._lock:
            self._backends.append(backend)
        self._emit(backend.name, "", JOINING, "registered")
        return backend

    def set_weight(self, name: str, weight: float) -> None:
        b = self.get(name)
        if b is None:
            return
        with b.lock:
            b.weight = float(weight)

    def set_state(self, name: str, state: str, reason: str) -> None:
        """Administrative transition (the deploy's draining/restore path);
        probe-driven transitions go through `_apply_probe`."""
        if state not in STATES:
            raise ValueError(f"unknown backend state {state!r}")
        b = self.get(name)
        if b is None:
            return
        with b.lock:
            prev = b.state
            b.state = state
            if state == JOINING:
                b.consec_ok = 0
                b.consec_fail = 0
        if prev != state:
            self._emit(name, prev, state, reason)

    def routable(self) -> List[Backend]:
        """Dispatch candidates, healthy preferred: degraded backends are
        offered only when no healthy backend remains."""
        snaps = [(b, b.snapshot()) for b in self.backends()]
        healthy = [b for b, s in snaps
                   if s["state"] == HEALTHY and s["weight"] > 0.0]
        if healthy:
            return healthy
        return [b for b, s in snaps
                if s["state"] == DEGRADED and s["weight"] > 0.0]

    # ---------------- prober lifecycle ----------------

    def start(self) -> "BackendRegistry":
        self._stop.clear()
        self._thread = threading.Thread(target=self._probe_loop,
                                        name="gateway-prober", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_cycle()
            except Exception as e:  # a probe bug must never kill the fleet
                observe.log(f"gateway: probe cycle failed: {e!r}")
            jitter = 1.0 + self._cfg.probe_jitter * self._rng.random()
            self._stop.wait(self._cfg.probe_interval_s * jitter)

    def probe_cycle(self) -> None:
        """One synchronous sweep over the roster (public so tests can step
        membership deterministically without the thread)."""
        backends = self.backends()
        for i, b in enumerate(backends):
            if self._stop.is_set():
                return
            self._probe_one(i, b)
        if self._on_cycle is not None:
            self._on_cycle([b.snapshot() for b in backends])

    # ---------------- one probe ----------------

    def _probe_one(self, index: int, b: Backend) -> None:
        forced = (self._chaos is not None
                  and self._chaos.on_gateway_probe(index, b.name))
        if forced:
            ok, stats, robust_ok, err = False, None, True, "chaos: wedged probe"
        else:
            ok, stats, robust_ok, err = self._collect(b)
        transition = self._apply_probe(b, ok, stats, robust_ok, err)
        if transition is not None:
            prev, new, reason = transition
            self._emit(b.name, prev, new, reason)

    def _collect(self, b: Backend) -> Tuple[bool, Optional[dict], bool, str]:
        """All the probe's HTTP, outside every lock. A backend is probe-ok
        iff /healthz answers 200; /stats feeds the load signals (failure
        leaves them stale, not unhealthy); /robustness gates degradation."""
        health, err = self._get_json(b.url + "/healthz")
        if health is None:
            return False, None, True, err
        stats, _ = self._get_json(b.url + "/stats")
        robust_ok = True
        if getattr(self._cfg, "check_robustness", True):
            verdict, _ = self._get_json(b.url + "/robustness")
            robust_ok = verdict is not None
        return True, stats, robust_ok, ""

    def _get_json(self, url: str) -> Tuple[Optional[dict], str]:
        req = urllib.request.Request(
            url, headers={"Accept": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self._cfg.probe_timeout_s) as resp:
                return json.loads(resp.read().decode("utf-8") or "{}"), ""
        except urllib.error.HTTPError as e:
            return None, f"http {e.code}"
        except (urllib.error.URLError, OSError, ValueError) as e:
            return None, f"{type(e).__name__}: {e}"

    def _apply_probe(self, b: Backend, ok: bool, stats: Optional[dict],
                     robust_ok: bool, err: str
                     ) -> Optional[Tuple[str, str, str]]:
        """Fold one probe result into the state machine — the one short
        critical section per probe. Returns (prev, new, reason) when the
        membership state changed."""
        cfg = self._cfg
        with b.lock:
            prev = b.state
            reason = ""
            if ok:
                b.consec_ok += 1
                b.consec_fail = 0
                b.last_error = ""
                b.robustness_ok = robust_ok
                if stats is not None:
                    b.occupancy = float(stats.get("occupancy", b.occupancy))
                    b.reject_rate = float(
                        stats.get("reject_rate", b.reject_rate))
                    b.queue_depth = int(
                        stats.get("queue_depth", b.queue_depth))
                    b.warm = bool(stats.get("warm", b.warm))
                if b.state == EJECTED:
                    # re-admission hysteresis leg 1: one good probe only
                    # re-enters joining; traffic waits for ok_threshold
                    b.state = JOINING
                    b.consec_ok = 1
                    reason = "probe_ok"
                elif (b.state == JOINING
                      and b.consec_ok >= cfg.ok_threshold):
                    b.state = HEALTHY if robust_ok else DEGRADED
                    reason = "probe_ok" if robust_ok else "robustness"
                elif b.state == HEALTHY and not robust_ok:
                    b.state = DEGRADED
                    reason = "robustness"
                elif b.state == DEGRADED and robust_ok:
                    b.state = HEALTHY
                    reason = "robustness"
            else:
                b.consec_fail += 1
                b.consec_ok = 0
                b.last_error = err
                if (b.state in (JOINING, HEALTHY, DEGRADED)
                        and b.consec_fail >= cfg.fail_threshold):
                    b.state = EJECTED
                    reason = "probe_fail"
            new = b.state
        if new != prev:
            return prev, new, reason
        return None

    def _emit(self, name: str, prev: str, new: str, reason: str) -> None:
        if self._on_transition is not None:
            self._on_transition(name, prev, new, reason)
