"""The gateway itself: membership + router + telemetry in one object.

`Gateway` owns the fleet roster (`BackendRegistry` + prober thread), the
power-of-two router, its OWN metric registry and event log (a gateway is
a separate process with its own books — the fleet report joins them with
each backend's), and the signal-only autoscaler. It is deliberately
jax-free: routing certified-inference traffic needs sockets and JSON,
not an accelerator backend, so the gateway process never pays a jax
import or initialization.

Exactly-once accounting contract (what `observe.report --fleet` checks):

- every admitted request writes `gateway.admit` (opens_trace) at ingress
  and exactly one terminal `gateway.request` event — the terminal event
  closes the trace even when the answering backend was SIGKILLed before
  writing its own terminal record;
- `gateway_requests_total{status}` must equal the client's view exactly,
  and `gateway_backend_responses_total{backend, status}` must equal the
  sum of the backends' own `serve_requests_total` books (the killed
  backend's in-flight batch is counted NOWHERE — chaos `kill_backend`
  flushes committed counters before the SIGKILL and the router retries
  the unresolved requests on a survivor).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import List, Optional

from dorpatch_tpu import observe
from dorpatch_tpu.gateway.autoscale import Autoscaler
from dorpatch_tpu.gateway.membership import (ROUTABLE_STATES, STATES,
                                             Backend, BackendRegistry)
from dorpatch_tpu.gateway.router import Router


class Gateway:
    def __init__(self, cfg, result_dir: str = "", run_id: str = ""):
        self.cfg = cfg
        self.result_dir = result_dir
        self.run_id = run_id
        self.chaos = None
        if getattr(cfg, "chaos", ""):
            from dorpatch_tpu.chaos import Chaos, parse_faults
            state_dir = result_dir or tempfile.mkdtemp(
                prefix="dorpatch_gateway_chaos_")
            self.chaos = Chaos(parse_faults(cfg.chaos), job_id="gateway",
                               state_dir=state_dir, crash_mode="raise")
        self.metrics = observe.MetricRegistry()
        self._requests = self.metrics.counter(
            "gateway_requests_total",
            help="gateway-answered requests by terminal status")
        self._backend_responses = self.metrics.counter(
            "gateway_backend_responses_total",
            help="backend-resolved responses by backend and status — must "
                 "reconcile with each backend's serve_requests_total")
        self._retries = self.metrics.counter(
            "gateway_retries_total",
            help="connection-failure re-dispatches onto a next backend")
        self._rollbacks = self.metrics.counter(
            "gateway_rollbacks_total",
            help="rolling deploys rolled back by the canary gate")
        self._transitions = self.metrics.counter(
            "gateway_membership_transitions_total",
            help="membership state changes by backend/prev/state")
        self._latency = self.metrics.histogram(
            "gateway_request_latency_seconds",
            help="gateway-side request latency (ingress to relay)")
        self._backends_gauge = self.metrics.gauge(
            "gateway_backends", help="fleet size by membership state")
        # the gateway's own sink, NOT observe's process-global active log:
        # a smoke (or test) may run an in-process serve service whose
        # telemetry must not interleave with the gateway's books
        self._elog = observe.EventLog(
            os.path.join(result_dir, "events.jsonl") if result_dir else None,
            run_id=run_id)
        backends = [Backend(url) for url in cfg.backends]
        self.registry = BackendRegistry(
            backends, cfg, chaos=self.chaos,
            on_transition=self._on_transition, on_cycle=self._on_cycle)
        self.router = Router(self.registry, cfg)
        self.autoscaler = Autoscaler(cfg, self.metrics, self._elog.event)
        self._started_mono: Optional[float] = None

    # ---------------- lifecycle ----------------

    def start(self) -> "Gateway":
        if self.result_dir:
            observe.write_run_manifest(
                self.result_dir, cfg=None, run_id=self.run_id,
                extra={"kind": "gateway",
                       "backends": [b.snapshot()["url"]
                                    for b in self.registry.backends()]})
        self._started_mono = time.monotonic()
        self._elog.event(
            "gateway.started",
            backends=[b.name for b in self.registry.backends()],
            probe_interval_s=float(self.cfg.probe_interval_s),
            fail_threshold=int(self.cfg.fail_threshold),
            ok_threshold=int(self.cfg.ok_threshold),
            inflight_cap=int(self.cfg.inflight_cap))
        self.registry.start()
        return self

    def stop(self) -> None:
        self.registry.stop()
        self._elog.event("gateway.stopped", **self._fleet_counts())
        if self.result_dir:
            self.metrics.dump(os.path.join(self.result_dir, "metrics.json"))
        self._elog.close()

    def __enter__(self) -> "Gateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------- roster administration (deploy API) ----------------

    def add_backend(self, url: str, weight: float = 0.0) -> Backend:
        """Register a canary backend. Default weight 0: it joins, warms,
        and becomes healthy WITHOUT taking traffic — the rolling deploy
        owns the traffic split."""
        return self.registry.add(Backend(url, weight=weight))

    def record_rollback(self, reason: str, canaries: List[str],
                        step: float, findings: List[str]) -> None:
        """The deploy's one typed rollback record (event + counter)."""
        self._rollbacks.inc()
        self._elog.event("gateway.rollback", reason=reason,
                         canaries=list(canaries), step=float(step),
                         findings=list(findings))

    def emit(self, name: str, **attrs) -> None:
        self._elog.event(name, **attrs)

    # ---------------- request path ----------------

    def handle_predict(self, body: bytes, trace_id: str):
        """Route one POST /predict body; returns the RouteResult whose
        payload already carries the gateway attribution block."""
        t0 = time.monotonic()
        self._elog.event("gateway.admit", trace=trace_id, opens_trace=True)
        result = self.router.route(body, trace_id)
        latency_s = time.monotonic() - t0
        status = str(result.payload.get("status", "internal_error"))
        self._requests.inc(status=status)
        if result.backend:
            self._backend_responses.inc(backend=result.backend,
                                        status=status)
        if result.retries:
            self._retries.inc(result.retries)
        self._latency.observe(latency_s)
        # terminal event CLOSES the trace — even when the backend died
        # mid-request and never wrote its own terminal record
        self._elog.event("gateway.request", trace=trace_id, status=status,
                         backend=result.backend, retries=result.retries,
                         latency_s=round(latency_s, 6))
        result.payload.setdefault("gateway", {})
        result.payload["gateway"].update(
            {"backend": result.backend, "retries": result.retries,
             "attempted": list(result.attempted)})
        return result

    # ---------------- membership/autoscale hooks ----------------

    def _on_transition(self, name: str, prev: str, new: str,
                       reason: str) -> None:
        self._transitions.inc(backend=name, prev=prev or "none", state=new)
        self._elog.event("gateway.membership", backend=name,
                         prev=prev or "none", state=new, reason=reason)

    def _on_cycle(self, snapshots: List[dict]) -> None:
        counts = {s: 0 for s in STATES}
        for snap in snapshots:
            counts[snap["state"]] = counts.get(snap["state"], 0) + 1
        for state, n in counts.items():
            self._backends_gauge.set(float(n), state=state)
        routable = [s for s in snapshots
                    if s["state"] in ROUTABLE_STATES and s["weight"] > 0.0]
        if routable:
            occ = sum(s["occupancy"] for s in routable) / len(routable)
            rej = sum(s["reject_rate"] for s in routable) / len(routable)
        else:
            occ, rej = 1.0, 1.0  # an empty fleet is a saturated fleet
        self.autoscaler.observe(occ, rej, len(routable))

    # ---------------- observability surfaces ----------------

    def _fleet_counts(self) -> dict:
        counts = {s: 0 for s in STATES}
        for b in self.registry.backends():
            counts[b.snapshot()["state"]] += 1
        return counts

    def healthz(self) -> dict:
        counts = self._fleet_counts()
        routable = counts["healthy"] + counts["degraded"]
        return {"status": "ok" if routable > 0 else "unhealthy",
                "role": "gateway", "routable": routable, "fleet": counts}

    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        uptime = (time.monotonic() - self._started_mono
                  if self._started_mono is not None else 0.0)
        return {
            "role": "gateway",
            "uptime_s": round(uptime, 3),
            "backends": [b.snapshot() for b in self.registry.backends()],
            "requests": {
                k: int(v) for k, v in observe.labeled_values(
                    snap, "gateway_requests_total", "status").items()},
            "retries": int(self.metrics.value("gateway_retries_total")),
            "rollbacks": int(self.metrics.value("gateway_rollbacks_total")),
            "autoscale_recommendation": self.metrics.value(
                "gateway_autoscale_recommendation"),
        }

    def describe(self) -> str:
        return json.dumps(self.stats(), indent=2, sort_keys=True)
