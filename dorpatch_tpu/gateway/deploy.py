"""Canary-gated rolling deploys over the live fleet.

Two AOT store versions coexist during a deploy: the stable group keeps
serving from the old store while the canary group (freshly booted serve
processes on the new store, registered via `Gateway.add_backend` at
weight 0) earns traffic in configured steps. At each step the deploy

1. shifts the traffic split (`canary_steps` fraction to the canary
   group, the rest to stable) by setting per-backend weights — the
   router's weighted power-of-two sampling does the rest;
2. soaks for `canary_hold_s`;
3. evaluates every canary's `GET /robustness` verdict (plus any findings
   an injected `finding_source` reports — the recert gate's DP305 AOT
   drift / DP400 robustness-regression rule ids).

Any DP305 or DP400 finding, a failing verdict, or an unreachable
`/robustness` probe rolls the fleet BACK automatically: canaries go to
weight 0 + `draining`, stable weights are restored, and the gateway
records the typed `gateway.rollback` event + counter. Surviving every
step promotes the canary: stable drains, the canary group takes weight
1.0, and `gateway.deploy.complete` is recorded.

Chaos hook: `poison_canary` (dorpatch_tpu.chaos) replaces ONE evaluation
result with a failing DP400 verdict at this module's evaluation site —
the smoke proves the rollback machinery without regressing a real model.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, List, Optional, Sequence

from dorpatch_tpu.gateway.membership import DRAINING, HEALTHY

#: Recert rule ids that gate a deploy (recert/gate.py vocabulary):
#: DP305 = AOT executable drift, DP400 = robustness regression.
BLOCKING_RULES = ("DP305", "DP400")


class RollingDeploy:
    def __init__(self, gateway, canaries: Sequence[str],
                 steps: Optional[Sequence[float]] = None,
                 hold_s: Optional[float] = None,
                 finding_source: Optional[Callable[[], List[str]]] = None):
        self.gateway = gateway
        self.canaries = list(canaries)
        cfg = gateway.cfg
        self.steps = tuple(steps if steps is not None else cfg.canary_steps)
        self.hold_s = float(hold_s if hold_s is not None
                            else cfg.canary_hold_s)
        self._finding_source = finding_source
        self._wake = threading.Event()  # interruptible soak timer

    # ---------------- driving ----------------

    def run(self, warm_timeout_s: float = 60.0) -> dict:
        gw = self.gateway
        reg = gw.registry
        stable = [b.name for b in reg.backends()
                  if b.name not in self.canaries
                  and b.snapshot()["state"] != DRAINING]
        gw.emit("gateway.deploy.begin", canaries=list(self.canaries),
                stable=stable, steps=[float(s) for s in self.steps],
                hold_s=self.hold_s)
        if not self._await_canaries_healthy(warm_timeout_s):
            result = self._rollback(stable, step=0.0,
                                    reason="canary never became healthy",
                                    findings=[])
            return result
        for fraction in self.steps:
            self._set_split(stable, float(fraction))
            gw.emit("gateway.deploy.step", fraction=float(fraction),
                    canaries=list(self.canaries))
            self._wake.wait(self.hold_s)
            bad_reason, findings = self._evaluate()
            if bad_reason:
                return self._rollback(stable, step=float(fraction),
                                      reason=bad_reason, findings=findings)
        return self._promote(stable)

    def _await_canaries_healthy(self, timeout_s: float) -> bool:
        """Wait (bounded, monotonic) until every canary probed healthy —
        a canary that cannot even pass admission must never take traffic."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            snaps = {b.name: b.snapshot()
                     for b in self.gateway.registry.backends()}
            if all(snaps.get(c, {}).get("state") == HEALTHY
                   for c in self.canaries):
                return True
            self._wake.wait(0.05)
        return False

    # ---------------- traffic split ----------------

    def _set_split(self, stable: List[str], fraction: float) -> None:
        reg = self.gateway.registry
        c_w = fraction / max(1, len(self.canaries))
        s_w = (1.0 - fraction) / max(1, len(stable))
        for name in self.canaries:
            reg.set_weight(name, c_w)
        for name in stable:
            reg.set_weight(name, s_w)

    # ---------------- the canary gate ----------------

    def _evaluate(self):
        """(reason, findings) — reason is \"\" when every canary passes.
        The chaos `poison_canary` site lives here: the verdict each canary
        actually answered is passed through it before judging."""
        findings: List[str] = []
        reason = ""
        chaos = getattr(self.gateway, "chaos", None)
        for name in self.canaries:
            b = self.gateway.registry.get(name)
            if b is None:
                return f"canary {name} left the roster", findings
            verdict = self._fetch_verdict(b.url)
            if chaos is not None and verdict is not None:
                verdict = chaos.poison_canary(verdict)
            if verdict is None:
                return (f"canary {name}: /robustness unreachable", findings)
            hit = [rule for rule in BLOCKING_RULES
                   if verdict.get("findings_by_rule", {}).get(rule)]
            if self._finding_source is not None:
                extra = [f for f in self._finding_source()
                         if f.split(":", 1)[0] in BLOCKING_RULES]
                hit.extend(f.split(":", 1)[0] for f in extra)
                findings.extend(extra)
            for rule in hit:
                for msg in (verdict.get("findings_by_rule", {})
                            .get(rule, []) or [f"{rule} reported"]):
                    findings.append(f"{rule}: {msg}")
            if hit:
                reason = (f"canary {name}: blocking finding(s) "
                          f"{sorted(set(hit))}")
                return reason, findings
            if verdict.get("status") != "ok":
                return (f"canary {name}: robustness verdict "
                        f"{verdict.get('status')!r}", findings)
        return "", findings

    def _fetch_verdict(self, url: str) -> Optional[dict]:
        """The canary's robustness verdict, 200 or 503 alike (a failing
        verdict IS data — only an unreachable canary returns None)."""
        req = urllib.request.Request(
            url + "/robustness", headers={"Accept": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.gateway.cfg.probe_timeout_s) as resp:
                return self._parse(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return self._parse(e.read())
            except OSError:
                return None
        except (urllib.error.URLError, OSError):
            return None

    @staticmethod
    def _parse(raw: bytes) -> Optional[dict]:
        try:
            payload = json.loads(raw or b"{}")
        except ValueError:
            return None
        return payload if isinstance(payload, dict) else None

    # ---------------- outcomes ----------------

    def _rollback(self, stable: List[str], step: float, reason: str,
                  findings: List[str]) -> dict:
        reg = self.gateway.registry
        for name in self.canaries:
            reg.set_weight(name, 0.0)
            reg.set_state(name, DRAINING, reason="deploy rollback")
        for name in stable:
            reg.set_weight(name, 1.0)
        self.gateway.record_rollback(reason, self.canaries, step, findings)
        return {"outcome": "rolled_back", "reason": reason,
                "step": step, "findings": findings,
                "canaries": list(self.canaries), "stable": stable}

    def _promote(self, stable: List[str]) -> dict:
        reg = self.gateway.registry
        for name in self.canaries:
            reg.set_weight(name, 1.0)
        for name in stable:
            reg.set_weight(name, 0.0)
            reg.set_state(name, DRAINING, reason="deploy promoted")
        self.gateway.emit("gateway.deploy.complete",
                          canaries=list(self.canaries), stable=stable,
                          steps=[float(s) for s in self.steps])
        return {"outcome": "promoted", "canaries": list(self.canaries),
                "stable": stable}
