"""Signal-only autoscaling: recommendations, never actions.

The gateway cannot start or stop serve processes (that is an operator's
or an orchestrator's job), so this module emits SIGNALS an external
scaler can act on: a typed `gateway.autoscale` event into the gateway's
events.jsonl plus gauges in the metric registry, derived from fleet
occupancy and reject rate over a sliding window.

Policy (deliberately boring — the value is in the plumbing, not the
controller):

- scale **up** when mean occupancy over the window exceeds
  `autoscale_high_occupancy`, or the mean reject rate exceeds
  `autoscale_high_reject` (the fleet is shedding load);
- scale **down** when mean occupancy sits below
  `autoscale_low_occupancy` AND nothing was rejected in the window;
- otherwise steady.

A cooldown (`autoscale_cooldown_s`, monotonic-clock based — rule DP504)
separates consecutive recommendations so a noisy boundary cannot spam
the event log; the gauges update every cycle regardless.

All state is confined to the registry's prober thread (`observe()` is
called from the probe cycle only), so the class needs no lock.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Optional


class Autoscaler:
    def __init__(self, cfg, metrics, emit: Callable[..., None]):
        self._cfg = cfg
        self._metrics = metrics
        self._emit = emit  # emit(event_name, **attrs) -> events.jsonl
        self._window = collections.deque()  # (t_mono, occupancy, reject)
        self._last_fired = float("-inf")
        self._events = metrics.counter(
            "gateway_autoscale_events_total",
            help="scale recommendations emitted, by direction")
        self._reco = metrics.gauge(
            "gateway_autoscale_recommendation",
            help="current recommendation: 1 scale-up, -1 scale-down, "
                 "0 steady")
        self._occ = metrics.gauge(
            "gateway_fleet_occupancy_mean",
            help="fleet mean occupancy over the autoscale window")
        self._rej = metrics.gauge(
            "gateway_fleet_reject_rate_mean",
            help="fleet mean reject rate over the autoscale window")

    def observe(self, occupancy: float, reject_rate: float,
                routable: int) -> Optional[str]:
        """Fold one probe cycle's fleet means in; returns the direction
        (\"up\"/\"down\") when a recommendation fired this cycle."""
        cfg = self._cfg
        now = time.monotonic()
        self._window.append((now, float(occupancy), float(reject_rate)))
        horizon = now - cfg.autoscale_window_s
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()
        n = len(self._window)
        mean_occ = sum(o for _, o, _ in self._window) / n
        mean_rej = sum(r for _, _, r in self._window) / n
        self._occ.set(mean_occ)
        self._rej.set(mean_rej)
        if (mean_occ > cfg.autoscale_high_occupancy
                or mean_rej > cfg.autoscale_high_reject):
            direction = "up"
        elif mean_occ < cfg.autoscale_low_occupancy and mean_rej == 0.0:
            direction = "down"
        else:
            direction = "steady"
        self._reco.set({"up": 1.0, "down": -1.0}.get(direction, 0.0))
        if direction == "steady":
            return None
        if now - self._last_fired < cfg.autoscale_cooldown_s:
            return None
        self._last_fired = now
        self._events.inc(direction=direction)
        self._emit("gateway.autoscale", direction=direction,
                   occupancy=round(mean_occ, 4),
                   reject_rate=round(mean_rej, 4), routable=int(routable),
                   window_s=float(cfg.autoscale_window_s),
                   samples=n)
        return direction
