"""Load-aware request dispatch: power-of-two-choices, exactly-once.

Candidate selection never scans the fleet for the global minimum — it
weighted-samples TWO distinct routable backends (weights come from the
rolling deploy's traffic split) and dispatches to the less loaded of the
two (`Backend.score`: scraped occupancy + reject pressure + the
gateway's own inflight view). Power-of-two-choices gets within a
constant factor of the global scan's load balance without herding every
concurrent request onto the same momentarily-idle backend.

Failure semantics are the heart of the exactly-once story:

- **connection-level failures** (refused, reset, remote hung up before a
  status line) mean the backend never resolved the request — the router
  retries on a backend the request has NOT yet touched, up to
  `dispatch_retries` times.
- **anything with an HTTP status** — including 4xx/5xx — is an ANSWER:
  the backend admitted the request, so it is passed through verbatim and
  never re-dispatched (a retry could double-answer).
- **timeouts are never retried**: a timed-out backend may still be
  working on the request, and re-dispatching it would double-dispatch an
  admitted request. The caller gets a typed `deadline_exceeded`.

When no routable backend has a free inflight slot the router answers a
typed `FleetOverloaded` (503) — admission control, not queueing: the
gateway holds no queue of its own, backpressure lives in each backend's
bounded micro-batcher queue.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

from dorpatch_tpu.gateway.membership import (ROUTABLE_STATES, Backend,
                                             BackendRegistry)

#: Exception types that prove the request never reached a resolving
#: backend (safe to re-dispatch). A timeout is deliberately absent.
_CONNECTION_ERRORS = (ConnectionRefusedError, ConnectionResetError,
                      ConnectionAbortedError, BrokenPipeError,
                      http.client.RemoteDisconnected,
                      http.client.BadStatusLine)


@dataclasses.dataclass(frozen=True)
class FleetOverloaded:
    """Typed admission reject: every routable backend is saturated (or
    none is routable). Mirrors the serve-side `Overloaded` contract —
    clients back off and retry; nothing was dispatched anywhere."""

    status = "overloaded"
    routable: int
    backends: int
    inflight_cap: int

    def to_dict(self) -> dict:
        return {"status": self.status, "scope": "fleet",
                "routable": self.routable, "backends": self.backends,
                "inflight_cap": self.inflight_cap}


@dataclasses.dataclass(frozen=True)
class RouteResult:
    """One routed request's outcome: the HTTP code + JSON payload to
    relay, which backend answered (\"\" for gateway-local rejects), and
    the re-dispatch trail for attribution."""

    code: int
    payload: dict
    backend: str
    retries: int
    attempted: Tuple[str, ...]


class Router:
    def __init__(self, registry: BackendRegistry, cfg):
        self._registry = registry
        self._cfg = cfg
        self._lock = threading.Lock()
        # weighted-choice source; its draws are the only state the router
        # owns, guarded because every handler thread routes through here
        self._rng = random.Random(0x90A7E)  # guarded-by: self._lock

    # ---------------- selection ----------------

    def _pick(self, candidates: List[Backend]) -> List[Backend]:
        """Up to two distinct candidates, weighted-sampled by deploy
        weight, ordered best-score-first (the power-of-two comparison)."""
        snaps = [(b, max(0.0, b.snapshot()["weight"])) for b in candidates]
        snaps = [(b, w) for b, w in snaps if w > 0.0]
        if not snaps:
            return []
        if len(snaps) == 1:
            return [snaps[0][0]]
        with self._lock:
            first = self._weighted_draw(snaps)
            rest = [(b, w) for b, w in snaps if b is not first]
            second = self._weighted_draw(rest)
        cap = self._cfg.inflight_cap
        pair = sorted((first, second), key=lambda b: b.score(cap))
        return pair

    def _weighted_draw(self, snaps: List[Tuple[Backend, float]]) -> Backend:
        total = sum(w for _, w in snaps)
        x = self._rng.random() * total
        for b, w in snaps:
            x -= w
            if x <= 0.0:
                return b
        return snaps[-1][0]

    def _reserve(self, exclude: List[str]) -> Optional[Backend]:
        """Pick and atomically reserve an inflight slot on a backend the
        request has not touched. The post-pick fallback over the remaining
        candidates only covers the reservation race (a slot vanishing
        between snapshot and reserve) — selection itself stays O(2)."""
        candidates = [b for b in self._registry.routable()
                      if b.name not in exclude]
        cap = self._cfg.inflight_cap
        pair = self._pick(candidates)
        for b in pair:
            if b.begin_dispatch(cap):
                return b
        for b in candidates:
            if b not in pair and b.begin_dispatch(cap):
                return b
        return None

    # ---------------- dispatch ----------------

    def route(self, body: bytes, trace_id: str) -> RouteResult:
        cfg = self._cfg
        attempted: List[str] = []
        last_err = ""
        while len(attempted) < cfg.dispatch_retries + 1:
            b = self._reserve(attempted)
            if b is None:
                break
            attempted.append(b.name)
            try:
                outcome = self._post(b, body, trace_id)
            finally:
                b.end_dispatch()
            code, payload, conn_failed, err = outcome
            if not conn_failed:
                return RouteResult(code, payload, b.name,
                                   retries=len(attempted) - 1,
                                   attempted=tuple(attempted))
            last_err = err
        if not attempted:
            snaps = [b.snapshot() for b in self._registry.backends()]
            routable = sum(1 for s in snaps
                           if s["state"] in ROUTABLE_STATES
                           and s["weight"] > 0.0)
            reject = FleetOverloaded(routable=routable, backends=len(snaps),
                                     inflight_cap=cfg.inflight_cap)
            return RouteResult(503, reject.to_dict(), "", retries=0,
                               attempted=())
        # connection failures exhausted every retry (or the fleet): the
        # request was never resolved anywhere, so an internal_error is
        # honest — nothing to double-answer
        payload = {"status": "internal_error",
                   "reason": f"no backend completed the request "
                             f"(connection failures on "
                             f"{', '.join(attempted)}): {last_err}"}
        return RouteResult(500, payload, "", retries=len(attempted) - 1,
                           attempted=tuple(attempted))

    def _post(self, b: Backend, body: bytes, trace_id: str
              ) -> Tuple[int, dict, bool, str]:
        """(code, payload, connection_failed, error). Runs outside every
        lock (DP502); the inflight slot is held by the caller."""
        req = urllib.request.Request(
            b.url + "/predict", data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "X-Trace-Id": trace_id})
        try:
            with urllib.request.urlopen(
                    req, timeout=self._cfg.dispatch_timeout_s) as resp:
                return (resp.status,
                        self._parse(resp.read()), False, "")
        except urllib.error.HTTPError as e:
            # an answered non-2xx (overloaded/deadline/error): relay it
            try:
                payload = self._parse(e.read())
            except OSError:
                payload = {"status": "error",
                           "reason": f"backend answered http {e.code}"}
            return e.code, payload, False, ""
        except _CONNECTION_ERRORS as e:
            return 0, {}, True, f"{type(e).__name__}: {e}"
        except TimeoutError as e:
            return (504, {"status": "deadline_exceeded",
                          "reason": "backend dispatch timed out "
                                    "(not retried: the backend may still "
                                    "answer)",
                          "backend": b.name}, False, str(e))
        except urllib.error.URLError as e:
            reason = getattr(e, "reason", None)
            if isinstance(reason, _CONNECTION_ERRORS):
                return 0, {}, True, f"{type(reason).__name__}: {reason}"
            if isinstance(reason, TimeoutError):
                return (504, {"status": "deadline_exceeded",
                              "reason": "backend dispatch timed out "
                                        "(not retried: the backend may "
                                        "still answer)",
                              "backend": b.name}, False, str(reason))
            # unresolvable host / closed socket family: never admitted
            return 0, {}, True, f"URLError: {reason}"

    @staticmethod
    def _parse(raw: bytes) -> dict:
        try:
            payload = json.loads(raw or b"{}")
        except ValueError:
            return {"status": "error", "reason": "backend sent non-JSON"}
        if not isinstance(payload, dict):
            return {"status": "error", "reason": "backend sent non-object"}
        return payload
