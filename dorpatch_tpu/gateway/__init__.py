"""Horizontal serve fleet: a stdlib-only gateway over N serve processes.

The serve package scales *vertically* (a replica pool inside one
process, one accelerator); this package scales *horizontally*: each
backend is a whole `python -m dorpatch_tpu.serve` process (its own
device, its own AOT store generation, its own telemetry dir) and the
gateway is a separate, deliberately jax-free process that routes
`POST /predict` across them.

    gateway = Gateway(cfg.gateway, result_dir=...)
    with gateway, GatewayFrontend(gateway, port=cfg.gateway.port):
        ...                      # or: python -m dorpatch_tpu.gateway

Pieces (one module each):

- `membership.py` — probe-driven roster: joining → healthy ⇄ degraded →
  ejected → (re-admission hysteresis) → joining; `draining` for deploys.
- `router.py`     — power-of-two-choices dispatch, connection-failure
  retry on an untouched backend, typed fleet `Overloaded` admission.
- `deploy.py`     — canary-gated rolling deploys with automatic rollback
  on DP305/DP400 findings or a failing robustness verdict.
- `autoscale.py`  — signal-only scale recommendations (events + gauges).
- `http.py`       — the gateway's own /predict /healthz /stats /metrics.

Telemetry follows the standard contract (events.jsonl + metrics.json in
the gateway's run dir); `observe.report --fleet` joins the gateway's
books with every backend's and the client's, checking exactly-once
accounting end to end. Zero new jit entry points — the gateway never
imports jax.
"""

from dorpatch_tpu.gateway.autoscale import Autoscaler  # noqa: F401
from dorpatch_tpu.gateway.deploy import RollingDeploy  # noqa: F401
from dorpatch_tpu.gateway.http import GatewayFrontend  # noqa: F401
from dorpatch_tpu.gateway.membership import (  # noqa: F401
    Backend,
    BackendRegistry,
)
from dorpatch_tpu.gateway.router import (  # noqa: F401
    FleetOverloaded,
    RouteResult,
    Router,
)
from dorpatch_tpu.gateway.service import Gateway  # noqa: F401

__all__ = [
    "Autoscaler",
    "Backend",
    "BackendRegistry",
    "FleetOverloaded",
    "Gateway",
    "GatewayFrontend",
    "RollingDeploy",
    "RouteResult",
    "Router",
]
