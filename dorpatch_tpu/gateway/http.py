"""The gateway's own stdlib HTTP surface (mirrors `serve/http.py`).

Endpoints:

- `POST /predict` — the fleet ingress. The body is relayed verbatim to
  the chosen backend; the response comes back with the backend's own
  status mapping plus a `gateway` attribution block (which backend
  answered, how many re-dispatches). The request's trace id (caller's
  `X-Trace-Id` header, `trace_id` body field, or minted here) is
  forwarded to the backend in `X-Trace-Id`, so one id correlates the
  client's log line, the gateway's admit/terminal events, and the
  backend's serve telemetry — `observe.report --fleet` joins on it.
- `GET /healthz` — gateway liveness + fleet routability.
- `GET /stats`   — fleet roster snapshot (per-backend membership state,
  load signals, weights) + the gateway's own counters.
- `GET /metrics` — Prometheus text exposition of the gateway registry.

One handler thread per connection (`ThreadingHTTPServer`), all funneling
into `Gateway.handle_predict` — admission control is the router's typed
`FleetOverloaded`, not socket backlog.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dorpatch_tpu import observe


class _Handler(BaseHTTPRequestHandler):
    # set per-server via the factory in GatewayFrontend
    gateway = None

    def _send_json(self, code: int, payload: dict, headers=()) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in headers:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str) -> None:
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        if self.path == "/healthz":
            h = self.gateway.healthz()
            self._send_json(200 if h["status"] == "ok" else 503, h)
        elif self.path == "/stats":
            self._send_json(200, self.gateway.stats())
        elif self.path == "/metrics":
            self._send_text(200, self.gateway.metrics.render_text())
        else:
            self._send_json(404, {"status": "error",
                                  "reason": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 — http.server contract
        if self.path != "/predict":
            self._send_json(404, {"status": "error",
                                  "reason": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length) or b"{}"
            payload = json.loads(raw)
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, TypeError) as e:
            self._send_json(400, {"status": "error",
                                  "reason": f"bad request body: {e!r}"})
            return
        # same trace-id precedence as the serve front-end: header wins
        # over body field; minted here only when the caller sent neither
        trace_id = str(self.headers.get("X-Trace-Id", "")
                       or payload.get("trace_id", "")
                       or observe.new_trace_id())
        result = self.gateway.handle_predict(raw, trace_id)
        body = dict(result.payload)
        body["trace_id"] = trace_id
        self._send_json(result.code, body,
                        headers=(("X-Trace-Id", trace_id),))

    def log_message(self, fmt: str, *args) -> None:
        # route through observe (rule DP101: no bare prints); request-level
        # telemetry already lands in the gateway's events.jsonl
        pass


class GatewayFrontend:
    """Owns the listening socket + serve_forever thread; `port` reports
    the bound port (pass 0 to bind an ephemeral one for tests)."""

    def __init__(self, gateway, host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundHandler", (_Handler,), {"gateway": gateway})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "GatewayFrontend":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="gateway-http", daemon=True)
        self._thread.start()
        observe.log(f"gateway: http front-end on {self.host}:{self.port} "
                    f"(/predict /healthz /stats /metrics)")
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "GatewayFrontend":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
