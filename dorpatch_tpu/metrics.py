"""Final-report metrics (the reference's evaluation block,
`/root/reference/main.py:162-187`): clean/robust accuracy plus per-radius
acc@PC, certified-acc@PC and certified-ASR@PC, as structured data and as the
reference's printed report line."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


def _fmt_list(values: Sequence[float]) -> str:
    return ", ".join(f"{v:.2f}" for v in values)


def compute_metrics(
    preds_clean: np.ndarray,
    y: np.ndarray,
    preds_adv: np.ndarray,
    defense_results: Sequence,   # PatchCleanserResult per radius
    targets: Optional[np.ndarray] = None,
) -> Dict:
    """All final metrics. `targets` given -> targeted certified-ASR
    (prediction == target & certified); else untargeted (!= label & certified)."""
    acc_clean = float((preds_clean == y).mean() * 100)
    acc_robust = float((preds_adv == y).mean() * 100)

    acc_pc: List[float] = []
    cert_acc_pc: List[float] = []
    cert_asr_pc: List[float] = []
    for res in defense_results:
        p = res.predictions
        c = res.certifications
        acc_pc.append(float((p == y).mean() * 100))
        cert_acc_pc.append(float(((p == y) & c).mean() * 100))
        if targets is not None:
            cert_asr_pc.append(float(((p == targets) & c).mean() * 100))
        else:
            cert_asr_pc.append(float(((p != y) & c).mean() * 100))
    return {
        "clean_accuracy": acc_clean,
        "robust_accuracy": acc_robust,
        "acc_pc": acc_pc,
        "certified_acc_pc": cert_acc_pc,
        "certified_asr_pc": cert_asr_pc,
    }


def report_line(m: Dict) -> str:
    """The reference's single printed report line (`main.py:186-187`)."""
    return (
        "clean accuracy: {:.2f}%, robust accuracy:{:.2f}%, acc@PC:{:s}%, "
        "certified_ACC@PC:{:s}%, certified_ASR@PC:{:s}%".format(
            m["clean_accuracy"], m["robust_accuracy"], _fmt_list(m["acc_pc"]),
            _fmt_list(m["certified_acc_pc"]), _fmt_list(m["certified_asr_pc"]),
        )
    )
