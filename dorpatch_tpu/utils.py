"""Seed and device utilities (the reference's `set_device`/`set_random_seed`,
`/root/reference/utils.py:12-21`, re-thought for JAX).

The reference seeds four global RNGs and sets `CUDA_VISIBLE_DEVICES`. In this
framework randomness is *threaded*: every stochastic component takes an
explicit `jax.random` key (the attack carry holds its own split key on
device), so runs are reproducible under jit by construction. These helpers
cover the remaining host-side surface: numpy/python RNGs used by data
shuffling and target sampling, plus a device selector that maps the
reference's integer device flag onto the jax device list.
"""

from __future__ import annotations

import os
import random
from typing import Optional

import jax
import numpy as np


def enable_compilation_cache(cache_dir: Optional[str] = None) -> str:
    """Point XLA's persistent compilation cache at a shared on-disk dir.

    Compiles through the remote TPU tunnel cost minutes for Pallas-heavy
    programs; the cache keys on the optimized HLO + backend, so the repeated
    jobs this repo runs (bench children, chip-validation steps, the driver's
    round-end bench) pay that once. `JAX_COMPILATION_CACHE_DIR` in the env
    wins; the default lives outside the repo so artifacts/ stays textual.
    Safe to call repeatedly; returns the directory in effect."""
    d = (cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
         or os.path.join(os.path.expanduser("~"), ".cache", "dorpatch_xla"))
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # default min compile time is ~1 s; keep tiny programs out of the cache
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    return d


_GLOBAL_SEED = 1234  # last seed handed to set_global_seed (config default)


def set_global_seed(seed: int = 1234) -> jax.Array:
    """Seed host-side RNGs (python, numpy legacy) and return the root
    `PRNGKey` all device-side randomness should be split from. Also records
    the seed so `global_key` can re-derive the root key anywhere."""
    global _GLOBAL_SEED
    _GLOBAL_SEED = int(seed)
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def global_key(fold: int = 0) -> jax.Array:
    """Root PRNG key derived from the configured seed (the last
    `set_global_seed` call — the pipeline seeds it from `config.seed`).

    This is the sanctioned fallback for components that need a key but were
    not handed one: seeds must flow from the config (rule DP104,
    `dorpatch_tpu.analysis`), never from a hard-coded `PRNGKey(<int>)` that
    forks the run's seed universe. `fold` derives an independent stream per
    caller site (`jax.random.fold_in`)."""
    key = jax.random.PRNGKey(_GLOBAL_SEED)
    return jax.random.fold_in(key, fold) if fold else key


def select_device(device: str = "0") -> Optional[jax.Device]:
    """The reference's `--device` flag (`utils.py:12-13`): pick the default
    accelerator by index. The reference's CUDA_VISIBLE_DEVICES index is a
    per-host notion, so this indexes `jax.local_devices()` — under
    `jax.distributed`, `jax.devices()[0]` may belong to ANOTHER process,
    and pinning the default device there strands every eager output on a
    non-addressable buffer. Returns None (and changes nothing) when the
    index does not parse or is out of range — sharded runs address devices
    through the mesh instead."""
    try:
        idx = int(str(device).split(",")[0])
        dev = jax.local_devices()[idx]
    except (ValueError, IndexError):
        return None
    jax.config.update("jax_default_device", dev)
    return dev


def preds_margins(logits):
    """(argmax predictions int32, top-1/top-2 logit gaps float32) of a
    logits array over its last axis — THE escalation signal of the
    incremental certify engines (`models/vit.py`, `ops/stem_fold.py` share
    this one definition so the token and stem margin semantics cannot
    drift). Margins are read out in float32 regardless of the logits dtype:
    under the bf16 certify banks this is the single deliberate upcast at
    the program boundary (the dtype contract's "logits/margins read out in
    f32"), exempted from the DP208 promotion-leak lint by design."""
    import jax.numpy as jnp
    from jax import lax

    top2 = lax.top_k(logits, 2)[0].astype(jnp.float32)  # noqa: DP208
    return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
            top2[..., 0] - top2[..., 1])
