"""CLI (the reference's argparse surface, `/root/reference/main.py:8-41`,
plus backend/mesh/synthetic extensions).

Run:  python -m dorpatch_tpu.cli --dataset cifar10 --synthetic ...
"""

from __future__ import annotations

import argparse

from dorpatch_tpu.config import (AotConfig, AttackConfig, DefenseConfig,
                                 ExperimentConfig, FarmConfig, GatewayConfig,
                                 RecertConfig, ServeConfig)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="TPU-native DorPatch: distributed occlusion-robust "
        "adversarial patches vs certified defenses")
    # reference flags (`main.py:8-41`)
    p.add_argument("--device", default="0", help="accelerator selector (kept for CLI parity)")
    p.add_argument("--dataset", "-d", default="imagenet",
                   choices=["cifar10", "imagenet", "cifar100"])
    p.add_argument("--data_dir", default="/home/data/data")
    p.add_argument("--model_dir", default="pretrained_models/")
    p.add_argument("--base_arch", "-ba", default="resnetv2",
                   choices=["resnetv2", "vit", "resmlp", "resnet18",
                            "cifar_vit"])
    p.add_argument("--targeted", "-t", action="store_true")
    p.add_argument("--patch_budget", type=float, default=0.12)
    p.add_argument("--attack", "-a", default="DorPatch", choices=["DorPatch"])
    p.add_argument("-b", "--batch-size", type=int, default=1)
    p.add_argument("-e", "--epsilon", type=float, default=4.0, help="L2 bound")
    p.add_argument("--lr", "--learning-rate", type=float, default=0.01)
    p.add_argument("--num_patch", type=int, default=-1)
    p.add_argument("--dropout", type=int, default=2, choices=[0, 1, 2])
    p.add_argument("--density", type=float, default=1e-3)
    p.add_argument("--structured", type=float, default=1e-3)
    # extensions
    p.add_argument("--backend", default="jax-tpu", choices=["jax-tpu", "torch"])
    p.add_argument("--synthetic", action="store_true",
                   help="synthetic data (no dataset on disk needed)")
    p.add_argument("--data-source", default="auto",
                   choices=["auto", "disk", "synthetic", "procedural"],
                   help="image stream: 'procedural' = the learnable "
                   "generated task with genuine labels (trained-victim "
                   "runs); 'auto' follows --synthetic")
    p.add_argument("--num-batches", type=int, default=10)
    p.add_argument("--max-iterations", type=int, default=5000)
    p.add_argument("--sampling-size", type=int, default=128)
    p.add_argument("--basic-unit", type=int, default=7,
                   help="patch group cell size (reference hardcodes 7)")
    p.add_argument("--switch-iteration", type=int, default=500,
                   help="stage-0 untargeted->targeted switch iteration "
                        "(reference hardcodes 500); scale down with "
                        "--max-iterations on reduced budgets")
    p.add_argument("--sweep-interval", type=int, default=100,
                   help="full-universe failure-sweep cadence in iterations "
                        "(reference hardcodes 100)")
    p.add_argument("--failure-sampling-start", type=int, default=1000,
                   help="iteration from which mask sampling biases toward "
                        "the failure set (reference hardcodes 1000)")
    p.add_argument("--img-size", type=int, default=224)
    p.add_argument("--seed", type=int, default=1234)
    p.add_argument("--results-root", default="results")
    p.add_argument("--mesh-data", type=int, default=1)
    p.add_argument("--mesh-mask", type=int, default=1)
    p.add_argument("--trace-dir", default="",
                   help="write a jax.profiler trace of the run here")
    p.add_argument("--sanitize", action="store_true",
                   help="arm the runtime sanitizers: jax debug_nans, "
                        "log_compiles routed into observe events, and the "
                        "recompile-budget watchdog (fails the run when a "
                        "jitted entry point re-traces past its declared "
                        "budget); debugging runs only — costs throughput")
    p.add_argument("--no-metrics-log", action="store_true",
                   help="disable run telemetry (metrics JSONL, events "
                        "JSONL span log, heartbeats) in the results dir")
    p.add_argument("--hang-timeout", type=float, default=0.0,
                   help="seconds without telemetry progress before the "
                        "watchdog prints every process's last-known phase "
                        "and aborts (0 = disabled); must exceed the longest "
                        "single jitted block including its compile; "
                        "requires telemetry (no effect with --no-metrics-log)")
    p.add_argument("--heartbeat-interval", type=float, default=5.0,
                   help="seconds between heartbeat_<proc>.jsonl beats")
    p.add_argument("--carry-checkpoints", action="store_true",
                   help="orbax-checkpoint the optimizer carry every sweep "
                        "block (mid-stage crash recovery)")
    p.add_argument("--use-pallas", default="auto",
                   choices=["auto", "on", "off", "interpret"],
                   help="fused mask-fill kernel dispatch")
    p.add_argument("--compute-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="EOT forward+backward precision (carry stays float32)")
    p.add_argument("--remat", default="auto", choices=["auto", "on", "off"],
                   help="rematerialize the EOT forward in the backward "
                        "(memory for ~25%% step time; auto: only when the "
                        "masked batch exceeds the remat threshold)")
    p.add_argument("--gn-impl", default="auto",
                   choices=["auto", "flax", "pallas", "interpret", "jnp"],
                   help="GroupNorm+ReLU impl for ResNetV2 victims (auto: "
                        "fused Pallas kernel on single-chip TPU, flax "
                        "elsewhere — see ops/fused_gn.py)")
    p.add_argument("--dual", action="store_true",
                   help="second independent occlusion layer per EOT sample "
                        "(the reference's dormant dual branch, "
                        "attack.py:208-218, live here in both backends)")
    p.add_argument("--defense-n-patch", type=int, default=1, choices=[1, 2],
                   help="PatchCleanser mask-set patch count for the defense "
                        "bank (the reference always certifies n_patch=1; "
                        "2 = pair/triple mask sets, PatchCleanser.py:24-37)")
    p.add_argument("--prune", default="exact",
                   choices=["off", "exact", "consensus"],
                   help="double-masking certification scheduling: 'exact' "
                        "(default) runs the two-phase pruned path — "
                        "first-round table, then only the second-round "
                        "entries each verdict reads — with bit-identical "
                        "verdicts; 'consensus' additionally early-exits "
                        "first-round-unanimous images after 36 forwards "
                        "(weaker, consensus-only certificates); 'off' is "
                        "the exhaustive 666-forward parity oracle")
    p.add_argument("--no-prune", dest="prune", action="store_const",
                   const="off",
                   help="alias for --prune off (the exhaustive parity "
                        "oracle)")
    p.add_argument("--incremental", default="auto",
                   choices=["auto", "token", "token-exact", "mixer",
                            "mixer-exact", "stem", "off"],
                   help="mask-aware incremental masked forwards on the "
                        "pruned certify path: 'auto' (default) picks per "
                        "family — 'token-exact' for ViT victims "
                        "(token-pruned forwards over a clean KV cache, "
                        "per-mask cost ~ mask_tokens/T, plus re-running "
                        "images whose read entries sit within "
                        "--incremental-margin of the decision boundary "
                        "through the exhaustive program, so verdicts stay "
                        "bit-identical under the documented drift "
                        "tolerance), 'mixer-exact' for ResMLP victims "
                        "(dirty-row tracking through a skinny slice of "
                        "the token-mixing matmul, same margin contract), "
                        "or the exact conv masked-stem fold "
                        "('stem'); plain 'token'/'mixer' opt into "
                        "tolerance-contracted verdicts with no "
                        "escalation; 'off' = full masked forwards for "
                        "every scheduled entry")
    p.add_argument("--certify-dtype", default="float32",
                   choices=["float32", "bfloat16"],
                   help="certification sweep precision (the defense's "
                        "compute_dtype): 'bfloat16' runs the masked "
                        "forwards — phase-1 tables, pair audits, rows, "
                        "and the incremental engines — in bf16 with f32 "
                        "logit/margin readouts; images whose evaluated "
                        "entries come within --incremental-margin of the "
                        "argmax boundary re-certify through the f32 "
                        "exhaustive program, so verdicts never weaken "
                        "(the token-exact escalation law)")
    p.add_argument("--incremental-margin", type=float, default=0.5,
                   help="token/mixer-exact escalation threshold: top-2 "
                        "logit gap "
                        "below which an incremental table entry is "
                        "distrusted and its image re-certified through the "
                        "exhaustive program")
    # serving (`python -m dorpatch_tpu.serve` reuses this parser)
    p.add_argument("--serve-port", type=int, default=8700,
                   help="HTTP front-end port for the certified-inference "
                        "service (0 = ephemeral)")
    p.add_argument("--serve-max-batch", type=int, default=8,
                   help="largest serving micro-batch; shape buckets are "
                        "data.batch_buckets(max_batch), e.g. 8 -> 1/8")
    p.add_argument("--serve-queue-depth", type=int, default=64,
                   help="backpressure bound: requests past this queue depth "
                        "are rejected with a typed Overloaded response")
    p.add_argument("--serve-deadline-ms", type=float, default=2000.0,
                   help="default per-request latency budget; the batcher "
                        "flushes a partial batch once half of it is spent")
    p.add_argument("--serve-replicas", type=int, default=1,
                   help="replica pool size: worker loops sharing the one "
                        "micro-batcher queue, each owning an independent "
                        "jitted program bank; a supervisor quarantines and "
                        "restarts sick replicas (serve/pool.py)")
    p.add_argument("--serve-max-restarts", type=int, default=2,
                   help="restarts a quarantined replica gets (AOT warm "
                        "boot when --aot-cache is set) before it retires "
                        "and the pool degrades to reduced capacity")
    p.add_argument("--serve-restart-backoff-base", type=float, default=0.5,
                   help="replica restart backoff base seconds (shared "
                        "backoff.retry_delay: base * 2^(n-1), capped, "
                        "deterministic jitter)")
    p.add_argument("--serve-restart-backoff-cap", type=float, default=30.0,
                   help="replica restart backoff cap seconds")
    p.add_argument("--serve-replica-stale-s", type=float, default=0.0,
                   help="missed-beat staleness window before the "
                        "supervisor declares a replica wedged (0 = derive "
                        "from --serve-deadline-ms); raise it above the "
                        "slowest legitimate batch — replicas beat only at "
                        "batch boundaries, so a window shorter than one "
                        "batch false-positives a healthy replica as "
                        "wedged (first-execution batches on a cold, slow "
                        "host are the usual trap)")
    # AOT executable store (`python -m dorpatch_tpu.aot build` writes it;
    # serve/farm warm-boot from it — README "AOT executable store")
    p.add_argument("--aot-cache", default="",
                   help="AOT executable store directory: serve boots by "
                        "deserializing pre-compiled executables keyed by "
                        "the baseline fingerprints instead of tracing "
                        "('' = disabled)")
    p.add_argument("--aot", default="off",
                   choices=["off", "auto", "strict"],
                   help="warm-boot mode: 'auto' compiles-and-rewrites the "
                        "store on any miss (fingerprint/topology drift, "
                        "corrupt blob — never serves stale); 'strict' is "
                        "the deploy mode, failing boot on any miss so a "
                        "fleet restart either comes up warm with zero "
                        "traces or visibly refuses")
    # continuous re-certification (`python -m dorpatch_tpu.recert` runs the
    # scheduler; serve consults its published verdict at boot)
    p.add_argument("--recert-dir", default="",
                   help="recert directory holding the scheduler's published "
                        "robustness verdict (recert_verdict.json); enables "
                        "GET /robustness on the serve front-end "
                        "('' = no robustness surface)")
    p.add_argument("--recert-baseline", default="",
                   help="robustness baseline file override ('' = the "
                        "package's recert/robustness_baseline.json)")
    p.add_argument("--require-recert", default="off",
                   choices=["off", "warn", "strict"],
                   help="serve-boot robustness gate against the recert "
                        "verdict: 'warn' serves on a failing/stale verdict "
                        "but reports it (canary mode); 'strict' is the "
                        "deploy mode — boot refuses serving-ready with a "
                        "typed error unless the verdict exists and is ok, "
                        "so a fleet never serves silently-uncertified "
                        "(mirrors --aot strict)")
    # fleet gateway (`python -m dorpatch_tpu.gateway` routes POST /predict
    # across N serve processes; README "Fleet gateway")
    p.add_argument("--gateway-backends", default="",
                   help="comma-joined backend base URLs "
                        "(http://host:port) the gateway fronts; each is a "
                        "`python -m dorpatch_tpu.serve` process")
    p.add_argument("--gateway-port", type=int, default=8800,
                   help="gateway bind port (0 = ephemeral)")
    p.add_argument("--gateway-probe-interval", type=float, default=1.0,
                   help="per-backend health-probe cadence seconds "
                        "(/healthz + /stats + /robustness, jittered)")
    p.add_argument("--gateway-fail-threshold", type=int, default=3,
                   help="consecutive probe failures before a backend is "
                        "ejected from routing")
    p.add_argument("--gateway-ok-threshold", type=int, default=2,
                   help="consecutive probe successes before an ejected "
                        "backend is re-admitted (flap hysteresis)")
    p.add_argument("--gateway-inflight-cap", type=int, default=32,
                   help="per-backend concurrent dispatches before the "
                        "gateway answers typed Overloaded (503)")
    p.add_argument("--gateway-canary-steps", default="0.1,0.5,1.0",
                   help="rolling-deploy traffic fractions the canary group "
                        "is stepped through (comma-joined floats)")
    p.add_argument("--gateway-canary-hold", type=float, default=2.0,
                   help="soak seconds per canary step before evaluating "
                        "its robustness verdict")
    # farm (`python -m dorpatch_tpu.farm` shares these defaults; setting
    # them here persists them into the config record a spec's `base` carries)
    p.add_argument("--farm-lease-ttl", type=float, default=60.0,
                   help="attack-sweep farm: heartbeat staleness (seconds) "
                        "after which a worker's leased jobs are reclaimable "
                        "by survivors; must exceed both the worker "
                        "heartbeat interval and the longest gap between "
                        "attack-block boundaries (lease renewal points)")
    p.add_argument("--farm-max-attempts", type=int, default=3,
                   help="attack-sweep farm: per-job attempt cap across "
                        "transient retries and crash reclaims")
    p.add_argument("--farm-backoff-base", type=float, default=2.0,
                   help="attack-sweep farm: transient retry delay base "
                        "(base * 2^(attempt-1), capped, plus deterministic "
                        "per-job jitter)")
    p.add_argument("--chaos", default="",
                   help="deterministic fault injection (smoke/recovery "
                        "testing; dorpatch_tpu.chaos): comma-joined list. "
                        "Farm faults: crash_block, ckpt_raise, "
                        "wedge_heartbeat, enospc_events. Serve faults "
                        "(python -m dorpatch_tpu.serve): wedge_dispatch, "
                        "raise_in_worker, wedge_heartbeat, kill_backend. "
                        "Gateway faults (python -m dorpatch_tpu.gateway): "
                        "wedge_probe, poison_canary")
    p.add_argument("--remat-policy", default="full",
                   choices=["full", "conv", "dots"],
                   help="what an active remat recomputes: full = the whole "
                        "forward; conv = keep conv outputs, replay only the "
                        "normalize chains (ResNetV2); dots = keep matmul "
                        "outputs (ViT/ResMLP)")
    return p


def config_from_args(args: argparse.Namespace) -> ExperimentConfig:
    # NOTE: "on"/"interpret" are legal under a mesh: the Pallas kernel runs
    # per-shard via shard_map (ops.masked_fill._sharded_masked_fill_fn), so
    # GSPMD opacity is no longer a concern; shapes the mesh does not divide
    # fall back to the partitionable XLA path automatically.
    attack = AttackConfig(
        patch_budget=args.patch_budget,
        targeted=args.targeted,
        lr=args.lr,
        max_iterations=args.max_iterations,
        switch_iteration=args.switch_iteration,
        sweep_interval=args.sweep_interval,
        failure_sampling_start=args.failure_sampling_start,
        basic_unit=args.basic_unit,
        dropout=args.dropout,
        sampling_size=args.sampling_size,
        density=args.density,
        structured=args.structured,
        eps=args.epsilon,
        num_patch=args.num_patch,
        dual=args.dual,
        use_pallas=args.use_pallas,
        compute_dtype=args.compute_dtype,
        remat=args.remat,
        remat_policy=args.remat_policy,
    )
    return ExperimentConfig(
        dataset=args.dataset,
        data_dir=args.data_dir,
        model_dir=args.model_dir,
        base_arch=args.base_arch,
        attack_name=args.attack,
        batch_size=args.batch_size,
        num_batches=args.num_batches,
        seed=args.seed,
        backend=args.backend,
        device=args.device,
        results_root=args.results_root,
        synthetic_data=args.synthetic,
        data_source=args.data_source,
        img_size=args.img_size,
        gn_impl=args.gn_impl,
        mesh_data=args.mesh_data,
        mesh_mask=args.mesh_mask,
        metrics_log=not args.no_metrics_log,
        sanitize=args.sanitize,
        trace_dir=args.trace_dir,
        hang_timeout=args.hang_timeout,
        heartbeat_interval=args.heartbeat_interval,
        carry_checkpoints=args.carry_checkpoints,
        attack=attack,
        defense=DefenseConfig(use_pallas=args.use_pallas,
                              n_patch=args.defense_n_patch,
                              prune=args.prune,
                              incremental=args.incremental,
                              incremental_margin=args.incremental_margin,
                              compute_dtype=args.certify_dtype),
        serve=ServeConfig(port=args.serve_port,
                          max_batch=args.serve_max_batch,
                          max_queue_depth=args.serve_queue_depth,
                          deadline_ms=args.serve_deadline_ms,
                          replicas=args.serve_replicas,
                          max_restarts=args.serve_max_restarts,
                          restart_backoff_base=args.serve_restart_backoff_base,
                          restart_backoff_cap=args.serve_restart_backoff_cap,
                          replica_stale_s=args.serve_replica_stale_s,
                          chaos=args.chaos),
        farm=FarmConfig(lease_ttl=args.farm_lease_ttl,
                        max_attempts=args.farm_max_attempts,
                        backoff_base=args.farm_backoff_base,
                        chaos=args.chaos),
        aot=AotConfig(cache_dir=args.aot_cache, mode=args.aot),
        recert=RecertConfig(dir=args.recert_dir,
                            baseline_file=args.recert_baseline,
                            require=args.require_recert),
        gateway=GatewayConfig(
            backends=tuple(b for b in args.gateway_backends.split(",") if b),
            port=args.gateway_port,
            probe_interval_s=args.gateway_probe_interval,
            fail_threshold=args.gateway_fail_threshold,
            ok_threshold=args.gateway_ok_threshold,
            inflight_cap=args.gateway_inflight_cap,
            canary_steps=tuple(float(s) for s in
                               args.gateway_canary_steps.split(",") if s),
            canary_hold_s=args.gateway_canary_hold,
            chaos=args.chaos),
    )


def main(argv=None):
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    from dorpatch_tpu.pipeline import run_experiment

    return run_experiment(cfg)


if __name__ == "__main__":
    main()
