"""PatchCleanser double-masking certification, TPU-native.

Reimplements the reference defense (`/root/reference/defenses/PatchCleanser.py:62-118`)
as one jitted program per (model, mask-family): a `lax.scan` over mask chunks
computes all one- and two-masked predictions, and the two-round
decision/certification logic runs as pure jnp on the `[36]`/`[630]` prediction
tables — batched over images, no per-image Python loops.

Key identity that removes the reference's data-dependent second round
(`PatchCleanser.py:85-90`): masking twice equals double-masking,
`mask_j(mask_i(img)) == mask_{(i,j)}(img)` (both leave `img` where both masks
keep and `fill` elsewhere). Hence every second-round prediction is already in
the 630-entry double-masked table (diagonal = the one-masked prediction,
since masking is idempotent), and the whole procedure needs exactly
36 + 630 = 666 forwards per image per radius — always the certify=True cost,
which is how the reference driver invokes it (`/root/reference/main.py:151`).

Tie-breaking notes (documented deviations, metric-neutral):
- Majority label on count ties: smallest label with the maximal count. The
  reference takes `labels[counts.argmax()]` over `torch.unique(sorted=False)`
  output, whose order is implementation-defined.
- If several minority one-masked images pass the unanimity recovery check
  with different labels (impossible for an actual R-covered patch, per the
  PatchCleanser paper's Lemma 1), the reference keeps the last success in an
  implementation-defined label order; we keep the success with the largest
  mask index.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dorpatch_tpu import data as data_lib
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu import observe
from dorpatch_tpu import ops
from dorpatch_tpu.config import DefenseConfig


class PatchCleanserRecord(NamedTuple):
    """Per-image verdict (reference `PatchCleanserRecord`, `PatchCleanser.py:121-126`)."""

    prediction: int
    certification: bool
    preds_1: np.ndarray  # [M] one-masked predictions
    preds_2: np.ndarray  # [P] double-masked predictions


class PatchCleanserResult:
    """Batch aggregation (reference `PatchCleanserResult`, `PatchCleanser.py:129-134`)."""

    def __init__(self, records: Sequence[PatchCleanserRecord]):
        self.predictions = np.stack([r.prediction for r in records])
        self.certifications = np.stack([r.certification for r in records])
        self.predictions_1 = np.stack([r.preds_1 for r in records])
        self.predictions_2 = [r.preds_2 for r in records]


def plan_chunks(n: int, chunk_size: int, mask_axis: int = 1):
    """Split an n-long mask axis into (n_chunks, chunk) with chunk <=
    chunk_size (hard memory bound), minimal padding, and — when possible —
    chunk divisible by `mask_axis` (the mesh's mask-axis size, so the
    sharded Pallas fill keeps its fast path). See `masked_predictions`."""
    m = mask_axis if chunk_size >= mask_axis else 1
    quantum = (chunk_size // m) * m              # largest multiple of m <= bound
    n_chunks = -(-n // quantum) if n else 0
    chunk = m * -(-n // (m * n_chunks)) if n_chunks else chunk_size
    return n_chunks, chunk


def masked_predictions(
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    imgs: jax.Array,
    rects: jax.Array,
    chunk_size: int,
    fill: float = 0.5,
    use_pallas: str = "auto",
    mesh=None,
) -> jax.Array:
    """Predictions under every mask in `rects`: `[B,H,W,C] x [N,K,4] -> [B,N]`.

    A `lax.scan` over chunks of the mask axis bounds live memory at
    `B * chunk_size` images while keeping each forward a large MXU-friendly
    batch (the reference's chunked sweeps, `PatchCleanser.py:102-112`,
    `attack.py:384-406`, but compiled as one program). The mask-apply is the
    fused `ops.masked_fill` (Pallas on TPU).

    `chunk_size` is a hard upper bound (its B*chunk_size live-memory
    contract is never exceeded): the mask axis is split into the fewest
    chunks that respect it, then the chunks are equalized so padding masks
    (whose forwards are wasted work) are minimized — e.g. the 666-mask
    certification sweep at chunk_size=128 runs as 6x111 (zero padding)
    instead of 6x128 (15% padded forwards). On a multi-device mesh the
    equalization quantizes to multiples of the mask-axis size so the
    sharded Pallas fill stays on its fast path
    (`ops.masked_fill._mesh_divides`); if chunk_size is smaller than the
    mask axis, the unquantized split is kept (the fill falls back to the
    partitionable XLA path rather than exceeding the memory bound).
    """
    n = rects.shape[0]
    m = 1
    if mesh is not None and getattr(mesh, "devices", None) is not None \
            and mesh.devices.size > 1:
        m = dict(mesh.shape).get("mask", 1)
    n_chunks, chunk_size = plan_chunks(n, chunk_size, m)
    pad = n_chunks * chunk_size - n
    rects_p = jnp.concatenate(
        [jnp.asarray(rects, jnp.int32),
         jnp.zeros((pad,) + rects.shape[1:], jnp.int32)], axis=0
    ).reshape(n_chunks, chunk_size, *rects.shape[1:])
    batch = imgs.shape[0]

    def body(carry, chunk_rects):
        xm = ops.masked_fill(imgs, chunk_rects, fill, use_pallas, mesh=mesh)
        logits = apply_fn(params, xm.reshape((-1,) + imgs.shape[1:]))
        return carry, jnp.argmax(logits, axis=-1).reshape(batch, chunk_size)

    _, preds = jax.lax.scan(body, None, rects_p)
    return jnp.moveaxis(preds, 0, 1).reshape(batch, -1)[:, :n]


def _second_round_index_grid(num_masks: int) -> np.ndarray:
    """`grid[i, j]` = index into the pair table for {i, j} (diagonal -> 0,
    patched up separately since mask_i(mask_i(x)) == mask_i(x))."""
    grid = np.zeros((num_masks, num_masks), dtype=np.int32)
    for i in range(num_masks):
        for j in range(num_masks):
            if i != j:
                a, b = min(i, j), max(i, j)
                grid[i, j] = masks_lib.pair_index(num_masks, a, b)
    return grid


def double_masking_verdict(
    preds_1: jax.Array,
    preds_2: jax.Array,
    num_masks: int,
    num_classes: int,
):
    """The two-round PatchCleanser decision + certification, pure jnp.

    preds_1 `[B, M]`, preds_2 `[B, C(M,2)]` -> (pred `[B]`, certified `[B]`).

    Round 1 (`PatchCleanser.py:70-79`): unanimous one-masked predictions give
    the output label, certified iff every double-masked prediction agrees.
    Round 2 (`PatchCleanser.py:81-90`): otherwise, a minority one-masked image
    whose own 36 second-round predictions unanimously keep its label wins;
    else the majority label stands. Never certified on disagreement.
    """
    grid = jnp.asarray(_second_round_index_grid(num_masks))  # [M, M]

    counts = jnp.sum(jax.nn.one_hot(preds_1, num_classes, dtype=jnp.int32), axis=1)
    majority = jnp.argmax(counts, axis=-1).astype(preds_1.dtype)  # [B]

    unanimous = jnp.all(preds_1 == preds_1[:, :1], axis=1)
    cert_consistent = jnp.all(preds_2 == majority[:, None], axis=1)
    certified = unanimous & cert_consistent

    # Second-round table [B, M, M]: row i = predictions of mask_i-masked image
    # under every second mask j (diagonal = preds_1[:, i]).
    second = jnp.take_along_axis(
        preds_2[:, None, :].repeat(num_masks, 1), grid[None], axis=2
    )
    eye = jnp.eye(num_masks, dtype=bool)[None]
    second = jnp.where(eye, preds_1[:, :, None], second)

    is_minority = preds_1 != majority[:, None]  # [B, M]
    row_unanimous = jnp.all(second == preds_1[:, :, None], axis=2)  # [B, M]
    recovers = is_minority & row_unanimous
    any_recovery = jnp.any(recovers, axis=1)
    # Largest successful mask index wins (see tie-breaking notes above).
    idx = jnp.argmax(
        jnp.where(recovers, jnp.arange(num_masks)[None], -1), axis=1
    )
    recovered_label = jnp.take_along_axis(preds_1, idx[:, None], axis=1)[:, 0]
    pred = jnp.where(unanimous, majority,
                     jnp.where(any_recovery, recovered_label, majority))
    return pred, certified


def double_masking_verdict_np(
    preds_1: np.ndarray,
    preds_2: np.ndarray,
    num_masks: int,
    num_classes: int,
):
    """Pure-numpy twin of `double_masking_verdict` for the torch oracle
    backend, which must not execute jax ops (in production environments any
    jnp op initializes — and claims — the accelerator backend). Equivalence
    with the jnp implementation is asserted by
    `tests/test_torch_backend.py::test_verdict_np_matches_jnp` on random
    tables, so the decision logic cannot drift silently."""
    preds_1 = np.asarray(preds_1)
    preds_2 = np.asarray(preds_2)
    grid = _second_round_index_grid(num_masks)  # [M, M]
    b = preds_1.shape[0]

    counts = np.zeros((b, num_classes), np.int32)
    np.add.at(counts, (np.arange(b)[:, None], preds_1), 1)
    majority = counts.argmax(axis=-1).astype(preds_1.dtype)

    unanimous = (preds_1 == preds_1[:, :1]).all(axis=1)
    cert_consistent = (preds_2 == majority[:, None]).all(axis=1)
    certified = unanimous & cert_consistent

    second = preds_2[:, grid]  # [B, M, M]
    eye = np.eye(num_masks, dtype=bool)[None]
    second = np.where(eye, preds_1[:, :, None], second)

    is_minority = preds_1 != majority[:, None]
    row_unanimous = (second == preds_1[:, :, None]).all(axis=2)
    recovers = is_minority & row_unanimous
    any_recovery = recovers.any(axis=1)
    idx = np.where(recovers, np.arange(num_masks)[None], -1).argmax(axis=1)
    recovered_label = preds_1[np.arange(b), idx]
    pred = np.where(unanimous, majority,
                    np.where(any_recovery, recovered_label, majority))
    return pred, certified


@dataclasses.dataclass
class PatchCleanser:
    """One certifier per mask family (reference `PatchCleanser`,
    `PatchCleanser.py:62-118`): `robust_predict` over image batches, fully
    jitted; `collect` aggregates records as the reference does."""

    apply_fn: Callable[[Any, jax.Array], jax.Array]
    spec: masks_lib.MaskSpec
    config: DefenseConfig = dataclasses.field(default_factory=DefenseConfig)
    result: Any = None
    # optional (data, mask) mesh: keeps the fused Pallas mask-fill sharded
    # on multi-chip meshes (see ops.masked_fill)
    mesh: Any = None
    # declared trace budget for the jitted 666-mask sweep: one bucket per
    # distinct image-batch size (the driver's correctness filter makes B
    # dynamic). Enforced only under --sanitize (analysis/sanitize.py).
    recompile_budget: Any = None

    def __post_init__(self):
        singles, doubles = masks_lib.mask_sets(self.spec)
        self._num_singles = singles.shape[0]
        k = max(singles.shape[1], doubles.shape[1])
        self._rects = jnp.asarray(
            np.concatenate(
                [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)], axis=0
            )
        )

        def _predict(params, imgs, num_classes: int):
            preds = masked_predictions(
                self.apply_fn, params, imgs, self._rects,
                self.config.chunk_size, self.config.mask_fill,
                self.config.use_pallas, mesh=self.mesh,
            )
            p1 = preds[:, : self._num_singles]
            p2 = preds[:, self._num_singles:]
            pred, certified = double_masking_verdict(
                p1, p2, self._num_singles, num_classes)
            return pred, certified, p1, p2

        out_shardings = None
        if self.mesh is not None:
            # replicated outputs: the [B]/[B,M] verdict tables must be
            # host-addressable on EVERY process of a multi-process run
            # (robust_predict materializes them with np.asarray)
            from jax.sharding import NamedSharding, PartitionSpec

            out_shardings = NamedSharding(self.mesh, PartitionSpec())
        # telemetry: first call = trace + XLA compile of the whole 666-mask
        # sweep; recorded as a `compile` event on the driver's EventLog
        self._predict = observe.timed_first_call(
            jax.jit(_predict, static_argnums=2, out_shardings=out_shardings),
            f"defense.predict.r{self.spec.patch_ratio}",
            recompile_budget=self.recompile_budget)

    def predict_tables(self, params, imgs: jax.Array, num_classes: int):
        """DEVICE-resident verdict tables `(pred [B], certified [B],
        preds_1 [B,M], preds_2 [B,P])` — dispatch-only, no host sync.
        The serving worker uses this to launch every certifier (and the
        clean forward) before materializing ANY result, so the programs
        overlap on device instead of serializing on per-radius transfers;
        `robust_predict` is this plus host marshalling."""
        return self._predict(params, imgs, num_classes)

    def robust_predict(
        self, params, imgs: jax.Array, num_classes: int,
        bucket_sizes: Optional[Sequence[int]] = None,
    ) -> List[PatchCleanserRecord]:
        """Batched robust prediction + certification; returns one record per
        image (the reference's per-image `robust_predict(img, certify=True)`,
        vmapped away).

        `bucket_sizes` (e.g. `data.batch_buckets(cfg.batch_size)`) rounds a
        ragged batch up to the nearest fixed bucket before hitting the jitted
        sweep, so the program compiles once per *bucket* instead of once per
        exact batch size — the correctness filter and final data batches
        otherwise force a fresh XLA compile for every distinct B. Padding
        repeats the first image; every verdict is a pure per-row function of
        the prediction tables, so padded rows cannot perturb real rows, and
        they are sliced out of the returned records."""
        n = int(imgs.shape[0])
        if bucket_sizes is not None and n:
            m = data_lib.bucket_batch(n, bucket_sizes)
            if m > n:
                fill = jnp.broadcast_to(imgs[:1], (m - n,) + imgs.shape[1:])
                imgs = jnp.concatenate([imgs, fill], axis=0)
        pred, certified, p1, p2 = self.predict_tables(params, imgs,
                                                      num_classes)
        pred, certified, p1, p2 = map(np.asarray, (pred, certified, p1, p2))
        return [
            PatchCleanserRecord(int(pred[b]), bool(certified[b]), p1[b], p2[b])
            for b in range(n)
        ]

    def reset(self):
        self.result = None

    def collect(self, records: Sequence[PatchCleanserRecord]):
        self.result = PatchCleanserResult(records)


def build_defenses(
    apply_fn, img_size: int, config: DefenseConfig = DefenseConfig(),
    mesh=None, recompile_budget=None,
) -> List[PatchCleanser]:
    """The reference driver's 4-radius defense bank (`/root/reference/main.py:61`)."""
    return [
        PatchCleanser(
            apply_fn,
            masks_lib.geometry(img_size, r, config.n_patch, config.num_mask_per_axis),
            config,
            mesh=mesh,
            recompile_budget=recompile_budget,
        )
        for r in config.ratios
    ]
