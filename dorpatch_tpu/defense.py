"""PatchCleanser double-masking certification, TPU-native.

Reimplements the reference defense (`/root/reference/defenses/PatchCleanser.py:62-118`)
as one jitted program per (model, mask-family): a `lax.scan` over mask chunks
computes all one- and two-masked predictions, and the two-round
decision/certification logic runs as pure jnp on the `[36]`/`[630]` prediction
tables — batched over images, no per-image Python loops.

Key identity that removes the reference's data-dependent second round
(`PatchCleanser.py:85-90`): masking twice equals double-masking,
`mask_j(mask_i(img)) == mask_{(i,j)}(img)` (both leave `img` where both masks
keep and `fill` elsewhere). Hence every second-round prediction is already in
the 630-entry double-masked table (diagonal = the one-masked prediction,
since masking is idempotent), and the whole procedure needs exactly
36 + 630 = 666 forwards per image per radius — always the certify=True cost,
which is how the reference driver invokes it (`/root/reference/main.py:151`).

Tie-breaking notes (documented deviations, metric-neutral):
- Majority label on count ties: smallest label with the maximal count. The
  reference takes `labels[counts.argmax()]` over `torch.unique(sorted=False)`
  output, whose order is implementation-defined.
- If several minority one-masked images pass the unanimity recovery check
  with different labels (impossible for an actual R-covered patch, per the
  PatchCleanser paper's Lemma 1), the reference keeps the last success in an
  implementation-defined label order; we keep the success with the largest
  mask index.

Pruned two-phase scheduling (`DefenseConfig.prune`, default "exact"): the
verdict is a sparse function of the 666-entry table, so the exhaustive sweep
overcomputes. Phase 1 runs the jitted 36-mask first-round table for the
whole batch; the host inspects the tiny `[B, 36]` label table (the path's
single designed sync) and dispatches only the second-round entries the
verdict reads: first-round *disagreeing* images exit the certification
audit immediately (a disagreement already kills the certificate — the
first-round table is part of the two-mask set via the masking-idempotence
diagonal) and schedule ragged (image, minority-mask) second-round rows for
the recovery check; *unanimous* images schedule the 630-pair certificate
audit. Both phase-2 worklists dispatch through a greedy bucket
decomposition (`data.bucket_plan`: full buckets largest-first, one padded
tail), so every call shape is a fixed bucket — the programs compile once
per bucket and never retrace, and padding waste is confined to the tail.
Verdicts are bit-identical to the exhaustive path by construction — every
skipped entry is provably unread. Per-image executed-forward counts land in
`PatchCleanserRecord.forwards`. `prune="consensus"` additionally lets
unanimous images skip the pair audit (36 forwards total, ~18x): their
certificate then asserts round-1 consensus only, which is the reference's
early-exit *inference* answer but a strictly weaker certificate — opt-in.

Incremental masked forwards (`DefenseConfig.incremental`, default "auto"):
pruning decides *which* table entries run; the incremental engines make
each surviving entry cheaper. Every scheduled entry's mask covers a small
contiguous window, so most of the victim's activations are identical to
the clean image's across all masks. The pruned-path programs
(phase1/pairs/rows) are swapped for engine-backed twins that share a
per-image clean-activation cache:

- ViT families ("token", `models.vit.TokenPrunedViT`): the clean per-block
  token activations are computed once; each masked entry recomputes only
  the mask-touched patch tokens (+ cls) with attention reading the clean
  KV cache for untouched positions — per-entry cost ~ dirty_tokens/(T+1),
  the fraction recorded in `PatchCleanserRecord.forward_equivalents`.
  Exact for each block given its inputs (in particular the final-block
  readout) but untouched tokens keep clean activations, so logits carry a
  small bounded drift; programs therefore also return top-2 logit margins,
  and "token-exact" re-runs any image whose evaluated entries come within
  `incremental_margin` of the argmax boundary through the exhaustive
  program — verdicts then stay bit-identical whenever the drift stays
  below that documented tolerance.
- ResMLP families ("mixer", `models.resmlp.MixerPrunedResMLP`): the only
  cross-token operator — Affine then the token-mixing Linear — is exactly
  linear, so each masked entry tracks only its dirty token rows and
  propagates their delta through a skinny `[dirty, dirty]` slice of the
  `[T, T]` mixing matmul against cached clean block inputs/mix outputs,
  then runs the channel MLP dense on the dirty rows alone; the mean-pool
  head is linear too, so clean logits plus a rank-S pooled delta finish
  the entry. Same contract as "token": exact per block given its inputs,
  frozen clean rows drift, margins returned, "mixer-exact" escalates.
- Conv families ("stem", `ops.stem_fold.StemFoldEngine`): the bias-free
  stem conv is linear, so the 36-mask first round folds `apply_masks`
  into per-mask delta convs over static windows scattered into one shared
  post-stem cache — algebraically exact, no tolerance; phase 2 keeps the
  standard programs (pair windows approach the full image).

Meshed certifiers (a `(data, mask)` mesh attached) run the SAME two-phase
schedule sharded: the fixed-shape 36-mask phase 1 shards over the mesh as
the exhaustive sweep always did (no ragged shapes there), the one designed
sync reads back the replicated `[B, 36]` label table, and phase-2
worklists are planned SHARD-LOCALLY — images split contiguously over the
data axis (matching `place_batch`'s block layout), each shard's worklist
is bucket-planned independently (`data.shard_bucket_plan`), and every
wave dispatches one `[S * bucket]` SPMD program call whose rows
interleave the shards' entries, gathered host-side and placed sharded
over the data axis. Shards whose worklist ran dry pad their slots with a
replicated owned row (discarded). Wave shapes depend only on the static
row-bucket ladder, never on the batch size or verdict mix — zero
recompiles — and since padding is excluded from every table read and
forward count, verdicts and per-image `forwards` stay bit-identical to
the single-chip pruned oracle. The incremental engines ride the same
shard-local schedule unchanged (their programs are pure jnp; GSPMD
propagates the data sharding through them).

bf16 certify bank (`DefenseConfig.compute_dtype="bfloat16"`, CLI
`--certify-dtype`): the pruned-path programs — phase1/pairs/rows and the
engine twins — sweep in bfloat16 (the forward-dominated certify path is
bandwidth-bound, so halved byte traffic is the win). The dtype contract:
params are cast once per weight tree (`PatchCleanser._cast_params`),
images are cast at the program boundary INSIDE the traced programs
(callers keep handing f32 batches, so jit cache keys, entrypoint
registrations and warmup placements never fork on dtype), and
preds/margins are read out in f32 (`utils.preds_margins`). Correctness
rides the margin-escalation law, generalized from "token-exact" to every
bf16 bank: all programs return top-2 logit margins, and any image whose
evaluated entries come within `incremental_margin` of the argmax boundary
re-certifies through the f32 exhaustive program — rounding can only flip
a label where the margin is small, and small-margin images are exactly
the ones escalated, so bf16 never weakens a verdict. Program names gain a
`.bf16` tag (`defense.phase1.bf16.r*`, composing with `.mesh`) so the
baseline tier prices both banks as distinct program sets.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dorpatch_tpu import data as data_lib
from dorpatch_tpu import masks as masks_lib
from dorpatch_tpu import observe
from dorpatch_tpu import ops
from dorpatch_tpu import utils
from dorpatch_tpu.config import DefenseConfig


#: Legal values of `DefenseConfig.prune` (see the module docstring).
PRUNE_MODES = ("off", "exact", "consensus")

#: Legal values of `DefenseConfig.incremental`: mask-aware incremental
#: masked forwards riding the pruned dispatch path. "auto" resolves per
#: victim family ("token" for ViT engines, "mixer" for ResMLP engines,
#: "stem" for conv engines, "off" where no engine exists); the "-exact"
#: variants of the margin families (token, mixer) add margin-gated
#: escalation to the exhaustive program so verdicts stay bit-identical
#: whenever the incremental path's logit drift stays below
#: `DefenseConfig.incremental_margin`.
INCREMENTAL_MODES = ("auto", "token", "token-exact", "mixer",
                     "mixer-exact", "stem", "off")

#: Sentinel for double-masked table entries the pruned path never evaluated
#: (provably unread by the verdict); `preds_2` slots hold labels >= 0 only
#: where a forward actually ran.
UNEVALUATED = -1


class PatchCleanserRecord(NamedTuple):
    """Per-image verdict (reference `PatchCleanserRecord`, `PatchCleanser.py:121-126`).

    `preds_2` entries are `UNEVALUATED` (-1) where the pruned scheduler
    proved the verdict never reads them. `forwards` counts the masked-table
    ENTRIES this image actually evaluated (bucket-padding waste excluded);
    -1 marks records written before forward accounting existed.
    `forward_equivalents` credits incremental entries fractionally: a
    token-pruned ViT forward that recomputes S of T+1 tokens costs
    S/(T+1) of a full forward, so the float is the image's true certify
    cost in full-forward units (== forwards on non-incremental paths;
    -1.0 on pre-incremental records)."""

    prediction: int
    certification: bool
    preds_1: np.ndarray  # [M] one-masked predictions
    preds_2: np.ndarray  # [P] double-masked predictions
    forwards: int = -1   # evaluated masked-table entries for this image
    forward_equivalents: float = -1.0  # fractional full-forward cost


class PatchCleanserResult:
    """Batch aggregation (reference `PatchCleanserResult`, `PatchCleanser.py:129-134`)."""

    def __init__(self, records: Sequence[PatchCleanserRecord]):
        self.predictions = np.stack([r.prediction for r in records])
        self.certifications = np.stack([r.certification for r in records])
        self.predictions_1 = np.stack([r.preds_1 for r in records])
        self.predictions_2 = [r.preds_2 for r in records]


def plan_chunks(n: int, chunk_size: int, mask_axis: int = 1):
    """Split an n-long mask axis into (n_chunks, chunk) with chunk <=
    chunk_size (hard memory bound), minimal padding, and — when possible —
    chunk divisible by `mask_axis` (the mesh's mask-axis size, so the
    sharded Pallas fill keeps its fast path). See `masked_predictions`."""
    m = mask_axis if chunk_size >= mask_axis else 1
    quantum = (chunk_size // m) * m              # largest multiple of m <= bound
    n_chunks = -(-n // quantum) if n else 0
    chunk = m * -(-n // (m * n_chunks)) if n_chunks else chunk_size
    return n_chunks, chunk


def masked_predictions(
    apply_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    imgs: jax.Array,
    rects: jax.Array,
    chunk_size: int,
    fill: float = 0.5,
    use_pallas: str = "auto",
    mesh=None,
    compute_dtype: str = "float32",
    with_margins: bool = False,
) -> jax.Array:
    """Predictions under every mask in `rects`: `[B,H,W,C] x [N,K,4] -> [B,N]`.

    A `lax.scan` over chunks of the mask axis bounds live memory at
    `B * chunk_size` images while keeping each forward a large MXU-friendly
    batch (the reference's chunked sweeps, `PatchCleanser.py:102-112`,
    `attack.py:384-406`, but compiled as one program). The mask-apply is the
    fused `ops.masked_fill` (Pallas on TPU).

    `chunk_size` is a hard upper bound (its B*chunk_size live-memory
    contract is never exceeded): the mask axis is split into the fewest
    chunks that respect it, then the chunks are equalized so padding masks
    (whose forwards are wasted work) are minimized — e.g. the 666-mask
    certification sweep at chunk_size=128 runs as 6x111 (zero padding)
    instead of 6x128 (15% padded forwards). On a multi-device mesh the
    equalization quantizes to multiples of the mask-axis size so the
    sharded Pallas fill stays on its fast path
    (`ops.masked_fill._mesh_divides`); if chunk_size is smaller than the
    mask axis, the unquantized split is kept (the fill falls back to the
    partitionable XLA path rather than exceeding the memory bound).

    `compute_dtype` ("float32" | "bfloat16") is the sweep precision:
    images are cast at the program boundary (here, inside the traced
    program — callers keep handing f32 batches so jit cache keys and
    warmup placements never fork on dtype) and the masked forwards run in
    that dtype end to end; `with_margins=True` additionally returns the
    top-2 logit margins `[B, N]`, read out in f32
    (`utils.preds_margins`) — the bf16 banks' escalation signal.
    """
    n = rects.shape[0]
    cdt = jnp.dtype(compute_dtype)
    if imgs.dtype != cdt:
        imgs = imgs.astype(cdt)
    m = 1
    if mesh is not None and getattr(mesh, "devices", None) is not None \
            and mesh.devices.size > 1:
        m = dict(mesh.shape).get("mask", 1)
    n_chunks, chunk_size = plan_chunks(n, chunk_size, m)
    pad = n_chunks * chunk_size - n
    rects_p = jnp.concatenate(
        [jnp.asarray(rects, jnp.int32),
         jnp.zeros((pad,) + rects.shape[1:], jnp.int32)], axis=0
    ).reshape(n_chunks, chunk_size, *rects.shape[1:])
    batch = imgs.shape[0]

    def body(carry, chunk_rects):
        xm = ops.masked_fill(imgs, chunk_rects, fill, use_pallas, mesh=mesh)
        logits = apply_fn(params, xm.reshape((-1,) + imgs.shape[1:]))
        if with_margins:
            preds, margins = utils.preds_margins(logits)
            return carry, (preds.reshape(batch, chunk_size),
                           margins.reshape(batch, chunk_size))
        return carry, jnp.argmax(logits, axis=-1).reshape(batch, chunk_size)

    _, out = jax.lax.scan(body, None, rects_p)

    def cat(t):
        return jnp.moveaxis(t, 0, 1).reshape(batch, -1)[:, :n]

    return (cat(out[0]), cat(out[1])) if with_margins else cat(out)


def _second_round_index_grid(num_masks: int) -> np.ndarray:
    """`grid[i, j]` = index into the pair table for {i, j} (diagonal -> 0,
    patched up separately since mask_i(mask_i(x)) == mask_i(x)). The
    pair-table view of `masks.second_round_table_indices`' combined-table
    grid — derived from it so the pair layout has one source of truth."""
    grid = masks_lib.second_round_table_indices(num_masks) - num_masks
    grid[np.eye(num_masks, dtype=bool)] = 0
    return grid


def double_masking_verdict(
    preds_1: jax.Array,
    preds_2: jax.Array,
    num_masks: int,
    num_classes: int,
):
    """The two-round PatchCleanser decision + certification, pure jnp.

    preds_1 `[B, M]`, preds_2 `[B, C(M,2)]` -> (pred `[B]`, certified `[B]`).

    Round 1 (`PatchCleanser.py:70-79`): unanimous one-masked predictions give
    the output label, certified iff every double-masked prediction agrees.
    Round 2 (`PatchCleanser.py:81-90`): otherwise, a minority one-masked image
    whose own 36 second-round predictions unanimously keep its label wins;
    else the majority label stands. Never certified on disagreement.
    """
    grid = jnp.asarray(_second_round_index_grid(num_masks))  # [M, M]

    counts = jnp.sum(jax.nn.one_hot(preds_1, num_classes, dtype=jnp.int32), axis=1)
    majority = jnp.argmax(counts, axis=-1).astype(preds_1.dtype)  # [B]

    unanimous = jnp.all(preds_1 == preds_1[:, :1], axis=1)
    cert_consistent = jnp.all(preds_2 == majority[:, None], axis=1)
    certified = unanimous & cert_consistent

    # Second-round table [B, M, M]: row i = predictions of mask_i-masked image
    # under every second mask j (diagonal = preds_1[:, i]).
    second = jnp.take_along_axis(
        preds_2[:, None, :].repeat(num_masks, 1), grid[None], axis=2
    )
    eye = jnp.eye(num_masks, dtype=bool)[None]
    second = jnp.where(eye, preds_1[:, :, None], second)

    is_minority = preds_1 != majority[:, None]  # [B, M]
    row_unanimous = jnp.all(second == preds_1[:, :, None], axis=2)  # [B, M]
    recovers = is_minority & row_unanimous
    any_recovery = jnp.any(recovers, axis=1)
    # Largest successful mask index wins (see tie-breaking notes above).
    idx = jnp.argmax(
        jnp.where(recovers, jnp.arange(num_masks)[None], -1), axis=1
    )
    recovered_label = jnp.take_along_axis(preds_1, idx[:, None], axis=1)[:, 0]
    pred = jnp.where(unanimous, majority,
                     jnp.where(any_recovery, recovered_label, majority))
    return pred, certified


def _majority_np(preds_1: np.ndarray, num_classes: int) -> np.ndarray:
    """Per-image majority label over the `[B, M]` first-round table, with
    `double_masking_verdict`'s tie-break (smallest label with the maximal
    count). THE host-side majority: `double_masking_verdict_np` and the
    pruned scheduler's `host_round1` both read it, so the pruned path's
    bit-parity contract cannot drift on the tie rule."""
    b = preds_1.shape[0]
    counts = np.zeros((b, num_classes), np.int64)
    np.add.at(counts, (np.arange(b)[:, None], preds_1), 1)
    return counts.argmax(axis=-1).astype(preds_1.dtype)


def double_masking_verdict_np(
    preds_1: np.ndarray,
    preds_2: np.ndarray,
    num_masks: int,
    num_classes: int,
):
    """Pure-numpy twin of `double_masking_verdict` for the torch oracle
    backend, which must not execute jax ops (in production environments any
    jnp op initializes — and claims — the accelerator backend). Equivalence
    with the jnp implementation is asserted by
    `tests/test_torch_backend.py::test_verdict_np_matches_jnp` on random
    tables, so the decision logic cannot drift silently."""
    preds_1 = np.asarray(preds_1)
    preds_2 = np.asarray(preds_2)
    grid = _second_round_index_grid(num_masks)  # [M, M]
    b = preds_1.shape[0]
    majority = _majority_np(preds_1, num_classes)

    unanimous = (preds_1 == preds_1[:, :1]).all(axis=1)
    cert_consistent = (preds_2 == majority[:, None]).all(axis=1)
    certified = unanimous & cert_consistent

    second = preds_2[:, grid]  # [B, M, M]
    eye = np.eye(num_masks, dtype=bool)[None]
    second = np.where(eye, preds_1[:, :, None], second)

    is_minority = preds_1 != majority[:, None]
    row_unanimous = (second == preds_1[:, :, None]).all(axis=2)
    recovers = is_minority & row_unanimous
    any_recovery = recovers.any(axis=1)
    idx = np.where(recovers, np.arange(num_masks)[None], -1).argmax(axis=1)
    recovered_label = preds_1[np.arange(b), idx]
    pred = np.where(unanimous, majority,
                    np.where(any_recovery, recovered_label, majority))
    return pred, certified


# ------------------------------------------------------- pruned scheduling


def host_round1(preds_1: np.ndarray, num_classes: int):
    """Host-side round-1 inspection of the tiny `[B, M]` first-round label
    table: (majority `[B]`, unanimous `[B]` bool). Majority comes from the
    shared `_majority_np`, so the tie-break matches the verdict functions
    by construction."""
    p1 = np.asarray(preds_1)
    majority = _majority_np(p1, num_classes)
    unanimous = (p1 == p1[:, :1]).all(axis=1)
    return majority, unanimous


def schedule_round2(p1: np.ndarray, majority: np.ndarray,
                    unanimous: np.ndarray, num_singles: int, num_pairs: int,
                    mode: str):
    """Decide, per image, which second-round entries the verdict reads.

    Returns `(need_pairs [B] bool, row_list)` where `row_list` is the
    ragged worklist of `(image, minority-mask)` second-round rows.

    - disagreeing images exit the certificate audit after round 1
      (certified=False is already decided) and need only their minority
      rows for the recovery check — M forwards per row. When an image has
      so many minority masks that its rows would cost more than the full
      pair table (k*M >= P, i.e. k >= 18 for the 36-mask family), it is
      routed through the pair program instead: pruning never exceeds the
      exhaustive forward count.
    - unanimous images need the full pair table for the certificate audit
      ("exact") or nothing at all ("consensus" — the weaker opt-in
      certificate; see the module docstring)."""
    minority = p1 != majority[:, None]                       # [B, M]
    k = minority.sum(axis=1)
    rows_cheaper = (~unanimous) & (k * num_singles < num_pairs)
    need_pairs = (~unanimous) & ~rows_cheaper
    if mode == "exact":
        need_pairs = need_pairs | unanimous
    row_list = [(int(b), int(i))
                for b in np.nonzero(rows_cheaper)[0]
                for i in np.nonzero(minority[b])[0]]
    return need_pairs, row_list


class _PrunedPending:
    """One in-flight pruned certification batch: created dispatch-only by
    `PatchCleanser.begin_pruned` (phase 1 launched, nothing synced),
    `schedule()` performs the path's single tiny host sync (the `[B, M]`
    first-round labels) and dispatches the phase-2 programs, `finalize()`
    materializes the phase-2 outputs and assembles the per-image records.
    The split lets the serving worker launch phase 1 for every radius
    before any sync, preserving cross-radius overlap on device."""

    def __init__(self, pc: "PatchCleanser", params, imgs, n: int,
                 num_classes: int, bucket_sizes, mode: str,
                 incremental: str = "off"):
        self.pc = pc
        self.params = params       # ORIGINAL tree: escalation runs f32
        # the bf16 banks dispatch phase 1/2 against the once-cast tree;
        # `_escalate` keeps the original so the oracle stays f32
        self.cparams = pc._cast_params(params)
        self.imgs = imgs           # device, possibly bucket-padded
        self.n = n                 # real (unpadded) image count
        self.num_classes = num_classes
        self.bucket_sizes = bucket_sizes
        self.mode = mode
        self.incr = incremental    # resolved incremental mode
        # phase 1: the incremental programs — and, under bf16, the
        # standard program too — return (preds, margins); the f32
        # standard program returns the bare [B_pad, M] prediction table
        if incremental != "off":
            self.t1, self.t1_margins = pc._phase1_incr(self.cparams, imgs)
        elif pc._bf16:
            self.t1, self.t1_margins = pc._phase1(self.cparams, imgs)
        else:
            self.t1, self.t1_margins = pc._phase1(self.cparams, imgs), None
        self._scheduled = False
        self.p1 = None
        self.m1 = None             # [n, M] phase-1 margins (incremental)
        self.majority = None
        self.unanimous = None
        self.pair_idx = np.zeros((0,), np.int64)
        self.row_list = []
        # phase-2 chunk bookkeeping: (device preds/(preds,margins), mapping)
        # where mapping names the REAL entries — [(table_row, image)] for
        # pair chunks, [(table_row, image, first_mask)] for row chunks.
        # Explicit row->entry maps keep finalize() identical across the
        # single-chip layout (real rows first, padding last) and the mesh
        # wave layout (shard s owns rows [s*bucket, (s+1)*bucket), padding
        # interleaved per shard).
        self.pair_chunks = []
        self.row_chunks = []

    def schedule(self) -> "_PrunedPending":
        """THE one designed host sync of the pruned path: materialize the
        tiny first-round label table, build the ragged worklist, dispatch
        phase 2. Idempotent."""
        if self._scheduled:
            return self
        self._scheduled = True
        pc = self.pc
        self.p1 = np.asarray(self.t1)[:self.n]
        self.majority, self.unanimous = host_round1(self.p1, self.num_classes)
        need_pairs, self.row_list = schedule_round2(
            self.p1, self.majority, self.unanimous,
            pc.num_first, pc.num_second, self.mode)
        self.pair_idx = np.nonzero(need_pairs)[0]

        # the margin families (token, mixer) share the engine program
        # shapes: pairs/rows return (preds, margins) and rows take
        # combined-table index rows
        rowsets = self.incr.split("-")[0] in ("token", "mixer")
        pairs_prog = pc._pairs_incr if rowsets else pc._pairs
        grid_full = np.asarray(pc._grid_full)
        if pc.mesh is not None:
            return self._schedule_mesh(pairs_prog, grid_full, rowsets)

        # Both worklists dispatch through a greedy bucket decomposition
        # (`data.bucket_plan`: full buckets largest-first, one padded tail)
        # rather than a single rounded-up call — a 34-entry worklist over
        # buckets (1, 8, 32, 128) runs as 32 + 8, not a 128-slot program
        # with 3.7x padding waste. Every call shape is still a bucket, so
        # the per-bucket compile contract is unchanged. Callers without an
        # explicit bucket ladder (sweep.py, direct robust_predict) still
        # get one derived from their fixed batch size: the pair worklist
        # size varies with the batch's verdict mix, and dispatching at the
        # raw size would recompile the 630-mask program per distinct k.
        if self.pair_idx.size:
            k = int(self.pair_idx.size)
            bs = (self.bucket_sizes if self.bucket_sizes is not None
                  else data_lib.batch_buckets(int(self.imgs.shape[0])))
            for off, cnt, bucket in data_lib.bucket_plan(k, bs):
                xu = data_lib.pad_to_bucket(
                    jnp.take(self.imgs,
                             jnp.asarray(self.pair_idx[off:off + cnt]),
                             axis=0), bucket)
                mapping = [(pos, int(self.pair_idx[off + pos]))
                           for pos in range(cnt)]
                self.pair_chunks.append((pairs_prog(self.cparams, xu),
                                         mapping))

        for off, w, wb in data_lib.bucket_plan(len(self.row_list),
                                               pc.row_bucket_sizes):
            chunk = self.row_list[off:off + w]
            img_idx = [b for b, _ in chunk] + [chunk[-1][0]] * (wb - w)
            mask_idx = [i for _, i in chunk] + [chunk[-1][1]] * (wb - w)
            xg = jnp.take(self.imgs, jnp.asarray(img_idx), axis=0)
            if rowsets:
                # the engine rows program takes each entry's combined-table
                # index row (the grid gather happens host-side, where the
                # first-mask ids live anyway)
                t = pc._rows_incr(self.cparams, xg,
                                  jnp.asarray(grid_full[mask_idx],
                                              dtype=jnp.int32))
            else:
                t = pc._rows(self.cparams, xg,
                             jnp.asarray(mask_idx, dtype=jnp.int32))
            self.row_chunks.append(
                (t, [(pos, b, i) for pos, (b, i) in enumerate(chunk)]))
        return self

    def _schedule_mesh(self, pairs_prog, grid_full, rowsets: bool):
        """Shard-local phase-2 dispatch (the meshed leg of the two-phase
        schedule; see the module docstring's mesh paragraph).

        Images are owned contiguously along the data axis (matching the
        contiguous block layout `place_batch`'s data sharding produces, so
        a shard mostly forwards rows it already holds); each shard's
        worklist is bucket-planned independently over the STATIC row
        ladder (`data.shard_bucket_plan`), and every wave is ONE
        `[S * bucket]` SPMD call whose rows interleave the shards' entries
        — shard s owns rows [s*bucket, (s+1)*bucket) — gathered host-side
        (the single-chip path's eager-gather idiom) and placed sharded
        over the data axis. A shard whose worklist ran dry fills its slots
        with an owned row (its first scheduled entry, else its first owned
        image, else image 0): the replicated-rows fallback — valid
        forwards whose outputs no mapping entry reads. Wave shapes never
        depend on the batch size or the verdict mix, so the bank stays
        zero-recompile past the ladder; padding is excluded from every
        table read and forward count, so verdicts stay bit-identical to
        the single-chip pruned oracle."""
        pc = self.pc
        n, S = self.n, pc._mesh_data
        blocks = np.array_split(np.arange(n), S)
        lo = [int(b[0]) if b.size else n for b in blocks]
        hi = [int(b[-1]) + 1 if b.size else n for b in blocks]

        if self.pair_idx.size:
            per = [self.pair_idx[(self.pair_idx >= lo[s])
                                 & (self.pair_idx < hi[s])]
                   for s in range(S)]
            for off, counts, bucket in data_lib.shard_bucket_plan(
                    [p.size for p in per], pc.row_bucket_sizes):
                idx = np.zeros((S, bucket), np.int64)
                mapping = []
                for s in range(S):
                    sel = per[s][off:off + counts[s]]
                    fill = (int(sel[0]) if sel.size
                            else int(per[s][0]) if per[s].size
                            else lo[s] if lo[s] < n else 0)
                    idx[s, :] = fill
                    idx[s, :sel.size] = sel
                    mapping += [(s * bucket + j, int(b))
                                for j, b in enumerate(sel)]
                xu = pc._mesh_place(
                    jnp.take(self.imgs, jnp.asarray(idx.reshape(-1)),
                             axis=0))
                self.pair_chunks.append((pairs_prog(self.cparams, xu),
                                         mapping))

        per_rows = [[e for e in self.row_list if lo[s] <= e[0] < hi[s]]
                    for s in range(S)]
        for off, counts, w in data_lib.shard_bucket_plan(
                [len(rw) for rw in per_rows], pc.row_bucket_sizes):
            img_idx = np.zeros((S, w), np.int64)
            mask_idx = np.zeros((S, w), np.int64)
            mapping = []
            for s in range(S):
                sel = per_rows[s][off:off + counts[s]]
                fb, fi = (sel[0] if sel
                          else per_rows[s][0] if per_rows[s]
                          else ((lo[s] if lo[s] < n else 0), 0))
                img_idx[s, :] = fb
                mask_idx[s, :] = fi
                for j, (b, i) in enumerate(sel):
                    img_idx[s, j] = b
                    mask_idx[s, j] = i
                    mapping.append((s * w + j, b, i))
            xg = pc._mesh_place(
                jnp.take(self.imgs, jnp.asarray(img_idx.reshape(-1)),
                         axis=0))
            flat_masks = mask_idx.reshape(-1)
            if rowsets:
                t = pc._rows_incr(self.cparams, xg,
                                  jnp.asarray(grid_full[flat_masks],
                                              dtype=jnp.int32))
            else:
                t = pc._rows(self.cparams, xg,
                             jnp.asarray(flat_masks, dtype=jnp.int32))
            self.row_chunks.append((t, mapping))
        return self

    def finalize(self) -> List[PatchCleanserRecord]:
        """Materialize phase-2 outputs and assemble records (host work;
        syncs the phase-2 prediction tables). Under the "-exact" margin
        modes ("token-exact", "mixer-exact") this is
        also where escalation happens: any image whose evaluated
        incremental entries include a top-2 logit margin below
        `DefenseConfig.incremental_margin` is re-certified through the
        exhaustive program in one extra bucketed dispatch, so its record —
        and therefore its verdict — is bit-identical to the oracle."""
        self.schedule()
        pc = self.pc
        m, p = pc.num_first, pc.num_second
        p1, majority, unanimous = self.p1, self.majority, self.unanimous
        # bf16 banks track margins on EVERY program (the dtype contract's
        # escalation law); at f32 only the drift-carrying engine families
        # (token, mixer) return them
        margins_on = pc._bf16 or self.incr.split("-")[0] in ("token", "mixer")
        if margins_on and self.m1 is None:
            self.m1 = np.asarray(self.t1_margins)[:self.n]

        def split(t):
            """Materialize one phase-2 chunk: (preds, margins). Whole
            tables come back (padding rows included); the chunk's mapping
            names the only rows anything below reads."""
            if isinstance(t, tuple):
                return np.asarray(t[0]), np.asarray(t[1])
            return np.asarray(t), None

        pair_tables = {}
        pair_margins = {}
        for t, mapping in self.pair_chunks:
            tbl, mg = split(t)
            for pos, b in mapping:
                pair_tables[b] = tbl[pos]
                if mg is not None:
                    pair_margins[b] = mg[pos]
        rows = {}                      # image -> {mask i -> [M] row}
        row_margins = {}
        for t, mapping in self.row_chunks:
            tbl, mg = split(t)
            for pos, b, i in mapping:
                rows.setdefault(b, {})[i] = tbl[pos]
                if mg is not None:
                    row_margins.setdefault(b, {})[i] = mg[pos]

        if self.incr == "off":
            # standard full forwards even when an engine family was built
            # (robust_predict(..., incremental="off") on an engine-backed
            # certifier): fe must equal the entry counts, not the token
            # fractions the aggregates carry
            fe_first, fe_pairs = float(m), float(p)
            fe_rows = np.full((m,), float(m))
        else:
            fe_first, fe_pairs = pc._fe_first, pc._fe_pairs
            fe_rows = pc._fe_rows
        grid = pc._np_grid             # [M, M] into preds_2, diagonal -> 0
        records: List[PatchCleanserRecord] = []
        min_margin = np.full((self.n,), np.inf)
        for b in range(self.n):
            mj = int(majority[b])
            if margins_on:
                min_margin[b] = self.m1[b].min()
            if unanimous[b]:
                if b in pair_tables:   # "exact": the certificate audit
                    p2 = pair_tables[b]
                    cert = bool((p2 == mj).all())
                    fwd, fe = m + p, fe_first + fe_pairs
                    if b in pair_margins:
                        min_margin[b] = min(min_margin[b],
                                            pair_margins[b].min())
                else:                  # "consensus": round-1 certificate
                    p2 = np.full((p,), UNEVALUATED, p1.dtype)
                    cert = True
                    fwd, fe = m, fe_first
                records.append(
                    PatchCleanserRecord(mj, cert, p1[b], p2, fwd, fe))
                continue
            # disagreement: the certificate died in round 1; only the
            # minority rows' recovery check remains
            minority = np.nonzero(p1[b] != mj)[0]
            if b in pair_tables:       # k*M >= P: full table was cheaper
                p2 = pair_tables[b]
                second = p2[grid]                       # [M, M]
                second[np.eye(m, dtype=bool)] = p1[b]   # idempotence diagonal
                brows = {int(i): second[i] for i in minority}
                fwd, fe = m + p, fe_first + fe_pairs
                if b in pair_margins:
                    min_margin[b] = min(min_margin[b],
                                        pair_margins[b].min())
            else:
                p2 = np.full((p,), UNEVALUATED, p1.dtype)
                brows = {}
                for i in minority:
                    row = rows[b][int(i)].copy()
                    # the diagonal forward re-evaluates mask_i alone; pin it
                    # to the phase-1 prediction so the recovery check reads
                    # exactly what double_masking_verdict reads
                    row[i] = p1[b, i]
                    brows[int(i)] = row
                    off = np.arange(m) != i
                    p2[grid[i][off]] = row[off]
                    if b in row_margins:
                        # off-diagonal row margins; the pinned diagonal
                        # reads the phase-1 entry, already accounted above
                        min_margin[b] = min(
                            min_margin[b], row_margins[b][int(i)][off].min())
                fwd = m + m * len(minority)
                fe = fe_first + float(sum(fe_rows[i] for i in minority))
            recovered = [i for i, row in brows.items()
                         if (row == p1[b, i]).all()]
            pred = int(p1[b, max(recovered)]) if recovered else mj
            records.append(
                PatchCleanserRecord(pred, False, p1[b], p2, fwd, fe))
        # kept for diagnostics (the bench's token-parity contract check):
        # per-image minimum top-2 logit margin over the evaluated
        # incremental entries; +inf without margins
        self.min_margin = min_margin
        if self.incr.endswith("-exact") or pc._bf16:
            records = self._escalate(records, min_margin)
        return records

    def _escalate(self, records, min_margin) -> List[PatchCleanserRecord]:
        """token/mixer-exact AND every bf16 bank: re-run every image whose
        evaluated entries came within `incremental_margin` of the argmax
        boundary through the f32 exhaustive program (bucketed, one designed
        extra dispatch); their records become exactly the oracle's, paying
        the cost already spent plus the full M + P sweep. This is the law
        that lets bf16 never weaken a verdict: rounding can only flip a
        label where the top-2 margin is small, and small-margin images are
        exactly the ones re-certified at f32."""
        pc = self.pc
        esc = np.nonzero(min_margin < pc.config.incremental_margin)[0]
        if not esc.size:
            return records
        m, p = pc.num_first, pc.num_second
        if pc.mesh is not None:
            # meshed certifiers bucket escalations on the row ladder (the
            # mesh phase-2 ladder) so the exhaustive program's warm shapes
            # stay the fixed `row_bucket_sizes` set — see `warm_pruned`.
            bs = pc.row_bucket_sizes
        else:
            bs = (self.bucket_sizes if self.bucket_sizes is not None
                  else data_lib.batch_buckets(int(self.imgs.shape[0])))
        for off, cnt, bucket in data_lib.bucket_plan(int(esc.size), bs):
            xe = data_lib.pad_to_bucket(
                jnp.take(self.imgs, jnp.asarray(esc[off:off + cnt]), axis=0),
                bucket)
            if pc.mesh is not None:
                xe = pc._mesh_place(xe)
            pred, cert, p1, p2 = map(
                np.asarray,
                pc._predict(self.params, xe, int(self.num_classes)))
            if self.mode == "consensus":
                # the consensus bank certifies on round-1 unanimity alone
                # (the weaker opt-in certificate); the exhaustive program's
                # cert bit is the full pair audit. Re-derive the consensus
                # certificate from the f32 first-round table so an
                # escalated record equals what the f32 consensus bank
                # would have produced. The prediction needs no fixup: on
                # unanimity both agree on the majority label, and on
                # disagreement the exhaustive recovery reads the same full
                # tables the consensus recovery reads.
                cert = (p1 == p1[:, :1]).all(axis=1)
            for pos in range(cnt):
                b = int(esc[off + pos])
                old = records[b]
                records[b] = PatchCleanserRecord(
                    int(pred[pos]), bool(cert[pos]), p1[pos], p2[pos],
                    old.forwards + m + p,
                    old.forward_equivalents + m + p)
        return records


def materialize_verdicts(entry):
    """Host-materialize one certifier's batch answer — the designated
    device-to-host sync the serving layer's `marshal_response` delegates to.
    `entry` is either the exhaustive `predict_tables` 4-tuple or a
    `_PrunedPending`; returns `(pred [n], certified [n], forwards [n],
    forward_equivalents [n])` — forwards counts evaluated table entries,
    forward_equivalents their fractional full-forward cost (equal except
    on the incremental paths)."""
    if isinstance(entry, _PrunedPending):
        recs = entry.finalize()
        return (np.asarray([r.prediction for r in recs]),
                np.asarray([r.certification for r in recs]),
                np.asarray([r.forwards for r in recs]),
                np.asarray([r.forward_equivalents for r in recs]))
    pred, certified, p1, p2 = entry
    exhaustive = int(p1.shape[1]) + int(p2.shape[1])
    pred, certified = np.asarray(pred), np.asarray(certified)
    full = np.full((pred.shape[0],), exhaustive)
    return pred, certified, full, full.astype(np.float64)


@dataclasses.dataclass
class PatchCleanser:
    """One certifier per mask family (reference `PatchCleanser`,
    `PatchCleanser.py:62-118`): `robust_predict` over image batches, fully
    jitted; `collect` aggregates records as the reference does."""

    apply_fn: Callable[[Any, jax.Array], jax.Array]
    spec: masks_lib.MaskSpec
    config: DefenseConfig = dataclasses.field(default_factory=DefenseConfig)
    result: Any = None
    # optional (data, mask) mesh: keeps the fused Pallas mask-fill sharded
    # on multi-chip meshes (see ops.masked_fill)
    mesh: Any = None
    # declared trace budget for the jitted 666-mask sweep: one bucket per
    # distinct image-batch size (the driver's correctness filter makes B
    # dynamic). Enforced only under --sanitize (analysis/sanitize.py).
    recompile_budget: Any = None
    # the victim family's incremental-inference engine
    # (`models.vit.TokenPrunedViT` | `ops.stem_fold.StemFoldEngine` |
    # None) — see `DefenseConfig.incremental` and `resolved_incremental`
    incremental_engine: Any = None
    #: diagnostics: per-image minimum evaluated top-2 logit margin of the
    #: most recent pruned `robust_predict` (a small HOST array — the
    #: bench's token-parity contract check reads it without re-dispatching
    #: the batch, and nothing device-resident is pinned past the call)
    last_min_margin: Any = dataclasses.field(default=None, init=False,
                                             repr=False)
    #: one-shot latch for the `defense.prune_downgrade` observe event: a
    #: certifier that silently runs exhaustive must say why exactly once,
    #: so report/serve stats can explain a 666 forwards/image row
    _downgrade_logged: bool = dataclasses.field(default=False, init=False,
                                                repr=False)

    def __post_init__(self):
        if self.config.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype={self.config.compute_dtype!r} "
                "(legal: float32, bfloat16)")
        # bf16 certify bank: the pruned-path programs sweep in bfloat16
        # (params cast once, images cast at the program boundary,
        # preds/margins read out in f32) and every image whose evaluated
        # margins land inside `incremental_margin` re-certifies through
        # the f32 exhaustive program (`_PrunedPending._escalate`). The
        # exhaustive `_predict` itself NEVER runs bf16 — it is the oracle.
        self._bf16 = self.config.compute_dtype == "bfloat16"
        self._cast_cache = None
        singles, doubles = masks_lib.mask_sets(self.spec)
        self._num_singles = singles.shape[0]
        self._num_doubles = doubles.shape[0]
        k = max(singles.shape[1], doubles.shape[1])
        self._rects = jnp.asarray(
            np.concatenate(
                [masks_lib.pad_rects(singles, k), masks_lib.pad_rects(doubles, k)], axis=0
            )
        )

        def _predict(params, imgs, num_classes: int):
            preds = masked_predictions(
                self.apply_fn, params, imgs, self._rects,
                self.config.chunk_size, self.config.mask_fill,
                self.config.use_pallas, mesh=self.mesh,
            )
            p1 = preds[:, : self._num_singles]
            p2 = preds[:, self._num_singles:]
            pred, certified = double_masking_verdict(
                p1, p2, self._num_singles, num_classes)
            return pred, certified, p1, p2

        self._out_shardings = None
        self._mesh_data = 0
        if self.mesh is not None:
            # replicated outputs: the [B]/[B,M] verdict tables must be
            # host-addressable on EVERY process of a multi-process run
            # (robust_predict materializes them with np.asarray)
            from jax.sharding import NamedSharding, PartitionSpec

            self._out_shardings = NamedSharding(self.mesh, PartitionSpec())
            # data-axis size S of the attached mesh: the shard-local
            # phase-2 scheduler's wave width multiplier (meshes without a
            # "data" axis degenerate to single-list planning, S=1)
            self._mesh_data = int(dict(self.mesh.shape).get("data", 1)) or 1
        # telemetry: first call = trace + XLA compile of the whole 666-mask
        # sweep; recorded as a `compile` event on the driver's EventLog
        self._predict = observe.timed_first_call(
            jax.jit(_predict, static_argnums=2,
                    out_shardings=self._out_shardings),
            f"defense.predict.r{self.spec.patch_ratio}",
            recompile_budget=self.recompile_budget)
        if self.spec.n_patch == 1:
            self._build_pruned_programs()

    def _cast_params(self, params):
        """The bf16 bank's once-cast weight tree (identity on f32 banks).

        Floating leaves cast to bfloat16, everything else passes through;
        a single-slot identity cache keyed on the ORIGINAL tree object
        makes the cast free after the first dispatch (certify reuses one
        weight tree for the whole run). The caller keeps the original tree
        alive through `_PrunedPending.params` — also what the f32
        escalation program consumes — so the `is` key cannot be recycled
        mid-flight."""
        if not self._bf16:
            return params

        def leaf(x):
            x = jnp.asarray(x)
            return (x.astype(jnp.bfloat16)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x)

        if self._cast_cache is None or self._cast_cache[0] is not params:
            self._cast_cache = (params, jax.tree_util.tree_map(leaf, params))
        return self._cast_cache[1]

    def _mesh_place(self, x):
        """Place a host-gathered batch on the mesh: sharded over the data
        axis when it divides the leading dim (the `[S * bucket]` phase-2
        wave batches always do), replicated otherwise (ragged escalation
        tails — tiny next to the masked activation batch). jit cache keys
        include input shardings, so `warm_pruned` routes its warm batches
        through this same rule to guarantee warm placements match live
        dispatch."""
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec()
        if self._mesh_data > 1 and x.shape[0] % self._mesh_data == 0:
            spec = PartitionSpec("data", *(None,) * (x.ndim - 1))
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def _build_pruned_programs(self):
        """The two-phase pruned path's three jitted programs (n_patch=1
        families; single-chip AND meshed certifiers — on a mesh the
        programs jit with replicated out_shardings so the tiny label
        tables stay host-addressable, carry `.mesh`-tagged telemetry names
        (a distinct program bank: sharded fills, different trace shapes),
        and phase 2 dispatches them at `[S * bucket]` shard-local wave
        shapes over the static row-bucket ladder — see `_schedule_mesh`)."""
        m = self._num_singles
        rects_first = self._rects[:m]
        # combined-table index grid: row i = the second-round mask set of
        # first-round mask i (diagonal -> the single mask, idempotence)
        self._grid_full = jnp.asarray(
            masks_lib.second_round_table_indices(m))
        self._np_grid = _second_round_index_grid(m)
        # ragged row worklists pad up to their own bucket ladder, capped by
        # chunk_size: each scan step forwards a [W]-image batch, so the
        # chunked sweep's B*chunk live-memory contract carries over
        self.row_bucket_sizes = data_lib.batch_buckets(
            max(1, int(self.config.chunk_size)))
        if self.mesh is not None:
            # meshes plan PER-SHARD worklists (~S times smaller than the
            # global one) and dispatch the whole phase 2 — pair audit
            # included — at these rungs, so the sparse x4 ladder's tail
            # padding (up to 7/8 of a wave, on every shard at once) would
            # routinely exceed the pruning savings. A dense power-of-two
            # ladder bounds the waste at 2x; it is still a fixed set, so
            # the per-rung compile contract (and the declared trace
            # budgets below) are unchanged in kind, just longer.
            cap = max(1, int(self.config.chunk_size))
            rungs = {1, cap}
            b = 2
            while b < cap:
                rungs.add(b)
                b *= 2
            self.row_bucket_sizes = tuple(sorted(rungs))

        cdt = self.config.compute_dtype

        def _phase1(params, imgs):
            return masked_predictions(
                self.apply_fn, params, imgs, rects_first,
                self.config.chunk_size, self.config.mask_fill,
                self.config.use_pallas, mesh=self.mesh,
                compute_dtype=cdt, with_margins=self._bf16)

        def _pairs(params, imgs):
            return masked_predictions(
                self.apply_fn, params, imgs, self._rects[m:],
                self.config.chunk_size, self.config.mask_fill,
                self.config.use_pallas, mesh=self.mesh,
                compute_dtype=cdt, with_margins=self._bf16)

        chunk_cap = max(1, int(self.config.chunk_size))

        def _rows(params, imgs_g, mask_idx):
            # [W,H,W,C] gathered images x [W] first-round mask ids ->
            # [W, M] second-round rows: scan over the M second masks in
            # groups of G columns, each step rasterizing a PER-ENTRY
            # rectangle set (entry w's column-j mask is {mask_idx[w], j})
            # and forwarding one [G*W] flat batch. G is the largest
            # divisor of M that keeps G*W inside the chunked sweep's
            # per-dispatch live-memory contract (G*W <= chunk_size) — a
            # small row wave would otherwise run M skinny forwards, whose
            # per-dispatch overhead (worst on a mesh, where each one is a
            # whole-mesh collective step) dwarfs the compute. The lerp
            # fill is bitwise `ops.masked_fill`'s XLA reference path.
            idx_tab = self._grid_full[mask_idx]           # [W, M]
            size = self.spec.img_size
            if self._bf16:
                # program-boundary image cast (callers keep f32 batches,
                # see `masked_predictions`); mk/fill follow imgs_g.dtype
                imgs_g = imgs_g.astype(jnp.bfloat16)
            w_sz = int(imgs_g.shape[0])
            cap = max(1, chunk_cap // max(1, w_sz))
            g = max(d for d in range(1, m + 1)
                    if m % d == 0 and d <= cap) if cap > 1 else 1

            def body(carry, idx_cols):                    # idx_cols [G, W]
                rects = self._rects[idx_cols.reshape(-1)]  # [G*W, K, 4]
                mk = masks_lib.rasterize(rects, size)[..., None]
                mk = mk.astype(imgs_g.dtype)
                xt = jnp.tile(imgs_g, (g, 1, 1, 1))
                xm = xt * mk + self.config.mask_fill * (1.0 - mk)
                logits = self.apply_fn(params, xm)
                if self._bf16:
                    preds, margins = utils.preds_margins(logits)
                    return carry, (preds.reshape(g, w_sz),
                                   margins.reshape(g, w_sz))
                return carry, jnp.argmax(logits, axis=-1).reshape(g, w_sz)

            cols = jnp.moveaxis(idx_tab, 0, 1).reshape(m // g, g, w_sz)
            _, out = jax.lax.scan(body, None, cols)
            if self._bf16:
                return tuple(jnp.moveaxis(t.reshape(m, w_sz), 0, 1)
                             for t in out)                # [W, M] x 2
            return jnp.moveaxis(out.reshape(m, w_sz), 0, 1)   # [W, M]

        r = self.spec.patch_ratio
        rb = self.recompile_budget
        row_rb = (len(self.row_bucket_sizes) if rb is not None else None)
        # the meshed bank is a distinct program set (sharded fills,
        # [S*bucket] wave shapes): tag its telemetry/audit names so the
        # single-chip entries stay distinct in the baseline registry. On a
        # mesh the pair audit dispatches at wave shapes over the row
        # ladder (not the caller's image buckets), so its trace budget is
        # the row ladder's too. The bf16 bank is likewise a distinct
        # program set (half-width sweeps, margin outputs): its `.bf16` tag
        # composes with `.mesh` — `defense.phase1.bf16.r*`,
        # `defense.phase1.bf16.mesh.r*` — so DP300/DP301 price both banks
        # side by side.
        dtag = ".bf16" if self._bf16 else ""
        tag = self._prog_tag = dtag + (
            ".mesh" if self.mesh is not None else "")
        osh = self._out_shardings
        pair_rb = row_rb if self.mesh is not None else rb
        self._phase1 = observe.timed_first_call(
            jax.jit(_phase1, out_shardings=osh),
            f"defense.phase1{tag}.r{r}", recompile_budget=rb)
        self._pairs = observe.timed_first_call(
            jax.jit(_pairs, out_shardings=osh),
            f"defense.pairs{tag}.r{r}", recompile_budget=pair_rb)
        self._rows = observe.timed_first_call(
            jax.jit(_rows, out_shardings=osh),
            f"defense.rows{tag}.r{r}", recompile_budget=row_rb)

        # forward-equivalent weights per combined-table mask (full-forward
        # units): all-ones without an engine; the margin engines' families
        # (token, mixer) overwrite them with their dirty-token fractions
        self._fe_combined = np.ones((m + self._num_doubles,), np.float64)
        self._incr_family = None
        self._phase1_incr = self._pairs_incr = self._rows_incr = None
        if (self.incremental_engine is not None
                and self.config.incremental != "off"):
            # meshed certifiers pass the mesh down: the engines' Pallas
            # kernels run per data-axis shard under shard_map (the DP603
            # shard-local proof — raw pallas_call is a custom call GSPMD
            # cannot partition, so the wrappers bypass GSPMD entirely),
            # and batches the data axis does not divide resolve "off"
            fam = self.incremental_engine.build_family(
                np.asarray(self._rects), m, self.config.chunk_size,
                self.config.mask_fill,
                use_pallas=self.config.use_pallas, mesh=self.mesh,
                compute_dtype=self.config.compute_dtype)
            self._incr_family = fam
            kind = self.incremental_engine.kind
            self._phase1_incr = observe.timed_first_call(
                jax.jit(fam.phase1, out_shardings=osh),
                f"defense.phase1.{kind}{tag}.r{r}", recompile_budget=rb)
            if kind in ("token", "mixer"):
                self._fe_combined = np.asarray(fam.fe, np.float64)
                self._pairs_incr = observe.timed_first_call(
                    jax.jit(fam.pairs, out_shardings=osh),
                    f"defense.pairs.{kind}{tag}.r{r}",
                    recompile_budget=pair_rb)
                self._rows_incr = observe.timed_first_call(
                    jax.jit(fam.rows, out_shardings=osh),
                    f"defense.rows.{kind}{tag}.r{r}",
                    recompile_budget=row_rb)
        # per-first-mask second-round row cost (all M entries of the row,
        # idempotence diagonal included — matching the row programs, which
        # evaluate the diagonal too). `cache_fe` charges each program
        # invocation's per-image clean-cache forward (token engine: the
        # cache + K/V projections; 0 elsewhere) so forward_equivalents
        # reflects every dispatched forward, not just the masked entries:
        # phase 1 pays it once per image, the pair audit once per
        # dispatched image, the rows program once per gathered row entry.
        cache_fe = float(getattr(self._incr_family, "cache_fe", 0.0) or 0.0)
        self._fe_rows = self._fe_combined[
            np.asarray(self._grid_full)].sum(axis=1) + cache_fe
        self._fe_first = float(self._fe_combined[:m].sum()) + cache_fe
        self._fe_pairs = float(self._fe_combined[m:].sum()) + cache_fe

    @property
    def num_first(self) -> int:
        """First-round (one-masked) table width M."""
        return int(self._num_singles)

    @property
    def num_second(self) -> int:
        """Second-round (double-masked) table width P = C(M, 2)."""
        return int(self._num_doubles)

    @property
    def num_forwards_exhaustive(self) -> int:
        """Masked forwards per image the exhaustive sweep always executes."""
        return self.num_first + self.num_second

    @property
    def first_round_forward_equivalents(self) -> float:
        """Per-image cost of the mandatory first-round sweep in full-forward
        units under the resolved incremental mode — the floor every
        certified image pays (M = 36 un-pruned; the token engine's fraction
        of that otherwise)."""
        if self.resolved_incremental() != "off":
            return float(self._fe_first)
        return float(self.num_first)

    def resolved_prune(self, prune: Optional[str] = None) -> str:
        """The effective prune mode: explicit arg > config. The two-phase
        pruned schedule runs on single-chip AND meshed certifiers — on a
        mesh, phase 1 shards over the devices as the exhaustive sweep
        always did and phase-2 worklists are planned shard-locally at
        fixed `[S * bucket]` wave shapes (see `_schedule_mesh`), so there
        is no mesh downgrade anymore. The one remaining downgrade is
        n_patch != 1 mask families (their verdict reads the full combined
        table; `_build_pruned_programs` never ran): they resolve to "off"
        and emit a one-time `defense.prune_downgrade` observe event so
        report/serve stats can explain why forwards/image is exhaustive."""
        mode = self.config.prune if prune is None else prune
        if mode not in PRUNE_MODES:
            raise ValueError(
                f"prune={mode!r} (legal: {', '.join(PRUNE_MODES)})")
        if self.spec.n_patch != 1:
            if mode != "off" and not self._downgrade_logged:
                self._downgrade_logged = True
                observe.record_event(
                    "defense.prune_downgrade", reason="n_patch",
                    n_patch=int(self.spec.n_patch), requested=str(mode),
                    ratio=float(self.spec.patch_ratio))
            return "off"
        return mode

    def resolved_incremental(self, incremental: Optional[str] = None,
                             prune: Optional[str] = None) -> str:
        """The effective incremental mode: explicit arg > config; "auto"
        resolves to the attached engine's kind. Always "off" without an
        engine (stub victims), without built incremental programs
        (config.incremental="off" at construction), or when the pruned
        dispatch path itself is off (n_patch!=1, prune="off") —
        incremental forwards ride the two-phase schedule, including its
        meshed shard-local form. An explicit token/mixer/stem request that
        contradicts the engine family is a config error, not a silent
        fallback."""
        mode = (self.config.incremental if incremental is None
                else incremental)
        if mode not in INCREMENTAL_MODES:
            raise ValueError(f"incremental={mode!r} "
                             f"(legal: {', '.join(INCREMENTAL_MODES)})")
        # n_patch!=1 certifiers never ran _build_pruned_programs
        if getattr(self, "_incr_family", None) is None \
                or self.resolved_prune(prune) == "off":
            return "off"
        kind = self.incremental_engine.kind
        if mode == "auto":
            # the default keeps the PR 5 verdict contract: conv families
            # are exact by construction ("stem"); the margin families
            # (ViT "token", ResMLP "mixer") get the margin-gated
            # escalation ("-exact"), whose extra cost is confined to
            # images near the argmax boundary. The plain modes
            # (tolerance-contracted verdicts, no escalation) are opt-in.
            return f"{kind}-exact" if kind in ("token", "mixer") else kind
        if mode != "off" and not mode.startswith(kind):
            raise ValueError(
                f"incremental={mode!r} but this victim family's engine "
                f"is {kind!r}")
        return mode

    def pruned_programs(self, incremental: Optional[str] = None):
        """`[(name, program, input_kind)]` for the programs the resolved
        pruned(+incremental) path dispatches — the single source the
        serving layer's trace accounting/enumeration and the audit
        registry derive from. `input_kind`: "imgs" (params, [B,H,W,C]),
        "rows" (params, gathered [W,H,W,C], [W] first-mask ids),
        "rows_sets" (params, gathered [W,H,W,C], [W,M] combined-table
        index rows — the token/mixer rows programs)."""
        r = self.spec.patch_ratio
        tag = getattr(self, "_prog_tag", "")
        mode = self.resolved_incremental(incremental)
        kind = mode.split("-")[0]
        if kind in ("token", "mixer"):
            return [
                (f"defense.phase1.{kind}{tag}.r{r}", self._phase1_incr,
                 "imgs"),
                (f"defense.pairs.{kind}{tag}.r{r}", self._pairs_incr,
                 "imgs"),
                (f"defense.rows.{kind}{tag}.r{r}", self._rows_incr,
                 "rows_sets"),
            ]
        if mode == "stem":
            return [
                (f"defense.phase1.stem{tag}.r{r}", self._phase1_incr,
                 "imgs"),
                (f"defense.pairs{tag}.r{r}", self._pairs, "imgs"),
                (f"defense.rows{tag}.r{r}", self._rows, "rows"),
            ]
        return [
            (f"defense.phase1{tag}.r{r}", self._phase1, "imgs"),
            (f"defense.pairs{tag}.r{r}", self._pairs, "imgs"),
            (f"defense.rows{tag}.r{r}", self._rows, "rows"),
        ]

    def begin_pruned(
        self, params, imgs: jax.Array, num_classes: int,
        n: Optional[int] = None,
        bucket_sizes: Optional[Sequence[int]] = None,
        prune: Optional[str] = None,
        incremental: Optional[str] = None,
    ) -> _PrunedPending:
        """Dispatch phase 1 of the pruned certification (no host sync).
        `imgs` may already be bucket-padded (pass the real count as `n`,
        the serving worker's contract); otherwise it is padded here when
        `bucket_sizes` is given. Call `.schedule()` then `.finalize()` on
        the returned pending — or let `robust_predict` drive all three."""
        mode = self.resolved_prune(prune)
        if mode == "off":
            raise ValueError("begin_pruned needs prune='exact'|'consensus'")
        incr = self.resolved_incremental(incremental, prune)
        total = int(imgs.shape[0])
        n = total if n is None else int(n)
        # meshed certifiers keep the exact batch: bucket-padding would
        # re-lay-out the caller's sharded input, and phase 2 pads at its
        # own [S*bucket] wave shapes anyway (the image buckets only bound
        # phase-1 trace shapes, covered by the caller's batch-size budget)
        if self.mesh is None and bucket_sizes is not None and n \
                and total == n:
            imgs = data_lib.pad_to_bucket(
                imgs, data_lib.bucket_batch(n, bucket_sizes))
        return _PrunedPending(self, params, imgs, n, num_classes,
                              bucket_sizes, mode, incr)

    def warm_pruned(self, params, bucket_sizes: Sequence[int],
                    num_classes: Optional[int] = None) -> None:
        """Compile every program the resolved pruned(+incremental) path can
        dispatch at run time: phase 1 per image bucket, the pair audit and
        row program per worklist bucket — and, under the "-exact" margin
        modes, the
        exhaustive escalation program (pass `num_classes`; it is a static
        argument of `_predict`). The serving warmup calls this so live
        traffic provably never retraces regardless of which verdict classes
        (and worklist sizes) it produces.

        Single-chip, the pair audit and escalation ride the image buckets
        (`bucket_sizes`); phase-2 rows ride `row_bucket_sizes`. On a mesh
        the whole phase 2 rides the row ladder — pairs and rows dispatch as
        `[S * bucket]` waves (S = data-axis size), escalation at the row
        buckets themselves — and every input is placed by the `_mesh_place`
        rule so warm jit-cache keys (which include input shardings) match
        live traffic."""
        size = self.spec.img_size
        mode = self.resolved_incremental()
        (_, phase1, _), (_, pairs, _), (_, rows, rows_kind) = \
            self.pruned_programs()
        meshed = self.mesh is not None
        place = self._mesh_place if meshed else (lambda x: x)
        S = self._mesh_data if meshed else 1
        # bf16 banks escalate through the f32 exhaustive program on small
        # margins exactly like "-exact" — warm it under the same contract
        esc_on = mode.endswith("-exact") or self._bf16
        if esc_on and num_classes is None:
            raise ValueError(
                f"warm_pruned needs num_classes under "
                f"{mode if mode.endswith('-exact') else 'bfloat16'} "
                "(the escalation program's static argument)")
        # warm against the once-cast tree: jit cache keys include the
        # params avals, so live bf16 dispatch must hit these same traces
        cparams = self._cast_params(params)

        def run(prog, *args):
            out = prog(*args)
            np.asarray(out[0] if isinstance(out, tuple) else out)

        def full(b):
            return place(jnp.full((int(b), size, size, 3), 0.5, jnp.float32))

        for b in bucket_sizes:
            imgs = full(b)
            run(phase1, cparams, imgs)
            if not meshed:
                run(pairs, cparams, imgs)
                if esc_on:
                    run(self._predict, params, imgs, int(num_classes))
        m = self.num_first
        for w in self.row_bucket_sizes:
            wave = S * int(w)
            imgs_g = full(wave)
            if rows_kind == "rows_sets":
                sets = jnp.asarray(
                    np.broadcast_to(np.asarray(self._grid_full)[0],
                                    (wave, m)).copy())
                run(rows, cparams, imgs_g, sets)
            else:
                run(rows, cparams, imgs_g, jnp.zeros((wave,), jnp.int32))
            if meshed:
                run(pairs, cparams, imgs_g)
                if esc_on:
                    run(self._predict, params, full(w), int(num_classes))

    def pruned_trace_counts(self) -> dict:
        """Compiled-trace count per active pruned-path program (the serving
        layer's zero-recompile bookkeeping); includes the escalation
        program under the "-exact" margin modes."""
        out = {name: int(fn._cache_size())
               for name, fn, _ in self.pruned_programs()}
        if self.resolved_incremental().endswith("-exact") or self._bf16:
            out[f"defense.predict.r{self.spec.patch_ratio}"] = \
                int(self._predict._cache_size())
        return out

    def predict_tables(self, params, imgs: jax.Array, num_classes: int):
        """DEVICE-resident verdict tables `(pred [B], certified [B],
        preds_1 [B,M], preds_2 [B,P])` — dispatch-only, no host sync.
        The serving worker uses this to launch every certifier (and the
        clean forward) before materializing ANY result, so the programs
        overlap on device instead of serializing on per-radius transfers;
        `robust_predict` is this plus host marshalling."""
        return self._predict(params, imgs, num_classes)

    def robust_predict(
        self, params, imgs: jax.Array, num_classes: int,
        bucket_sizes: Optional[Sequence[int]] = None,
        prune: Optional[str] = None,
        incremental: Optional[str] = None,
    ) -> List[PatchCleanserRecord]:
        """Batched robust prediction + certification; returns one record per
        image (the reference's per-image `robust_predict(img, certify=True)`,
        vmapped away).

        `bucket_sizes` (e.g. `data.batch_buckets(cfg.batch_size)`) rounds a
        ragged batch up to the nearest fixed bucket before hitting the jitted
        sweep, so the program compiles once per *bucket* instead of once per
        exact batch size — the correctness filter and final data batches
        otherwise force a fresh XLA compile for every distinct B. Padding
        repeats the first image; every verdict is a pure per-row function of
        the prediction tables, so padded rows cannot perturb real rows, and
        they are sliced out of the returned records.

        `prune` overrides `DefenseConfig.prune` ("off" = the exhaustive
        666-forward sweep, the parity oracle; "exact" = two-phase pruned
        scheduling with bit-identical verdicts; "consensus" = additionally
        early-exit unanimous images after round 1 — weaker certificates,
        see the module docstring)."""
        n = int(imgs.shape[0])
        mode = self.resolved_prune(prune)
        if n and mode != "off":
            pending = self.begin_pruned(params, imgs, num_classes,
                                        bucket_sizes=bucket_sizes,
                                        prune=mode, incremental=incremental)
            recs = pending.schedule().finalize()
            self.last_min_margin = pending.min_margin
            return recs
        if bucket_sizes is not None and n:
            imgs = data_lib.pad_to_bucket(
                imgs, data_lib.bucket_batch(n, bucket_sizes))
        pred, certified, p1, p2 = self.predict_tables(params, imgs,
                                                      num_classes)
        pred, certified, p1, p2 = map(np.asarray, (pred, certified, p1, p2))
        return [
            PatchCleanserRecord(int(pred[b]), bool(certified[b]), p1[b],
                                p2[b], self.num_forwards_exhaustive,
                                float(self.num_forwards_exhaustive))
            for b in range(n)
        ]

    def reset(self):
        self.result = None

    def collect(self, records: Sequence[PatchCleanserRecord]):
        self.result = PatchCleanserResult(records)


def build_defenses(
    apply_fn, img_size: int, config: DefenseConfig = DefenseConfig(),
    mesh=None, recompile_budget=None, incremental=None,
) -> List[PatchCleanser]:
    """The reference driver's 4-radius defense bank (`/root/reference/main.py:61`).

    `incremental` is the victim family's incremental-inference engine
    (`models.Victim.incremental`); each certifier builds its own per-radius
    mask-family programs from it (see `DefenseConfig.incremental`)."""
    return [
        PatchCleanser(
            apply_fn,
            masks_lib.geometry(img_size, r, config.n_patch, config.num_mask_per_axis),
            config,
            mesh=mesh,
            recompile_budget=recompile_budget,
            incremental_engine=incremental,
        )
        for r in config.ratios
    ]
