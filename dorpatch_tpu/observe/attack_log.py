"""Structured attack metrics: the JSONL sink for `DorPatch.on_block_end`.

The reference's only metrics are tqdm plus a print of the loss breakdown
every 20 iterations (`/root/reference/attack.py:318-330`). Here the attack's
on-device [8]-metrics vector is consumed at every jitted block boundary and
appended as JSONL records (one file per experiment, under the results dir),
with an optional console mirror of the reference's periodic line. Metrics
stay on device between block boundaries — logging cost is one [8]-vector
transfer per block, not per step.
"""

from __future__ import annotations

import json
import os
import time
from typing import IO, Optional

import numpy as np

# Layout of `TrainState.metrics` (see `attack.DorPatch._step`).
METRIC_NAMES = (
    "loss",         # total per-image objective, batch mean
    "loss_adv",     # CW margin over sampled masks, mean
    "loss_struc",   # structural TV ratio, mean
    "group_lasso",  # stage-0 group-lasso, mean
    "density",      # stage-0 density variance, mean
    "masked_acc",   # fraction of masked EOT samples predicted as state.y.
                    # Untargeted (y = true label): 1.0 = attack losing.
                    # After the targeted switch (y = target): 1.0 = winning.
    "l2",           # ||delta||_2 batch mean
    "n_failed",     # failure-set size (masks the attack currently loses to)
)


class AttackMetricsLogger:
    """JSONL metrics sink for `DorPatch.on_block_end`.

    Each record: `{"ts": ..., "batch": ..., "stage": 0|1, "step": ...,
    "stopped": ..., <METRIC_NAMES>...}`, plus `"run_id"` when one is given.
    The file opens in append mode so resumed runs accumulate — the run_id
    stamp is what disambiguates the attempts: without it, a resumed run
    interleaves duplicate `(batch, stage, step)` records with no way to
    tell them apart (the report CLI groups by run_id; see `observe/report.py`
    and `manifest.new_run_id`). Use as
    `attack.on_block_end = logger.on_block_end` (optionally after
    `logger.set_batch(i)`), or chain from an existing callback.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        echo_every: int = 0,
        clock=time.time,
        run_id: str = "",
    ):
        self.path = path
        self.echo_every = echo_every
        self.run_id = run_id
        self._clock = clock
        self._batch = 0
        self._fh: Optional[IO[str]] = None
        self.history = []
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a", buffering=1)

    def set_batch(self, batch_id: int) -> None:
        self._batch = batch_id

    def on_block_end(self, stage: int, step: int, info: dict) -> None:
        vals = np.asarray(info["metrics"], dtype=np.float64)
        rec = {
            "ts": round(self._clock(), 3),
            "batch": self._batch,
            "stage": int(stage),
            "step": int(step),
            "stopped": bool(info.get("stopped", False)),
        }
        if self.run_id:
            rec["run_id"] = self.run_id
        rec.update({k: float(v) for k, v in zip(METRIC_NAMES, vals)})
        self.history.append(rec)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
        if self.echo_every and (step % self.echo_every == 0 or rec["stopped"]):
            # the reference's periodic loss breakdown (`attack.py:318-330`)
            from dorpatch_tpu.observe.console import log

            log(
                f"[batch {self._batch} stage {stage} iter {step}] "
                f"loss {rec['loss']:.4f} (adv {rec['loss_adv']:.4f}, "
                f"struct {rec['loss_struc']:.4f}, gl {rec['group_lasso']:.5f}, "
                f"density {rec['density']:.5f}) l2 {rec['l2']:.2f} "
                f"masked-acc {rec['masked_acc']:.2f} "
                f"failures {rec['n_failed']:.0f}",
            )

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
