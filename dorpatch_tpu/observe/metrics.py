"""Typed in-process metric registry: the fleet's one accounting surface.

Before this module, every subsystem kept private tallies (`self._counts`
dicts in `serve/service.py`, per-replica attributes in `serve/pool.py`,
local summary dicts in `farm/worker.py`) that the `/stats` block, the
report CLI, and the bench rows each re-derived independently — ROADMAP
item 2's "serve `/stats` and the farm report agree on the query count"
contract was two numbers hoping to match. Here there is ONE registry per
process, three metric types, and every reader renders from it:

- `Counter`   — monotonic, labeled (`serve_requests_total{status=ok}`);
  negative increments raise.
- `Gauge`     — last-write-wins value, or a *computed* gauge bound to a
  callable (`set_function`) so hot paths (batcher queue depth) pay no
  bookkeeping at all.
- `Histogram` — fixed cumulative buckets for the exposition PLUS a
  bounded raw-sample window so `percentile()` answers with the exact
  shared `nearest_rank_percentile` semantics every other surface
  (`/stats`, loadgen, the report CLI) already uses. The window is
  bounded the same way the serve latency ring was (trim half at 8192),
  so long-running services keep recent-window percentiles.

Snapshots: `snapshot()` is a plain-JSON dict; `dump()` writes it
atomically (tmp + `os.replace`) next to `events.jsonl` and NEVER raises —
a full disk leaves the previous snapshot intact, mirroring the EventLog's
ENOSPC degradation. `render_text()` is the Prometheus text exposition
served by `GET /metrics`; `parse_exposition()` is its inverse, used by
`tools/loadgen.py --expect-metrics` to reconcile client-side counts
against a live server without any dependency beyond stdlib.

Thread safety: one registry lock shared by every metric it owns — update
paths are a dict-get plus an add under that lock, and the 8-thread
concurrent-increment exactness test pins the contract.

Stdlib only by design: this module must import on the host-only surfaces
(report CLI, farm tools) without touching numpy or a jax backend.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dorpatch_tpu.observe.timing import nearest_rank_percentile

LabelKey = Tuple[Tuple[str, str], ...]

# Default histogram buckets: latency-in-ms oriented, 1ms..10s.
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 5000.0, 10000.0)

# Raw-sample window bound per histogram series — identical to the serve
# latency ring this module replaced: trim the oldest half at the cap so
# percentiles track the recent window without unbounded memory.
RAW_WINDOW = 8192
RAW_TRIM = 4096


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_value(v: float) -> str:
    """Prometheus sample value: integral floats render without the
    trailing `.0` so counter lines read as the integers they are."""
    f = float(v)
    if math.isfinite(f) and f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(key: LabelKey, extra: Sequence[Tuple[str, str]] = ()
                   ) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + inner + "}"


class _Metric:
    """Base: name + help + the registry's shared lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock


class Counter(_Metric):
    """Monotonic labeled counter. `inc()` with a negative amount raises —
    a counter that can go down is a gauge wearing the wrong type."""

    kind = "counter"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        super().__init__(name, help, lock)
        self._series: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1, **labels) -> None:
        amt = float(amount)
        if amt < 0:
            raise ValueError(
                f"counter {self.name}: negative increment {amount!r}")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amt

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._series.values())

    def series(self) -> List[dict]:
        with self._lock:
            items = sorted(self._series.items())
        return [{"labels": dict(k), "value": v} for k, v in items]

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._series.items())
        return [f"{self.name}{_render_labels(k)} {_fmt_value(v)}"
                for k, v in items]


class Gauge(_Metric):
    """Last-write-wins value per label set; `set_function` binds a series
    to a callable evaluated at read time (computed gauges cost their
    producer nothing on the hot path)."""

    kind = "gauge"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        super().__init__(name, help, lock)
        self._series: Dict[LabelKey, float] = {}
        self._functions: Dict[LabelKey, Callable[[], float]] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def add(self, amount: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def set_function(self, fn: Callable[[], float], **labels) -> None:
        with self._lock:
            self._functions[_label_key(labels)] = fn

    def _eval(self, key: LabelKey) -> Optional[float]:
        fn = self._functions.get(key)
        if fn is None:
            return None
        try:
            return float(fn())
        except Exception:
            return None  # a dead producer must not kill the exposition

    def value(self, **labels) -> float:
        key = _label_key(labels)
        computed = self._eval(key)
        if computed is not None:
            return computed
        with self._lock:
            return self._series.get(key, 0.0)

    def series(self) -> List[dict]:
        with self._lock:
            keys = sorted(set(self._series) | set(self._functions))
        out = []
        for key in keys:
            computed = self._eval(key)
            if computed is None:
                with self._lock:
                    computed = self._series.get(key, 0.0)
            out.append({"labels": dict(key), "value": computed})
        return out

    def render(self) -> List[str]:
        return [f"{self.name}{_render_labels(_label_key(s['labels']))} "
                f"{_fmt_value(s['value'])}" for s in self.series()]


class _HistSeries:
    __slots__ = ("bucket_counts", "count", "sum", "raw")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets
        self.count = 0
        self.sum = 0.0
        self.raw: List[float] = []


class Histogram(_Metric):
    """Fixed-bucket histogram + bounded exact-percentile window."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.Lock,
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, lock)
        self.buckets: Tuple[float, ...] = tuple(
            sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not self.buckets:
            raise ValueError(f"histogram {self.name}: empty bucket list")
        self._series: Dict[LabelKey, _HistSeries] = {}

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets))
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    s.bucket_counts[i] += 1
                    break
            s.count += 1
            s.sum += v
            s.raw.append(v)
            if len(s.raw) >= RAW_WINDOW:
                del s.raw[:RAW_TRIM]

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Exact nearest-rank percentile over the bounded raw window —
        the SAME formula `/stats`, loadgen, and the report CLI use."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            vals = sorted(s.raw) if s is not None else []
        return nearest_rank_percentile(vals, q)

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.count if s is not None else 0

    def sum_(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s.sum if s is not None else 0.0

    def series(self) -> List[dict]:
        with self._lock:
            items = [(k, list(s.bucket_counts), s.count, s.sum)
                     for k, s in sorted(self._series.items())]
        out = []
        for key, counts, count, total in items:
            out.append({
                "labels": dict(key),
                "count": count,
                "sum": total,
                "buckets": {_fmt_value(b): c
                            for b, c in zip(self.buckets, counts)},
            })
        return out

    def render(self) -> List[str]:
        with self._lock:
            items = [(k, list(s.bucket_counts), s.count, s.sum)
                     for k, s in sorted(self._series.items())]
        lines = []
        for key, counts, count, total in items:
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                lines.append(
                    f"{self.name}_bucket"
                    f"{_render_labels(key, [('le', _fmt_value(bound))])}"
                    f" {cum}")
            lines.append(
                f"{self.name}_bucket{_render_labels(key, [('le', '+Inf')])}"
                f" {count}")
            lines.append(f"{self.name}_sum{_render_labels(key)} "
                         f"{_fmt_value(total)}")
            lines.append(f"{self.name}_count{_render_labels(key)} {count}")
        return lines


class MetricRegistry:
    """All of one process's metrics; constructors are idempotent per name
    (asking again returns the same object, a kind clash raises)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}  # guarded-by: self._lock

    def _make(self, name: str, help: str, cls, **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}")
                return existing
            metric = cls(name, help, self._lock, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._make(name, help, Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._make(name, help, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._make(name, help, Histogram, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def value(self, name: str, default: float = 0.0, **labels) -> float:
        """Counter/gauge series value (histograms: the series count)."""
        m = self.get(name)
        if m is None:
            return default
        if isinstance(m, Histogram):
            return float(m.count(**labels))
        return m.value(**labels)

    def percentile(self, name: str, q: float, **labels) -> Optional[float]:
        m = self.get(name)
        if isinstance(m, Histogram):
            return m.percentile(q, **labels)
        return None

    # ---------------- snapshots & exposition ----------------

    def snapshot(self) -> dict:
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, dict] = {}
        for m in sorted(metrics, key=lambda m: m.name):
            out[m.name] = {"type": m.kind, "help": m.help,
                           "series": m.series()}
        return {"version": 1, "metrics": out}

    def dump(self, path: str) -> bool:
        """Atomic snapshot write (tmp + `os.replace`); NEVER raises — on
        any failure the previous snapshot file is left intact and False
        is returned (the ENOSPC contract the chaos test pins)."""
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            snap = self.snapshot()
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(snap, fh, sort_keys=True)
                fh.write("\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            return True
        except Exception:
            try:
                os.remove(tmp)
            except OSError:
                pass
            return False

    def render_text(self) -> str:
        """Prometheus text exposition (the `GET /metrics` body)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in sorted(metrics, key=lambda m: m.name):
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


def parse_exposition(text: str) -> Dict[str, Dict[LabelKey, float]]:
    """Inverse of `render_text` for the sample lines (comments skipped):
    ``{sample_name: {label_key: value}}``. Histogram component samples
    appear under their suffixed names (`x_bucket`, `x_sum`, `x_count`)."""
    out: Dict[str, Dict[LabelKey, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            labels_raw, _, value_raw = rest.rpartition("} ")
            pairs = []
            for part in _split_labels(labels_raw):
                if "=" not in part:
                    continue
                k, v = part.split("=", 1)
                v = v.strip()
                if v.startswith('"') and v.endswith('"'):
                    v = v[1:-1]
                v = (v.replace("\\n", "\n").replace('\\"', '"')
                     .replace("\\\\", "\\"))
                pairs.append((k.strip(), v))
            key = tuple(sorted(pairs))
        else:
            parts = line.split()
            if len(parts) < 2:
                continue
            name, value_raw = parts[0], parts[1]
            key = ()
        try:
            value = float(value_raw.strip())
        except ValueError:
            continue
        out.setdefault(name.strip(), {})[key] = value
    return out


def _split_labels(raw: str) -> List[str]:
    """Split `a="x",b="y,z"` on commas outside quotes."""
    parts, buf, in_quotes, escaped = [], [], False, False
    for ch in raw:
        if escaped:
            buf.append(ch)
            escaped = False
            continue
        if ch == "\\":
            buf.append(ch)
            escaped = True
            continue
        if ch == '"':
            in_quotes = not in_quotes
            buf.append(ch)
            continue
        if ch == "," and not in_quotes:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return parts


def labeled_values(snapshot: dict, name: str, label: str
                   ) -> Dict[str, float]:
    """``{label_value: value}`` for one counter/gauge in a `snapshot()`
    (or `dump`ed) dict — the fleet cross-check's join primitive."""
    metric = (snapshot or {}).get("metrics", {}).get(name)
    out: Dict[str, float] = {}
    if not isinstance(metric, dict):
        return out
    for s in metric.get("series", ()):
        labels = s.get("labels", {})
        if label in labels and "value" in s:
            key = str(labels[label])
            out[key] = out.get(key, 0.0) + float(s["value"])
    return out
