"""Attributable console output for multi-process runs.

The reference prints anonymously (`/root/reference/attack.py:318-330`,
`main.py:186-187`); under an N-process SPMD driver those lines interleave
with no way to tell which process said what. `log()` is the framework-wide
`print` replacement: every line is prefixed with the process index and the
wall time since process start, so a four-way interleaved log is still
attributable post-mortem.

The process index is NOT read from `jax.process_index()` here: importing
(or touching) jax from a logging helper would initialize — and on shared
accelerators, claim — the backend, which the torch oracle paths must never
do (see `backends/torch_pipeline.py` module docstring). The jax pipeline
calls `set_process_index(jax.process_index())` once it owns the backend;
everything else defaults to process 0.
"""

from __future__ import annotations

import sys
import time

_T0 = time.monotonic()
_PROCESS_INDEX = 0


def set_process_index(index: int) -> None:
    """Record this process's index (the jax pipeline calls this once)."""
    global _PROCESS_INDEX
    _PROCESS_INDEX = int(index)


def process_index() -> int:
    return _PROCESS_INDEX


def elapsed() -> float:
    """Seconds since process start (well, since this module imported)."""
    return time.monotonic() - _T0


def log(msg, *, file=None, flush: bool = True) -> None:
    """`print` with a `[pN +T.Ts]` attribution prefix.

    `file` defaults to stdout (capsys-visible in tests); pass
    `sys.stderr` for diagnostics that must not pollute parseable stdout.
    """
    print(f"[p{_PROCESS_INDEX} +{elapsed():.1f}s] {msg}",
          file=file if file is not None else sys.stdout, flush=flush)
