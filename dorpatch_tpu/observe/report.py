"""Offline telemetry report: join `run.json` + `events.jsonl` +
`metrics.jsonl` + `heartbeat_*.jsonl` into a human summary.

    python -m dorpatch_tpu.observe.report <results_dir> [--json]

Host-only: parses JSONL, never imports jax/torch — safe to run on a login
node against a results dir a wedged TPU job left behind. Shows, per the
latest attempt (run_id): the per-phase time breakdown and span coverage,
compile vs run time, attack/certification throughput (MFU via the shared
`StepTimer.summary` FLOPs path when the manifest carries FLOPs accounting),
device-memory peaks, heartbeat stall detection, and spans left open by a
hang or crash.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from typing import Dict, List, Optional

from dorpatch_tpu.observe.heartbeat import summarize_heartbeats
from dorpatch_tpu.observe.manifest import MANIFEST_NAME
from dorpatch_tpu.observe.metrics import labeled_values
from dorpatch_tpu.observe.timing import StepTimer, nearest_rank_percentile


def _read_jsonl(path: str) -> List[dict]:
    rows = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue  # truncated tail of an aborted run
    except OSError:
        pass
    return rows


def load_manifest(result_dir: str) -> Optional[dict]:
    try:
        with open(os.path.join(result_dir, MANIFEST_NAME)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def load_events(result_dir: str) -> List[dict]:
    rows = []
    for path in sorted(glob.glob(os.path.join(result_dir, "events*.jsonl"))):
        rows.extend(_read_jsonl(path))
    return rows


def _aggregate(spans: List[dict]) -> List[dict]:
    """[{name, count, total_s}] sorted by total descending."""
    agg: Dict[str, dict] = {}
    for s in spans:
        a = agg.setdefault(s.get("name", "?"),
                           {"name": s.get("name", "?"), "count": 0,
                            "total_s": 0.0})
        a["count"] += 1
        a["total_s"] += float(s.get("dur_s", 0.0))
    out = sorted(agg.values(), key=lambda a: -a["total_s"])
    for a in out:
        a["total_s"] = round(a["total_s"], 3)
    return out


def summarize(result_dir: str, stall_factor: float = 5.0) -> dict:
    """Join every telemetry file in `result_dir` into one summary dict."""
    manifest = load_manifest(result_dir)
    events = load_events(result_dir)
    metrics = _read_jsonl(os.path.join(result_dir, "metrics.jsonl"))

    attempts: List[str] = list((manifest or {}).get("previous_run_ids", []))[::-1]
    for r in metrics + events:
        rid = r.get("run_id", "")
        if rid and rid not in attempts:
            attempts.append(rid)
    run_id = (manifest or {}).get("run_id") or (attempts[-1] if attempts else "")

    # latest attempt, driver process only, for the time accounting
    ev = [r for r in events
          if r.get("proc", 0) == 0 and r.get("run_id", "") == run_id]
    spans = [r for r in ev if r.get("kind") == "span"]
    begins = [r for r in ev if r.get("kind") == "begin"]
    compiles = [r for r in ev if r.get("kind") == "compile"]
    blocks = [r for r in ev if r.get("kind") == "block"]

    # run wall time: the closing "run" span, else (hang/crash) the distance
    # from its begin record to the last record seen
    run_spans = [s for s in spans if s.get("name") == "run"]
    run_complete = bool(run_spans)
    if run_spans:
        run_seconds = float(run_spans[-1]["dur_s"])
    else:
        run_begin = [b for b in begins if b.get("name") == "run"]
        run_seconds = (float(ev[-1]["ts"]) - float(run_begin[-1]["ts"])
                       if run_begin and ev else 0.0)

    top = [s for s in spans if s.get("depth") == 1]
    phases = _aggregate(top)
    covered = sum(p["total_s"] for p in phases)
    for p in phases:
        p["pct"] = round(100.0 * p["total_s"] / run_seconds, 1) \
            if run_seconds else 0.0
    inner = _aggregate([s for s in spans if s.get("depth", 0) >= 2])

    # spans left open: begin paths minus closed span paths (multiset)
    closed: Dict[str, int] = {}
    for s in spans:
        closed[s.get("path", "")] = closed.get(s.get("path", ""), 0) + 1
    open_spans = []
    for b in begins:
        p = b.get("path", "")
        if closed.get(p, 0) > 0:
            closed[p] -= 1
        else:
            open_spans.append(p)

    compile_total = round(sum(float(c.get("dur_s", 0.0)) for c in compiles), 3)

    # attack accounting: steps from metrics.jsonl (max step per batch/stage),
    # seconds from the attack.stage* spans, images from batch-span attrs
    mrecs = [m for m in metrics if m.get("run_id", run_id) == run_id]
    steps_by_key: Dict[tuple, int] = {}
    for m in mrecs:
        key = (m.get("batch", 0), m.get("stage", 0))
        steps_by_key[key] = max(steps_by_key.get(key, 0), int(m.get("step", 0)))
    attack_steps = sum(steps_by_key.values())
    attack_seconds = sum(float(s.get("dur_s", 0.0)) for s in spans
                         if str(s.get("name", "")).startswith("attack.stage"))
    batch_spans = [s for s in spans if s.get("name") == "batch"]
    images_total = sum(int(s.get("images", 0)) for s in batch_spans)
    images_generated = sum(int(s.get("images", 0)) for s in batch_spans
                           if not s.get("cached"))
    # certify accounting from the certify spans themselves: on resumed runs
    # cached batches skip certification entirely, so dividing ALL images by
    # certify time would inflate the rate
    certify_spans = [s for s in spans if s.get("name") == "certify"]
    certify_seconds = sum(float(s.get("dur_s", 0.0)) for s in certify_spans)
    certify_images = sum(int(s.get("images", 0)) for s in certify_spans)
    # pruned-certification accounting (PR 5): executed vs
    # exhaustive-equivalent masked forwards, from the span attrs the
    # pipeline records per batch; zero on pre-prune telemetry
    certify_fwd = sum(int(s.get("forwards", 0)) for s in certify_spans)
    certify_exh = sum(int(s.get("forwards_exhaustive", 0))
                      for s in certify_spans)
    # incremental accounting (mask-aware incremental forwards): the spans'
    # fractional full-forward cost; falls back to the entry count on
    # pre-incremental telemetry so the two totals coincide there
    certify_fe = sum(float(s.get("forward_equivalents", s.get("forwards", 0)))
                     for s in certify_spans)
    # mixed-precision accounting (bf16 certify bank): each certify span is
    # stamped with the DefenseConfig.compute_dtype it ran under; when one
    # results dir holds BOTH banks (an A/B run, or two attempts at
    # different precisions) the per-dtype image rates give the measured
    # speedup directly. Pre-bf16 telemetry carries no dtype attr -> None.
    dtype_rates: Dict[str, dict] = {}
    for s in certify_spans:
        dt = s.get("compute_dtype")
        if not dt:
            continue
        r = dtype_rates.setdefault(str(dt), {"seconds": 0.0, "images": 0})
        r["seconds"] += float(s.get("dur_s", 0.0))
        r["images"] += int(s.get("images", 0))
    certify_dtype = "+".join(sorted(dtype_rates)) if dtype_rates else None
    certify_dtype_speedup = None
    if {"f32", "bf16"} <= set(dtype_rates):
        f32, b16 = dtype_rates["f32"], dtype_rates["bf16"]
        if f32["seconds"] and b16["seconds"] and f32["images"] \
                and b16["images"]:
            certify_dtype_speedup = round(
                (b16["images"] / b16["seconds"])
                / (f32["images"] / f32["seconds"]), 3)

    peak_mem = 0
    for b in blocks:
        for d in b.get("mem") or []:
            peak_mem = max(peak_mem,
                           int(d.get("peak_bytes_in_use",
                                     d.get("bytes_in_use", 0)) or 0))

    # MFU through the one shared formula (StepTimer.summary): available when
    # the manifest records FLOPs accounting (e.g. a bench-style run)
    mfu = None
    tele = (manifest or {}).get("telemetry") or {}
    if attack_steps and attack_seconds and tele.get("flops_per_step") \
            and tele.get("peak_flops"):
        t = StepTimer()
        t.block_seconds = [attack_seconds]
        mfu = t.summary(steps_per_block=attack_steps, batch=1,
                        flops_per_step=float(tele["flops_per_step"]),
                        peak_flops=float(tele["peak_flops"]))

    serve = _summarize_serve(ev)
    replicas = _summarize_replicas(ev)
    baseline = _load_baseline_check(result_dir)

    metrics_by_attempt: Dict[str, int] = {}
    for m in metrics:
        rid = m.get("run_id", "(unstamped)")
        metrics_by_attempt[rid] = metrics_by_attempt.get(rid, 0) + 1

    return {
        "result_dir": result_dir,
        "manifest": manifest,
        "run_id": run_id,
        "attempts": attempts,
        "run_complete": run_complete,
        "run_seconds": round(run_seconds, 3),
        "phases": phases,
        "coverage": round(covered / run_seconds, 4) if run_seconds else 0.0,
        "inner_spans": inner,
        "open_spans": open_spans,
        "compile": {"total_s": compile_total, "programs": _aggregate(compiles)},
        "blocks": {"count": len(blocks),
                   "total_s": round(sum(float(b.get("dur_s", 0.0))
                                        for b in blocks), 3)},
        "attack": {
            "steps": attack_steps,
            "seconds": round(attack_seconds, 3),
            "steps_per_sec": round(attack_steps / attack_seconds, 3)
            if attack_seconds else 0.0,
            "images": images_total,
            "images_generated": images_generated,
            "images_per_sec": round(images_generated / attack_seconds, 3)
            if attack_seconds and images_generated else 0.0,
        },
        "certify": {
            "seconds": round(certify_seconds, 3),
            "images": certify_images,
            "images_per_sec": round(certify_images / certify_seconds, 3)
            if certify_seconds and certify_images else 0.0,
            "forwards": certify_fwd,
            "forwards_per_image": round(certify_fwd / certify_images, 1)
            if certify_fwd and certify_images else None,
            "forward_equivalents_per_image": round(
                certify_fe / certify_images, 2)
            if certify_fe and certify_images else None,
            "prune_rate": round(1.0 - certify_fwd / certify_exh, 4)
            if certify_fwd and certify_exh else None,
            "exhaustive_speedup": round(certify_exh / certify_fe, 2)
            if certify_fe and certify_exh else None,
            "compute_dtype": certify_dtype,
            "dtype_speedup": certify_dtype_speedup,
        },
        "mfu": mfu,
        "serve": serve,
        "replicas": replicas,
        "aot": _summarize_aot(ev),
        "baseline": baseline,
        "peak_device_bytes": peak_mem or None,
        "heartbeats": summarize_heartbeats(result_dir,
                                           stall_factor=stall_factor),
        "metrics_records": {"total": len(metrics),
                            "by_attempt": metrics_by_attempt},
    }


def _percentile_ms(sorted_s: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of an ascending seconds list, in ms."""
    v = nearest_rank_percentile(sorted_s, q)
    return None if v is None else round(v * 1e3, 3)


def _summarize_serve(ev: List[dict]) -> Optional[dict]:
    """The serving section: request latency percentiles, throughput,
    batch occupancy, and reject rate — from the `serve.request` events and
    `serve.batch` spans the service emits. None when the results dir holds
    no serving telemetry (batch runs keep their report unchanged)."""
    reqs = [r for r in ev
            if r.get("kind") == "event" and r.get("name") == "serve.request"]
    batches = [r for r in ev
               if r.get("kind") == "span" and r.get("name") == "serve.batch"]
    if not reqs and not batches:
        return None
    by_status: Dict[str, int] = {}
    for r in reqs:
        st = str(r.get("status", "?"))
        by_status[st] = by_status.get(st, 0) + 1
    ok_lat = sorted(float(r.get("latency_s", 0.0)) for r in reqs
                    if r.get("status") == "ok")
    # per-request certify cost (pruned-scheduling PR): executed masked
    # forwards vs the bank's exhaustive-equivalent, stamped on ok events
    fwd = sum(int(r.get("forwards", 0)) for r in reqs
              if r.get("status") == "ok")
    fwd_exh = sum(int(r.get("forwards_exhaustive", 0)) for r in reqs
                  if r.get("status") == "ok")
    # fractional full-forward cost under the incremental paths (== fwd on
    # pre-incremental telemetry, where the attr is absent)
    fe = sum(float(r.get("forward_equivalents", r.get("forwards", 0)))
             for r in reqs if r.get("status") == "ok")
    total = sum(by_status.values())
    rejected = by_status.get("overloaded", 0)
    ts = [float(r["ts"]) for r in reqs if "ts" in r]
    wall = (max(ts) - min(ts)) if len(ts) >= 2 else 0.0
    images = sum(int(b.get("images", 0)) for b in batches)
    slots = sum(int(b.get("bucket", 0)) for b in batches)
    # the certify-bank precision the replicas batched under (stamped per
    # serve.batch span); absent on pre-bf16 telemetry
    dtypes = sorted({str(b["compute_dtype"]) for b in batches
                     if b.get("compute_dtype")})
    return {
        "requests": total,
        "by_status": dict(sorted(by_status.items())),
        "latency_ms": {"count": len(ok_lat),
                       "p50": _percentile_ms(ok_lat, 0.50),
                       "p95": _percentile_ms(ok_lat, 0.95),
                       "p99": _percentile_ms(ok_lat, 0.99)},
        "throughput_rps": round(len(ok_lat) / wall, 3) if wall else None,
        "batches": len(batches),
        "batch_seconds": round(sum(float(b.get("dur_s", 0.0))
                                   for b in batches), 3),
        "occupancy": round(images / slots, 4) if slots else None,
        "reject_rate": round(rejected / total, 4) if total else 0.0,
        "certify_forwards_per_request": round(fwd / len(ok_lat), 1)
        if fwd and ok_lat else None,
        "certify_forward_equivalents_per_request": round(fe / len(ok_lat), 2)
        if fe and ok_lat else None,
        "certify_prune_rate": round(1.0 - fwd / fwd_exh, 4)
        if fwd and fwd_exh else None,
        "compute_dtype": "+".join(dtypes) if dtypes else None,
    }


def _summarize_replicas(ev: List[dict]) -> Optional[dict]:
    """The replica-pool section: per-replica lifecycle accounting from the
    `serve.replica.{start,sick,quarantine,restart,retire}` events the
    supervised pool emits, plus per-replica batch counts from the
    `serve.batch` spans. None when the dir predates the replica pool (or
    the run never served) so old reports render unchanged."""
    life = [r for r in ev if r.get("kind") == "event"
            and str(r.get("name", "")).startswith("serve.replica.")]
    if not life:
        return None
    batches = [r for r in ev
               if r.get("kind") == "span" and r.get("name") == "serve.batch"]
    per: Dict[int, dict] = {}

    def rep(i):
        return per.setdefault(int(i), {
            "replica": int(i), "generation": 0, "restarts": 0,
            "sick": 0, "sick_kinds": {}, "retired": False,
            "failed_over": 0, "batches": 0, "aot": None})

    drains = 0
    for r in life:
        if "replica" not in r:
            continue
        p = rep(r["replica"])
        name = r["name"]
        if name == "serve.replica.start":
            p["generation"] = max(p["generation"], int(r.get("generation", 0)))
            if r.get("aot") is not None:
                p["aot"] = bool(r["aot"])
        elif name == "serve.replica.sick":
            p["sick"] += 1
            cause = str(r.get("cause", "?"))
            p["sick_kinds"][cause] = p["sick_kinds"].get(cause, 0) + 1
            p["failed_over"] += int(r.get("inflight", 0))
        elif name == "serve.replica.restart":
            p["generation"] = max(p["generation"], int(r.get("generation", 0)))
            p["restarts"] = max(p["restarts"], int(r.get("restarts", 0)))
            p["restart_s"] = round(float(r.get("dur_s", 0.0)), 3)
            p["restart_traces"] = int(r.get("trace_counts", 0))
        elif name == "serve.replica.quarantine":
            p["restarts"] = max(p["restarts"], int(r.get("restarts", 0)))
        elif name == "serve.replica.retire":
            p["retired"] = True
            p["restarts"] = max(p["restarts"], int(r.get("restarts", 0)))
    for b in batches:
        if "replica" in b:
            rep(b["replica"])["batches"] += 1
    drains = sum(1 for r in ev if r.get("kind") == "event"
                 and r.get("name") == "serve.drain_timeout")
    out = sorted(per.values(), key=lambda p: p["replica"])
    return {
        "count": len(out),
        "retired": sum(1 for p in out if p["retired"]),
        "restarts": sum(p["restarts"] for p in out),
        "failed_over": sum(p["failed_over"] for p in out),
        "drain_timeouts": drains,
        "per_replica": out,
    }


def _summarize_aot(ev: List[dict]) -> Optional[dict]:
    """The AOT executable-store section: warm-boot hit rate and estimated
    compile seconds saved — from the `aot.load` / `aot.miss` / `aot.build`
    events the boot path emits plus the closing `aot.boot` summary event.
    None when the run never touched a store (every pre-AOT results dir
    renders unchanged)."""
    loads = [r for r in ev
             if r.get("kind") == "event" and r.get("name") == "aot.load"]
    misses = [r for r in ev
              if r.get("kind") == "event" and r.get("name") == "aot.miss"]
    builds = [r for r in ev
              if r.get("kind") == "event" and r.get("name") == "aot.build"]
    boots = [r for r in ev
             if r.get("kind") == "event" and r.get("name") == "aot.boot"]
    if not (loads or misses or builds or boots):
        return None
    miss_reasons: Dict[str, int] = {}
    for r in misses:
        reason = str(r.get("reason", "?"))
        miss_reasons[reason] = miss_reasons.get(reason, 0) + 1
    attempts = len(loads) + len(misses)
    out = {
        "loads": len(loads),
        "misses": len(misses),
        "builds": len(builds),
        "hit_rate": round(len(loads) / attempts, 4) if attempts else None,
        "miss_reasons": dict(sorted(miss_reasons.items())),
        "saved_s": round(sum(float(r.get("saved_s", 0.0)) for r in loads), 3),
    }
    if boots:
        b = boots[-1]
        out["boot"] = {"mode": b.get("mode", "?"),
                       "hits": int(b.get("hits", 0)),
                       "misses": int(b.get("misses", 0)),
                       "builds": int(b.get("builds", 0)),
                       "boot_s": round(float(b.get("boot_s", 0.0)), 3),
                       "saved_s": round(float(b.get("saved_s", 0.0)), 3)}
    return out


def _load_baseline_check(result_dir: str) -> Optional[dict]:
    """The program-baseline gate's machine-readable result, when a
    `--baseline check --baseline-report <dir>` run dropped one next to the
    telemetry (`baseline_check.json`). None when absent — results dirs
    predating the baseline tier render unchanged."""
    try:
        with open(os.path.join(result_dir, "baseline_check.json")) as fh:
            out = json.load(fh)
    except (OSError, ValueError):
        return None
    return out if isinstance(out, dict) else None


def _format_recert_report(st: dict, verdict: Optional[dict]) -> str:
    """Human rendering of a re-certification dir: scheduler status plus the
    latest generation's verdict (per-cell robust-accuracy vs baseline)."""
    lines = []
    add = lines.append
    add("= DorPatch re-certification report =")
    add(f"recert dir: {st['recert_dir']}")
    add(f"baseline: {st['baseline_file']}")
    add(f"completed generation: {st['generation']}")
    infl = st.get("inflight")
    if infl:
        c = infl.get("counts") or {}
        add(f"inflight: generation {infl['generation']} "
            f"({c.get('done', 0)}/{c.get('total', 0)} jobs done, "
            f"{c.get('failed_exhausted', 0)} exhausted, "
            f"{c.get('quarantined', 0)} quarantined)")
    if not verdict:
        add("(no verdict yet — run `python -m dorpatch_tpu.recert run`)")
        return "\n".join(lines)
    add(f"-- verdict (generation {verdict.get('generation')}, "
        f"baseline generation {verdict.get('baseline_generation')}) --")
    wm = verdict.get("worst_margin")
    add(f"  status: {verdict.get('status', '?')}"
        + (f", worst margin {wm:+.2f} pts above the tolerance floor"
           if wm is not None else "")
        + ("" if verdict.get("seeded") else " (baseline UNSEEDED)"))
    by_rule = verdict.get("findings_by_rule") or {}
    if by_rule:
        add("  findings: "
            + ", ".join(f"{k}: {v}" for k, v in sorted(by_rule.items())))
    cells = verdict.get("cells") or {}
    if cells:
        add(f"-- cells ({len(cells)}) --")
    for key, c in sorted(cells.items()):
        parts = []
        if "measured" in c:
            parts.append(f"measured {c['measured']:.2f}")
        if "baseline" in c:
            parts.append(f"baseline {c['baseline']:.2f} "
                         f"(tol {c.get('tolerance', '?')})")
        if "margin" in c:
            parts.append(f"margin {c['margin']:+.2f}")
        flag = str(c.get("status", "?"))
        add(f"  [{flag:>9}] {key}: " + ", ".join(parts or ["no data"]))
    for f in (verdict.get("findings") or [])[:8]:
        add(f"  {f.get('rule', '?')} {f.get('message', '')[:110]}")
    return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n} B"


def format_report(s: dict) -> str:
    """Human rendering of a `summarize()` dict."""
    lines = []
    add = lines.append
    add("= DorPatch run telemetry report =")
    add(f"results dir: {s['result_dir']}")
    m = s.get("manifest") or {}
    attempt = (f"attempt {len(s['attempts'])}" if len(s["attempts"]) > 1
               else "single attempt")
    add(f"run: {s['run_id'] or '(no run_id)'} ({attempt})"
        + (f" started {m['started_iso']}" if m.get("started_iso") else "")
        + (f" on {m['hostname']}" if m.get("hostname") else "")
        + (f" @ {m['git_sha'][:10]}" if m.get("git_sha") else ""))
    if m.get("backend") or m.get("jax"):
        add(f"backend: {m.get('backend', '?')} "
            f"({m.get('device_count', '?')} x {m.get('device_kind', '?')}, "
            f"{m.get('process_count', '?')} process(es)) "
            f"jax {m.get('jax', '?')}")
    if not s["run_complete"]:
        add("!! run span never closed: the run hung or crashed mid-flight")

    add(f"-- phase breakdown (proc 0, run {s['run_seconds']}s) --")
    for p in s["phases"]:
        add(f"  {p['name']:<14} {p['total_s']:>9.3f}s  {p['pct']:>5.1f}%  "
            f"({p['count']} span{'s' if p['count'] != 1 else ''})")
    add(f"  span coverage: {100.0 * s['coverage']:.1f}% of run wall time")
    if s["inner_spans"]:
        add("-- inner spans --")
        for p in s["inner_spans"]:
            add(f"  {p['name']:<24} {p['total_s']:>9.3f}s  ({p['count']})")
    if s["open_spans"]:
        add("-- spans left OPEN (hang/crash signature) --")
        for p in s["open_spans"]:
            add(f"  {p}")

    c = s["compile"]
    add("-- compile --")
    pct = (100.0 * c["total_s"] / s["run_seconds"]) if s["run_seconds"] else 0.0
    add(f"  compile time: {c['total_s']}s ({pct:.1f}% of run) over "
        f"{len(c['programs'])} program(s)")
    for p in c["programs"]:
        add(f"  {p['name']:<36} {p['count']} x {p['total_s']:.3f}s")

    a, ce = s["attack"], s["certify"]
    add("-- throughput --")
    add(f"  attack: {a['steps']} steps in {a['seconds']}s -> "
        f"{a['steps_per_sec']} steps/sec; {a['images_generated']} images "
        f"generated -> {a['images_per_sec']} images/sec")
    dt = f" [{ce['compute_dtype']}]" if ce.get("compute_dtype") else ""
    add(f"  certify{dt}: {ce['images']} images in {ce['seconds']}s -> "
        f"{ce['images_per_sec']} images/sec")
    if ce.get("dtype_speedup") is not None:
        add(f"  certify dtype speedup: {ce['dtype_speedup']}x "
            "(bf16 vs f32 images/sec, both banks in this dir)")
    if ce.get("forwards_per_image"):
        prune = (f", prune rate {100.0 * ce['prune_rate']:.1f}%, "
                 f"{ce['exhaustive_speedup']}x vs exhaustive"
                 if ce.get("prune_rate") is not None else "")
        incr = ""
        fe = ce.get("forward_equivalents_per_image")
        # the annotation marks a genuinely fractional cost, not the two
        # aggregates' different rounding precision
        if fe is not None and fe < ce["forwards_per_image"] - 0.5:
            incr = f" ({fe} full-forward equivalents, incremental)"
        add(f"  certify forwards: {ce['forwards_per_image']} "
            f"executed/image{incr}{prune}")
    if s["mfu"]:
        add(f"  mfu: {s['mfu'].get('mfu')} "
            f"({s['mfu'].get('achieved_tflops')} TFLOP/s achieved)")
    else:
        add("  mfu: n/a (no FLOPs accounting in run.json:telemetry)")
    if s["peak_device_bytes"]:
        add(f"  peak device memory: {_fmt_bytes(s['peak_device_bytes'])}")

    sv = s.get("serve")
    if sv:
        add("-- serve --")
        statuses = ", ".join(f"{k}: {v}" for k, v in sv["by_status"].items())
        add(f"  requests: {sv['requests']} ({statuses})"
            + (f", certify bank {sv['compute_dtype']}"
               if sv.get("compute_dtype") else ""))
        lat = sv["latency_ms"]
        if lat["count"]:
            add(f"  latency: p50 {lat['p50']} ms, p95 {lat['p95']} ms, "
                f"p99 {lat['p99']} ms ({lat['count']} ok)")
        if sv["throughput_rps"] is not None:
            add(f"  throughput: {sv['throughput_rps']} req/sec")
        occ = (f"{100.0 * sv['occupancy']:.1f}%"
               if sv["occupancy"] is not None else "n/a")
        add(f"  batches: {sv['batches']} in {sv['batch_seconds']}s, "
            f"occupancy {occ}, reject rate {100.0 * sv['reject_rate']:.1f}%")
        if sv.get("certify_forwards_per_request"):
            prune = (f", prune rate {100.0 * sv['certify_prune_rate']:.1f}%"
                     if sv.get("certify_prune_rate") is not None else "")
            incr = ""
            fe = sv.get("certify_forward_equivalents_per_request")
            if fe is not None and \
                    fe < sv["certify_forwards_per_request"] - 0.5:
                incr = f" ({fe} full-forward equivalents, incremental)"
            add(f"  certify forwards: "
                f"{sv['certify_forwards_per_request']}/request{incr}{prune}")

    rp = s.get("replicas")
    if rp:
        add("-- replicas --")
        add(f"  pool: {rp['count']} replica(s), {rp['restarts']} restart(s), "
            f"{rp['retired']} retired, "
            f"{rp['failed_over']} request(s) failed over"
            + (f", {rp['drain_timeouts']} drain timeout(s)"
               if rp["drain_timeouts"] else ""))
        for p in rp["per_replica"]:
            sick = (" sick[" + ", ".join(f"{k}: {v}" for k, v
                                         in sorted(p["sick_kinds"].items()))
                    + "]" if p["sick_kinds"] else "")
            restart = ""
            if "restart_s" in p:
                restart = (f" last restart {p['restart_s']}s "
                           f"({p['restart_traces']} trace(s))")
            add(f"  r{p['replica']}: gen {p['generation']}, "
                f"{p['batches']} batch(es), {p['restarts']} restart(s)"
                f"{sick}{restart}"
                + (" RETIRED" if p["retired"] else ""))

    ao = s.get("aot")
    if ao:
        add("-- aot --")
        rate = (f"{100.0 * ao['hit_rate']:.1f}%"
                if ao.get("hit_rate") is not None else "n/a")
        add(f"  executable store: {ao['loads']} load(s), "
            f"{ao['misses']} miss(es), {ao['builds']} build(s), "
            f"hit rate {rate}")
        if ao.get("miss_reasons"):
            add("  miss reasons: " + ", ".join(
                f"{k}: {v}" for k, v in ao["miss_reasons"].items()))
        bo = ao.get("boot")
        if bo:
            add(f"  warm boot [{bo['mode']}]: {bo['boot_s']}s to ready, "
                f"est {bo['saved_s']}s compile time saved")

    bl = s.get("baseline")
    if bl:
        add("-- program baseline --")
        verdict = "clean" if bl.get("clean") else "DRIFTED"
        add(f"  {verdict}: {bl.get('entries', '?')} entry point(s) vs "
            f"{bl.get('baseline_entries', '?')} baselined "
            f"(set {bl.get('fingerprint_set', '?')})")
        by_rule = bl.get("findings_by_rule") or {}
        if by_rule:
            add("  findings: "
                + ", ".join(f"{k}: {v}" for k, v in sorted(by_rule.items())))
            for f in (bl.get("findings") or [])[:8]:
                add(f"  {f.get('rule', '?')} {f.get('message', '')[:110]}")
        db = bl.get("dtype_bytes")
        if db and db.get("ratio") is not None:
            add(f"  bf16 bank: {db['paired_entries']} entry pair(s), "
                f"predicted HBM bytes ratio {db['ratio']} vs f32 twins")
        rows = bl.get("intensity") or []
        if rows:
            # estimated bytes accessed + arithmetic intensity (flops/byte)
            # per heaviest entry: low AI = bandwidth-bound, the programs
            # the Pallas kernel tier targets
            add("  heaviest entries (est bytes, flops/byte):")
            for r in rows:
                add(f"    {r.get('name', '?')}: "
                    f"{r.get('est_bytes', 0) / 1e6:,.1f} MB, "
                    f"AI {r.get('est_ai', 0):.2f}")

    add("-- heartbeats --")
    if not s["heartbeats"]:
        add("  (no heartbeat files)")
    for h in s["heartbeats"]:
        if not h.get("beats"):
            add(f"  {h['file']}: empty")
            continue
        flag = "  ** STALL **" if h.get("stalled") else ""
        exit_ = "clean exit" if h.get("clean_exit") else \
            f"last phase {h.get('last_phase', '')!r}"
        add(f"  {h['file']}: {h['beats']} beats, {exit_}, "
            f"median gap {h.get('median_gap_s')}s, "
            f"max {h.get('max_gap_s')}s{flag}")

    mr = s["metrics_records"]
    add("-- metrics.jsonl --")
    if mr["total"]:
        parts = ", ".join(f"{rid}: {n}" for rid, n in mr["by_attempt"].items())
        add(f"  {mr['total']} records across {len(mr['by_attempt'])} "
            f"attempt(s) ({parts})")
    else:
        add("  (no metrics records)")
    return "\n".join(lines)


# ---------------- cross-process fleet join (--fleet) ----------------


def summarize_fleet_dirs(dirs: List[str]) -> dict:
    """Merge several run/farm/recert dirs into one cross-process view.

    Two joins, both file-only:

    - **trace correlation** — every ingress (HTTP request, farm job claim,
      recert generation begin) records an `opens_trace` event carrying its
      trace id; every downstream record carries the same id (`trace` field,
      or the `traces` list on serve.batch span closes). An opened trace
      that no other record ever mentions is an ORPHAN: work that entered
      the system and left no telemetry of being answered.
    - **counter reconciliation** — the client-side registry snapshot
      (`metrics_client.json` from tools/loadgen.py) against the server-side
      snapshots (`metrics.json` from serve/farm/recert): per-status request
      counts must agree bit-for-bit, and the farm's outcome counters are
      folded in so a fleet that lost work cannot read as healthy.

    When a GATEWAY snapshot is among the dirs (a `metrics.json` carrying
    `gateway_requests_total`), the reconciliation becomes a three-way
    chain instead of the flat client-vs-server check: client counts must
    equal the gateway's per-status books (`kind: "client-gateway"`), and
    the gateway's per-backend response counts must equal the sum of the
    backends' own `serve_requests_total` books (`kind:
    "gateway-backend"`) — gateway-local rejects (fleet `overloaded`)
    live only in the first leg, and a SIGKILLed backend's unresolved
    batch is counted NOWHERE, so both legs stay exact across failover.
    """
    events: List[dict] = []
    event_files = 0
    server_snaps: List[dict] = []
    client_snaps: List[dict] = []
    for d in dirs:
        for root, _dirnames, files in os.walk(d):
            for fname in sorted(files):
                path = os.path.join(root, fname)
                if fname.startswith("events") and fname.endswith(".jsonl"):
                    event_files += 1
                    events.extend(_read_jsonl(path))
                elif fname == "metrics.json":
                    snap = _load_metrics_snapshot(path)
                    if snap is not None:
                        server_snaps.append(snap)
                elif fname == "metrics_client.json":
                    snap = _load_metrics_snapshot(path)
                    if snap is not None:
                        client_snaps.append(snap)

    opened: Dict[str, str] = {}
    closed: set = set()
    for r in events:
        ids = []
        trace = r.get("trace")
        if isinstance(trace, str) and trace:
            ids.append(trace)
        traces = r.get("traces")
        # only a LIST is a trace-id fan-out; `sanitize.retrace` events
        # reuse the key for an integer trace-cache size
        if isinstance(traces, (list, tuple)):
            for t in traces:
                if isinstance(t, str) and t:
                    ids.append(t)
        if not ids:
            continue
        if r.get("opens_trace"):
            for t in ids:
                opened.setdefault(t, str(r.get("name", "?")))
        else:
            closed.update(ids)
    orphans = sorted(t for t in opened if t not in closed)

    server_status = _sum_labeled(server_snaps, "serve_requests_total",
                                 "status")
    client_status = _sum_labeled(client_snaps, "loadgen_requests_total",
                                 "status")
    farm_outcomes = _sum_labeled(server_snaps, "farm_jobs_total", "outcome")
    recert_status = _sum_labeled(server_snaps, "recert_generations_total",
                                 "status")
    gateway_status = _sum_labeled(server_snaps, "gateway_requests_total",
                                  "status")
    gateway_backend_status = _sum_labeled(
        server_snaps, "gateway_backend_responses_total", "status")
    gateway_by_backend = _sum_labeled(
        server_snaps, "gateway_backend_responses_total", "backend")
    rollbacks = _sum_total(server_snaps, "gateway_rollbacks_total")
    autoscale = _sum_labeled(server_snaps, "gateway_autoscale_events_total",
                             "direction")

    checks: List[dict] = []
    if gateway_status:
        # gateway in the fleet: reconcile the chain, one leg at a time
        if client_snaps:
            for status in sorted(set(gateway_status) | set(client_status)):
                client_n = int(client_status.get(status, 0))
                gw_n = int(gateway_status.get(status, 0))
                checks.append({"kind": "client-gateway", "status": status,
                               "client": client_n, "server": gw_n,
                               "ok": client_n == gw_n})
        for status in sorted(set(gateway_backend_status)
                             | set(server_status)):
            gw_n = int(gateway_backend_status.get(status, 0))
            server_n = int(server_status.get(status, 0))
            checks.append({"kind": "gateway-backend", "status": status,
                           "client": gw_n, "server": server_n,
                           "ok": gw_n == server_n})
    elif client_snaps:
        for status in sorted(set(server_status) | set(client_status)):
            client_n = int(client_status.get(status, 0))
            server_n = int(server_status.get(status, 0))
            checks.append({"kind": "client-server", "status": status,
                           "client": client_n, "server": server_n,
                           "ok": client_n == server_n})
    consistent = all(c["ok"] for c in checks) and not orphans
    return {
        "dirs": [os.path.abspath(d) for d in dirs],
        "events_files": event_files,
        "records": len(events),
        "snapshots": {"server": len(server_snaps),
                      "client": len(client_snaps)},
        "traces": {"opened": len(opened), "closed_or_referenced": len(closed),
                   "orphans": orphans,
                   "opened_by_kind": _count_values(opened.values())},
        "requests": {"server_by_status": server_status,
                     "client_by_status": client_status},
        "gateway": {"by_status": gateway_status,
                    "backend_responses_by_status": gateway_backend_status,
                    "by_backend": gateway_by_backend,
                    "rollbacks": rollbacks,
                    "autoscale_by_direction": autoscale},
        "farm_jobs_by_outcome": farm_outcomes,
        "recert_generations_by_status": recert_status,
        "checks": checks,
        "consistent": consistent,
    }


def _load_metrics_snapshot(path: str) -> Optional[dict]:
    try:
        with open(path) as fh:
            snap = json.load(fh)
    except (OSError, ValueError):
        return None
    return snap if isinstance(snap, dict) and "metrics" in snap else None


def _sum_labeled(snaps: List[dict], name: str, label: str) -> Dict[str, int]:
    """Sum one counter's series across snapshots, keyed by `label` value."""
    out: Dict[str, int] = {}
    for snap in snaps:
        for value, count in labeled_values(snap, name, label).items():
            out[value] = out.get(value, 0) + int(count)
    return dict(sorted(out.items()))


def _sum_total(snaps: List[dict], name: str) -> int:
    """Sum one counter's every series across snapshots (label-blind)."""
    total = 0.0
    for snap in snaps:
        metric = (snap or {}).get("metrics", {}).get(name)
        if not isinstance(metric, dict):
            continue
        for s in metric.get("series", ()):
            total += float(s.get("value", 0.0))
    return int(total)


def _count_values(values) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for v in values:
        out[v] = out.get(v, 0) + 1
    return dict(sorted(out.items()))


def format_fleet_dirs(s: dict) -> str:
    """Human rendering of a `summarize_fleet_dirs()` dict."""
    lines: List[str] = []
    add = lines.append
    add("= DorPatch fleet telemetry report =")
    for d in s["dirs"]:
        add(f"dir: {d}")
    add(f"records: {s['records']} across {s['events_files']} events file(s); "
        f"{s['snapshots']['server']} server / {s['snapshots']['client']} "
        f"client metric snapshot(s)")
    add("-- cross-process --")
    tr = s["traces"]
    kinds = ", ".join(f"{k}: {v}" for k, v in tr["opened_by_kind"].items())
    add(f"  traces opened: {tr['opened']} ({kinds or 'none'})")
    if tr["orphans"]:
        add(f"  !! ORPHANED traces ({len(tr['orphans'])}): work entered but "
            "no other record ever mentioned it")
        for t in tr["orphans"][:8]:
            add(f"     {t}")
    else:
        add("  orphaned traces: 0 — every ingress joined to downstream "
            "telemetry")
    rq = s["requests"]
    if rq["server_by_status"]:
        add("  server requests: " + ", ".join(
            f"{k}: {v}" for k, v in rq["server_by_status"].items()))
    if rq["client_by_status"]:
        add("  client requests: " + ", ".join(
            f"{k}: {v}" for k, v in rq["client_by_status"].items()))
    gw = s.get("gateway") or {}
    if gw.get("by_status"):
        add("  gateway requests: " + ", ".join(
            f"{k}: {v}" for k, v in gw["by_status"].items()))
    if gw.get("by_backend"):
        add("  gateway responses by backend: " + ", ".join(
            f"{k}: {v}" for k, v in gw["by_backend"].items()))
    if gw.get("by_status") or gw.get("rollbacks"):
        add(f"  gateway rollbacks: {gw.get('rollbacks', 0)}")
    if gw.get("autoscale_by_direction"):
        add("  gateway autoscale signals: " + ", ".join(
            f"{k}: {v}" for k, v in gw["autoscale_by_direction"].items()))
    for c in s["checks"]:
        verdict = "ok" if c["ok"] else "MISMATCH"
        kind = c.get("kind", "client-server")
        left, right = (kind.split("-") + ["server"])[:2]
        add(f"  [{verdict:>8}] {kind} {c['status']}: {left} {c['client']} "
            f"vs {right} {c['server']}")
    if s["farm_jobs_by_outcome"]:
        add("  farm jobs: " + ", ".join(
            f"{k}: {v}" for k, v in s["farm_jobs_by_outcome"].items()))
    if s["recert_generations_by_status"]:
        add("  recert generations: " + ", ".join(
            f"{k}: {v}" for k, v in
            s["recert_generations_by_status"].items()))
    add("  consistent: " + ("yes" if s["consistent"] else "NO"))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dorpatch_tpu.observe.report",
        description="Offline telemetry report for a DorPatch results dir")
    p.add_argument("result_dir", nargs="?", default=None,
                   help="results dir holding run.json / "
                        "events.jsonl / metrics.jsonl / "
                        "heartbeat_*.jsonl")
    p.add_argument("--fleet", nargs="+", metavar="DIR",
                   help="merge several run/farm/recert dirs: cross-process "
                        "trace correlation + client/server counter "
                        "reconciliation")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable summary instead of text")
    p.add_argument("--stall-factor", type=float, default=5.0,
                   help="heartbeat gap > factor x median interval = stall")
    args = p.parse_args(argv)

    if args.fleet:
        bad = [d for d in args.fleet if not os.path.isdir(d)]
        if bad:
            print(f"not a directory: {', '.join(bad)}")
            return 2
        fleet = summarize_fleet_dirs(args.fleet)
        try:
            if args.json:
                print(json.dumps(fleet, indent=1, default=float))
            else:
                print(format_fleet_dirs(fleet))
        except BrokenPipeError:
            return 0
        return 0 if fleet["consistent"] else 1
    if args.result_dir is None:
        p.error("result_dir is required unless --fleet is given")
    if not os.path.isdir(args.result_dir):
        print(f"not a directory: {args.result_dir}")
        return 2
    # a recert dir (marked by recert_state.json) gets the re-certification
    # report: scheduler status + the latest verdict; lazy, host-only import
    if os.path.exists(os.path.join(args.result_dir, "recert_state.json")):
        from dorpatch_tpu.checkpoint import load_json
        from dorpatch_tpu.recert.scheduler import RecertScheduler

        sched = RecertScheduler(args.result_dir)
        st = sched.status()
        verdict = load_json(sched.verdict_path)
        try:
            if args.json:
                print(json.dumps({"status": st, "verdict": verdict},
                                 indent=1, default=float))
            else:
                print(_format_recert_report(
                    st, verdict if isinstance(verdict, dict) else None))
        except BrokenPipeError:
            return 0
        return 0
    # a farm dir (marked by farm.json) gets the fleet-level report; the
    # import is lazy and farm.report is host-only, same contract as here
    farm_marker = os.path.join(args.result_dir, "farm.json")
    if os.path.exists(farm_marker):
        from dorpatch_tpu.farm.report import (format_fleet_report,
                                              summarize_fleet)

        fleet = summarize_fleet(args.result_dir)
        try:
            if args.json:
                print(json.dumps(fleet, indent=1, default=float))
            else:
                print(format_fleet_report(fleet))
        except BrokenPipeError:
            return 0
        return 0
    s = summarize(args.result_dir, stall_factor=args.stall_factor)
    if not s["manifest"] and not s["attempts"] and not s["heartbeats"] \
            and not s["metrics_records"]["total"]:
        print(f"no telemetry files under {args.result_dir} "
              f"(expected {MANIFEST_NAME} / events.jsonl / metrics.jsonl / "
              "heartbeat_*.jsonl)")
        return 2
    try:
        if args.json:
            print(json.dumps(s, indent=1, default=float))
        else:
            print(format_report(s))
    except BrokenPipeError:
        return 0  # `report ... | head` is a legitimate way to read this
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
