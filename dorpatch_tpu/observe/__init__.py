"""Observability subsystem: run telemetry for the whole framework.

The reference's only observability is tqdm plus a print of the loss
breakdown every 20 iterations (`/root/reference/attack.py:318-330`) and
per-run result prints (`main.py:186-187`). Here that is a real telemetry
layer; every results dir carries a self-describing contract:

- `run.json`            — run manifest (`manifest.py`): resolved config,
  jax/jaxlib versions, device kind/topology, process count, hostname,
  git SHA, per-attempt run_id.
- `metrics.jsonl`       — the attack's on-device metrics vector per jitted
  block (`attack_log.AttackMetricsLogger`), run_id-stamped per attempt.
- `events.jsonl`        — per-process span/event log (`events.EventLog`):
  nested spans (`run`, `batch`, `attack.stage0/1`, `certify`,
  `artifact_io`, ...), jit-compile durations, device-memory samples.
- `heartbeat_<proc>.jsonl` — daemon-thread heartbeats per process
  (`heartbeat.Heartbeat`), the post-mortem for hung collectives; the
  `--hang-timeout` watchdog (`heartbeat.Watchdog`) aborts instead of
  hanging forever.

`python -m dorpatch_tpu.observe.report <results_dir>` joins all of it into
a human summary (`report.py`). `StepTimer`/`trace` (`timing.py`) and
`console.log` round out the surface. Every name that predates the package
(`AttackMetricsLogger`, `StepTimer`, `trace`, `METRIC_NAMES`) stays
importable from `dorpatch_tpu.observe`.
"""

from dorpatch_tpu.observe.attack_log import (  # noqa: F401
    METRIC_NAMES,
    AttackMetricsLogger,
)
from dorpatch_tpu.observe.console import (  # noqa: F401
    elapsed,
    log,
    process_index,
    set_process_index,
)
from dorpatch_tpu.observe.events import (  # noqa: F401
    EventLog,
    active,
    active_event_log,
    aot_resolver,
    device_memory_stats,
    entrypoint_recorder,
    events_filename,
    record_compile,
    record_event,
    recompile_guard,
    set_aot_resolver,
    set_entrypoint_recorder,
    set_recompile_guard,
    span,
    timed_first_call,
)
from dorpatch_tpu.observe.heartbeat import (  # noqa: F401
    Heartbeat,
    Watchdog,
    heartbeat_filename,
    heartbeat_gaps,
    last_beat,
    last_beat_ts,
    read_heartbeats,
    summarize_heartbeats,
)
from dorpatch_tpu.observe.manifest import (  # noqa: F401
    jax_environment,
    new_run_id,
    new_trace_id,
    run_manifest,
    write_run_manifest,
)
from dorpatch_tpu.observe.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    labeled_values,
    parse_exposition,
)
from dorpatch_tpu.observe.timing import (  # noqa: F401
    StepTimer,
    capture_profile,
    nearest_rank_percentile,
    trace,
)

__all__ = [
    "METRIC_NAMES",
    "AttackMetricsLogger",
    "Counter",
    "EventLog",
    "Gauge",
    "Heartbeat",
    "Histogram",
    "MetricRegistry",
    "StepTimer",
    "Watchdog",
    "active",
    "active_event_log",
    "aot_resolver",
    "capture_profile",
    "device_memory_stats",
    "elapsed",
    "entrypoint_recorder",
    "events_filename",
    "heartbeat_filename",
    "heartbeat_gaps",
    "jax_environment",
    "labeled_values",
    "last_beat",
    "last_beat_ts",
    "log",
    "nearest_rank_percentile",
    "new_run_id",
    "new_trace_id",
    "parse_exposition",
    "process_index",
    "read_heartbeats",
    "record_compile",
    "record_event",
    "recompile_guard",
    "run_manifest",
    "set_aot_resolver",
    "set_entrypoint_recorder",
    "set_process_index",
    "set_recompile_guard",
    "span",
    "summarize_heartbeats",
    "timed_first_call",
    "trace",
    "write_run_manifest",
]
