"""Run manifest: `run.json` makes every results dir self-describing.

`config.json` (artifacts.write_config_record) records WHAT was asked for;
`run.json` records what actually RAN it — library versions, device kind and
topology, process count, hostname, git SHA — plus the per-attempt `run_id`
that stamps every metrics/events/heartbeat record. Resumed runs overwrite
`run.json` with the newest attempt but chain the older ids into
`previous_run_ids`, so the report CLI can enumerate attempts even before
reading the JSONL files.

Host-only by construction: nothing here imports jax/torch. The jax pipeline
passes `jax_environment()` (which reads the live backend) as `extra`; the
torch pipeline passes its own backend blurb.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform
import socket
import subprocess
import sys
import time
import uuid
from typing import Optional

MANIFEST_NAME = "run.json"


def new_run_id() -> str:
    """Per-process, per-attempt id stamped onto every telemetry record."""
    return uuid.uuid4().hex[:12]


def new_trace_id() -> str:
    """Per-REQUEST correlation id, minted once at ingress (HTTP request,
    farm job claim, recert generation) and threaded through every process
    that touches the work — one adversarial query is one joinable identity
    across `events.jsonl` files (`observe.report --fleet`)."""
    return uuid.uuid4().hex[:16]


def git_sha() -> Optional[str]:
    """Best-effort SHA of the checkout this package runs from."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=5)
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def jax_environment() -> dict:
    """Backend/topology blurb for the manifest — call ONLY from code that
    already owns the jax backend (touching `jax.devices()` initializes it)."""
    import jax

    info = {"backend_impl": "jax", "jax": jax.__version__}
    try:
        import jaxlib

        info["jaxlib"] = getattr(
            jaxlib, "__version__",
            getattr(getattr(jaxlib, "version", None), "__version__", None))
    except Exception:
        pass
    try:
        devs = jax.devices()
        info.update({
            "backend": jax.default_backend(),
            "device_kind": str(devs[0].device_kind) if devs else "",
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "process_count": jax.process_count(),
            "process_index": jax.process_index(),
        })
    except Exception as e:  # backend refused to come up: record why
        info["backend_error"] = repr(e)
    return info


def run_manifest(cfg=None, run_id: str = "", extra: Optional[dict] = None,
                 clock=time.time) -> dict:
    """Assemble the manifest dict (pure; `write_run_manifest` persists it)."""
    m = {
        "schema": 1,
        "run_id": run_id,
        "started_ts": round(clock(), 3),
        "started_iso": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "platform": platform.platform(),
        "python": sys.version.split()[0],
        "argv": list(sys.argv),
    }
    sha = git_sha()
    if sha:
        m["git_sha"] = sha
    if cfg is not None:
        m["config"] = (dataclasses.asdict(cfg)
                       if dataclasses.is_dataclass(cfg) else dict(cfg))
    if extra:
        m.update(extra)
    return m


def write_run_manifest(result_dir: str, cfg=None, run_id: str = "",
                       extra: Optional[dict] = None) -> Optional[str]:
    """Write `run.json` at experiment start; returns its path (None when the
    results dir is read-only — telemetry must never fail the run). A prior
    manifest's run_id is chained into `previous_run_ids`."""
    path = os.path.join(result_dir, MANIFEST_NAME)
    previous = []
    try:
        with open(path) as fh:
            old = json.load(fh)
        previous = [old["run_id"]] if old.get("run_id") else []
        previous += list(old.get("previous_run_ids", []))
    except (OSError, ValueError, KeyError):
        pass
    m = run_manifest(cfg, run_id=run_id, extra=extra)
    if previous:
        m["previous_run_ids"] = previous
    try:
        os.makedirs(result_dir, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(m, fh, indent=1, default=float)
    except OSError:
        return None
    return path
