"""Multi-host heartbeats + hang watchdog.

The SPMD driver's documented failure mode (`parallel/multiproc.py`) is a
collective mismatch: one process takes a different jit-call branch and every
OTHER process blocks forever inside a collective, producing no output at
all. Two tools make that diagnosable:

- `Heartbeat` — a daemon thread per process appending `{ts, seq, phase}`
  beats to `heartbeat_<proc>.jsonl`. The main thread being wedged inside a
  device call does not stop the beats; what stops changing is the `phase`
  (the event log's current span path). Post-mortem, the per-process files
  show exactly which phase each process last entered.
- `Watchdog` — armed by `--hang-timeout`: when the process's EventLog has
  written nothing for longer than the timeout (heartbeats deliberately do
  not count as progress), it prints the last-known phase of EVERY process
  from the heartbeat files and aborts (`os._exit`) instead of hanging
  forever. The timeout must exceed the longest legitimate single jitted
  block (compile included), or a slow compile reads as a hang.

`read_heartbeats` / `heartbeat_gaps` / `summarize_heartbeats` are the
offline halves, shared with the report CLI's stall detection.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Callable, Dict, IO, List, Optional

from dorpatch_tpu.observe import console


def heartbeat_filename(process_index: int = 0) -> str:
    return f"heartbeat_{process_index}.jsonl"


class Heartbeat:
    """Daemon-thread JSONL heartbeat; context manager starts/stops it."""

    def __init__(self, path: Optional[str],
                 get_phase: Optional[Callable[[], str]] = None,
                 interval: float = 5.0, process_index: int = 0,
                 run_id: str = "", clock=time.time,
                 extra: Optional[Callable[[], Dict]] = None):
        self.path = path
        self.interval = max(float(interval), 0.01)
        self.process_index = process_index
        self.run_id = run_id
        self._get_phase = get_phase
        # `extra` folds a caller dict into every beat (farm workers ship
        # their live job counters this way, so `farm report` can show
        # throughput without waiting for the run to finish)
        self._get_extra = extra
        self._clock = clock
        self._seq = 0  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._wedged = False
        self._fh: Optional[IO[str]] = None  # guarded-by: self._lock
        if path:
            try:
                os.makedirs(os.path.dirname(os.path.abspath(path)),
                            exist_ok=True)
                self._fh = open(path, "a", buffering=1)
            except OSError:
                self._fh = None

    def beat(self, phase: Optional[str] = None) -> dict:
        if phase is None:
            phase = self._get_phase() if self._get_phase is not None else ""
        extra = {}
        if self._get_extra is not None:
            try:
                extra = dict(self._get_extra())
            except Exception:
                extra = {}  # a broken producer must not stop the beats
        with self._lock:
            rec = {"ts": round(self._clock(), 3), "seq": self._seq,
                   "phase": phase, "proc": self.process_index,
                   "pid": os.getpid()}
            for key, value in extra.items():
                rec.setdefault(str(key), value)
            if self.run_id:
                rec["run_id"] = self.run_id
            self._seq += 1
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec) + "\n")
                except OSError:
                    # disk full mid-run: stop persisting, keep beating (the
                    # thread must not die with an unlogged exception)
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
        return rec

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.beat()

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self.beat()  # first beat immediately: short runs still leave one
            self._thread = threading.Thread(
                target=self._loop, name="dorpatch-heartbeat", daemon=True)
            self._thread.start()
        return self

    def wedge(self) -> None:
        """Stop beating WITHOUT the final `exit` beat — the file freezes at
        the last ordinary beat, exactly what a process stuck inside a device
        call (or SIGSTOP'd) looks like from the outside. Chaos/test hook:
        the farm's lease expiry is driven by beat staleness, so wedging a
        live worker is how the reclaim path is exercised without killing
        the process that injects the fault."""
        self._wedged = True  # close() must not append the exit beat either
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval + 1.0)
            self._thread = None

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval + 1.0)
            self._thread = None
        if self._fh is not None:
            if not self._wedged:
                # outside the lock: beat() takes the same non-reentrant lock
                self.beat(phase="exit")  # clean shutdown visible post-mortem
            with self._lock:
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


# ---------------- offline readers (shared with the report CLI) ----------------


def read_heartbeats(result_dir: str) -> Dict[str, List[dict]]:
    """{heartbeat filename: [beats...]} for every process's file, bad lines
    skipped (a beat truncated by an abort must not kill the post-mortem)."""
    out: Dict[str, List[dict]] = {}
    for path in sorted(glob.glob(os.path.join(result_dir, "heartbeat_*.jsonl"))):
        beats = []
        try:
            # errors="replace": a beat truncated mid-multibyte-char (SIGKILL
            # between write syscalls) must not raise UnicodeDecodeError; the
            # mangled line then fails json parsing and is skipped like any
            # other partial line.
            with open(path, errors="replace") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        beats.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
        out[os.path.basename(path)] = beats
    return out


def last_beat(path: str) -> Optional[dict]:
    """The newest parseable beat RECORD in ONE heartbeat file, or None
    when the file is missing/empty/unreadable.

    This is the farm's lease-liveness primitive: a worker's lease is fresh
    exactly while its heartbeat file keeps advancing, so the reader must be
    cheap (tail read, not a full parse) and must tolerate a final line
    truncated by the very crash it is there to detect. Callers that care
    about liveness under wall-clock skew should prefer the monotonic
    ``seq`` field over ``ts`` (`farm.queue.lease_fresh` tracks seq
    advancement against its OWN clock)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - 8192))
            tail = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
            float(rec["ts"])  # a beat without a parseable ts is torn
        except (ValueError, KeyError, TypeError):
            continue
        return rec
    return None


def last_beat_ts(path: str) -> Optional[float]:
    """Timestamp of the newest parseable beat (see `last_beat`)."""
    rec = last_beat(path)
    return None if rec is None else float(rec["ts"])


def heartbeat_gaps(beats: List[dict]) -> List[float]:
    """Gaps (seconds) between consecutive beats of the SAME attempt —
    run_id changes are resume boundaries, not stalls."""
    gaps = []
    prev = None
    for b in beats:
        if prev is not None and b.get("run_id") == prev.get("run_id"):
            gaps.append(float(b["ts"]) - float(prev["ts"]))
        prev = b
    return gaps


def summarize_heartbeats(result_dir: str, stall_factor: float = 5.0,
                         min_gap: float = 1.0) -> List[dict]:
    """Per-process stall summary: a gap is a stall when it exceeds both
    `stall_factor` x the median beat interval and `min_gap` seconds."""
    rows = []
    for fname, beats in read_heartbeats(result_dir).items():
        if not beats:
            rows.append({"file": fname, "beats": 0})
            continue
        gaps = heartbeat_gaps(beats)
        med = sorted(gaps)[len(gaps) // 2] if gaps else 0.0
        max_gap = max(gaps) if gaps else 0.0
        last = beats[-1]
        rows.append({
            "file": fname,
            "proc": last.get("proc"),
            "beats": len(beats),
            "last_phase": last.get("phase", ""),
            "last_ts": last.get("ts"),
            "clean_exit": last.get("phase") == "exit",
            "median_gap_s": round(med, 3),
            "max_gap_s": round(max_gap, 3),
            "stalled": bool(gaps) and max_gap > max(stall_factor * med,
                                                    min_gap),
        })
    return rows


class Watchdog:
    """Abort a wedged run instead of hanging forever (`--hang-timeout`).

    Progress signal: the EventLog's `seconds_since_activity()` — any record
    written (span edge, block boundary, compile) resets it. On expiry the
    watchdog prints every process's last-known phase from the heartbeat
    files, then calls `on_abort` (default `os._exit(2)`, because the main
    thread is presumed stuck inside a device call that no exception can
    reach)."""

    def __init__(self, result_dir: str, event_log, timeout_s: float,
                 interval: Optional[float] = None,
                 on_abort: Optional[Callable[[], None]] = None,
                 echo=console.log, clock=time.time):
        self.result_dir = result_dir
        self.event_log = event_log
        self.timeout_s = float(timeout_s)
        self.interval = (interval if interval is not None
                         else max(min(self.timeout_s / 4.0, 5.0), 0.05))
        self._on_abort = on_abort if on_abort is not None else (
            lambda: os._exit(2))
        self._echo = echo
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check(self) -> bool:
        """One poll; fires (and returns True) when the timeout has expired."""
        idle = self.event_log.seconds_since_activity()
        if idle <= self.timeout_s:
            return False
        self.fire(idle)
        return True

    def fire(self, idle: float) -> None:
        import sys

        echo = self._echo
        echo(f"WATCHDOG: no telemetry progress for {idle:.1f}s "
             f"(--hang-timeout {self.timeout_s:g}s); "
             "last-known phase per process:", file=sys.stderr)
        now = self._clock()
        beats_by_file = read_heartbeats(self.result_dir)
        if not beats_by_file:
            echo("  (no heartbeat files found)", file=sys.stderr)
        for fname, beats in beats_by_file.items():
            if not beats:
                echo(f"  {fname}: empty", file=sys.stderr)
                continue
            last = beats[-1]
            echo(f"  {fname}: phase={last.get('phase', '')!r} "
                 f"last beat {now - float(last['ts']):.1f}s ago "
                 f"(seq {last.get('seq')})", file=sys.stderr)
        echo("aborting (a hung collective cannot be unwound in-process)",
             file=sys.stderr)
        self._on_abort()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if self.check():
                return

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="dorpatch-watchdog", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval + 1.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
