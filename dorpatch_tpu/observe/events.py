"""Span/event log: nested spans, jit-compile counters, device-memory samples.

One `events.jsonl` per process under the results dir (`events_filename`).
Every record carries a wall timestamp, the process index, the per-attempt
`run_id`, and a monotonically increasing `seq` — so records from a resumed
run group by attempt and a post-mortem reader can totally order one
process's telemetry even when wall clocks step.

Record kinds:

- `begin` / `span`  — a `begin` is written when a span opens, the matching
  `span` (with `dur_s` and any attributes) when it closes. A `begin` with
  no closing `span` is the signature of a hang: the last open path IS the
  phase the process died in (see `heartbeat.Watchdog`).
- `block`           — one jitted attack block finished (`DorPatch.on_block_end`
  boundary): stage, cumulative step, wall duration since the previous
  telemetry mark, and a `device.memory_stats()` sample when the backend
  provides one.
- `compile`         — first call of a jitted entry point (`timed_first_call`
  wraps the attack/defense jit programs), i.e. compile + first dispatch
  wall time. The report CLI sums these into compile-vs-run accounting.
- `event`           — free-form point event.

The module-level `span()` / `record_event()` / `record_compile()` helpers
delegate to the process's ACTIVE EventLog and no-op when none is installed,
so the attack/defense/train layers can emit telemetry without holding a
reference to (or even knowing about) the sink the driver configured.

Spans are main-thread only (the stack is per-process, not per-thread); the
heartbeat thread only *reads* `current_path()` under the lock.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import sys
import threading
import time
from typing import IO, List, Optional


def events_filename(process_index: int = 0) -> str:
    """Per-process event-log name; process 0 keeps the bare `events.jsonl`."""
    return ("events.jsonl" if process_index == 0
            else f"events_{process_index}.jsonl")


def device_memory_stats() -> Optional[List[dict]]:
    """Per-device `memory_stats()` sample, or None when unavailable.

    Reads jax from `sys.modules` instead of importing it: a host-only
    consumer (the report CLI, the torch backend) must never initialize the
    accelerator backend as a side effect of telemetry. CPU devices without
    allocator stats simply yield nothing."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        devices = jax.local_devices()
    except Exception:
        return None
    out = []
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        rec = {"device": int(getattr(d, "id", -1))}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in ms:
                rec[k] = int(ms[k])
        out.append(rec)
    return out or None


class EventLog:
    """Append-mode JSONL event sink with a nested-span stack.

    An unwritable results dir degrades to a no-file sink that still tracks
    the span stack (the heartbeat's phase and the watchdog's activity clock
    keep working; only persistence is lost) — same contract as the
    pipeline's best-effort `summary.json` write."""

    def __init__(self, path: Optional[str], run_id: str = "",
                 process_index: int = 0, clock=time.time,
                 perf=time.perf_counter):
        self.path = path
        self.run_id = run_id
        self.process_index = process_index
        self._clock = clock
        self._perf = perf
        self._lock = threading.RLock()
        self._seq = 0
        self._stack = []  # [(name, perf_t0)]
        self._last_mark = perf()
        self._last_activity = perf()
        self._fh: Optional[IO[str]] = None
        self._active_cms: List = []  # install-and-restore stack (__enter__)
        if path:
            try:
                os.makedirs(os.path.dirname(os.path.abspath(path)),
                            exist_ok=True)
                self._fh = open(path, "a", buffering=1)
            except OSError:
                self._fh = None

    # ---------------- record plumbing ----------------

    def _write(self, kind: str, name: Optional[str] = None, **fields) -> dict:
        with self._lock:
            rec = {"ts": round(self._clock(), 3), "seq": self._seq,
                   "proc": self.process_index, "run_id": self.run_id,
                   "kind": kind}
            if name is not None:
                rec["name"] = name
            rec.update(fields)
            self._seq += 1
            self._last_activity = self._perf()
            if self._fh is not None:
                try:
                    self._fh.write(json.dumps(rec, default=float) + "\n")
                except OSError:
                    # disk full / quota mid-run: telemetry must never take
                    # down the computation it observes — drop to the
                    # tracking-only sink (same contract as a failed open)
                    try:
                        self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
            return rec

    def seconds_since_activity(self) -> float:
        """Seconds since the main thread last wrote any record — the
        watchdog's liveness signal. Heartbeat beats deliberately do NOT
        count: they prove the process is alive, not that it progresses."""
        with self._lock:
            return self._perf() - self._last_activity

    def current_path(self) -> str:
        """`run/batch/attack.stage1`-style phase path (heartbeat payload)."""
        with self._lock:
            return "/".join(n for n, _ in self._stack) or "idle"

    # ---------------- span / event API ----------------

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Nested span; yields a mutable attrs dict — entries added inside
        the scope (e.g. the batch size discovered mid-span) land on the
        closing `span` record."""
        with self._lock:
            t0 = self._perf()
            self._stack.append((name, t0))
            depth = len(self._stack) - 1
            path = "/".join(n for n, _ in self._stack)
            self._last_mark = t0
        self._write("begin", name, path=path, depth=depth)
        out_attrs = dict(attrs)
        try:
            yield out_attrs
        finally:
            t1 = self._perf()
            with self._lock:
                self._stack.pop()
                self._last_mark = t1
            self._write("span", name, path=path, depth=depth,
                        dur_s=round(t1 - t0, 6), **out_attrs)

    def event(self, name: str, **attrs) -> None:
        self._write("event", name, **attrs)

    def compile(self, name: str, seconds: float) -> None:
        self._write("compile", name, dur_s=round(seconds, 6))

    def block_boundary(self, stage: int, step: int,
                       info: Optional[dict] = None) -> None:
        """One attack block finished: duration since the previous telemetry
        mark (span edge or block) plus a device-memory sample."""
        with self._lock:
            now = self._perf()
            dur = now - self._last_mark
            self._last_mark = now
        fields = {"stage": int(stage), "step": int(step),
                  "dur_s": round(dur, 6)}
        if info is not None:
            fields["stopped"] = bool(info.get("stopped", False))
        mem = device_memory_stats()
        if mem:
            fields["mem"] = mem
        self._write("block", None, **fields)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        # entering the log installs it as the process's active sink (and
        # exiting restores the previous one), so a bare
        # `with EventLog(path) as el:` wires up the module-level
        # span()/record_event() helpers. Historically __enter__ only
        # returned self — telemetry silently went nowhere unless the caller
        # also remembered `with observe.active(el):`, which remains legal
        # but redundant.
        cm = active(self)
        cm.__enter__()
        self._active_cms.append(cm)
        return self

    def __exit__(self, *exc):
        if self._active_cms:
            self._active_cms.pop().__exit__(None, None, None)
        self.close()


# ---------------- process-wide active log ----------------

_ACTIVE: Optional[EventLog] = None


def active_event_log() -> Optional[EventLog]:
    return _ACTIVE


@contextlib.contextmanager
def active(elog: Optional[EventLog]):
    """Install `elog` as the process's active sink for the scope (None is a
    legal no-op, so callers don't need to branch on telemetry being off)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = elog
    try:
        yield elog
    finally:
        _ACTIVE = prev


@contextlib.contextmanager
def span(name: str, **attrs):
    """Span against the active EventLog; no-op when telemetry is off.

    Always yields a dict callers may add attributes to — a throwaway one
    when no log is active, so call sites never branch on telemetry."""
    el = _ACTIVE
    if el is None:
        yield dict(attrs)
        return
    with el.span(name, **attrs) as out_attrs:
        yield out_attrs


def record_event(name: str, **attrs) -> None:
    el = _ACTIVE
    if el is not None:
        el.event(name, **attrs)


def record_compile(name: str, seconds: float) -> None:
    el = _ACTIVE
    if el is not None:
        el.compile(name, seconds)


# ---------------- recompile guard hook (analysis/sanitize.py) ----------------

# Installed by the runtime sanitizer (`dorpatch_tpu.analysis.sanitize`): an
# object with `after_call(name, wrapped, budget)` inspected after EVERY call
# through a _FirstCallTimer. Lives here (not in analysis/) so observe never
# imports the analysis package; None means no enforcement.
_RECOMPILE_GUARD = None


def set_recompile_guard(guard) -> None:
    global _RECOMPILE_GUARD
    _RECOMPILE_GUARD = guard


def recompile_guard():
    return _RECOMPILE_GUARD


# ------------- entry-point recorder hook (analysis/entrypoints.py) -----------

# Installed by the program auditor (`dorpatch_tpu.analysis.entrypoints`): an
# object whose `on_wrap(name, fn)` fires when `timed_first_call` wraps a
# jitted entry point and whose `on_call(name, fn, args, kwargs)` fires before
# every invocation through the wrapper — which is how the auditor learns the
# exact (name, program, example arguments) production compiles, without
# observe ever importing the analysis package. None means no recording.
_ENTRYPOINT_RECORDER = None


def set_entrypoint_recorder(recorder) -> None:
    global _ENTRYPOINT_RECORDER
    _ENTRYPOINT_RECORDER = recorder


def entrypoint_recorder():
    return _ENTRYPOINT_RECORDER


# ---------------- aot warm-boot resolver hook (dorpatch_tpu/aot) -------------

# Installed by the AOT warm-boot layer (`dorpatch_tpu.aot.boot`): an object
# whose `before_first_call(name, wrapped, args, kwargs)` fires exactly once,
# on a timer's FIRST invocation, and may return a replacement callable (a
# store-backed dispatcher serving a pre-compiled executable) to install as
# `__wrapped__` before the timed call runs. Returning None keeps the original
# program. Lives here so observe never imports the aot package; None means
# no warm boot.
_AOT_RESOLVER = None


def set_aot_resolver(resolver) -> None:
    global _AOT_RESOLVER
    _AOT_RESOLVER = resolver


def aot_resolver():
    return _AOT_RESOLVER


class _FirstCallTimer:
    """Callable proxy recording the wrapped fn's first-call wall time as a
    `compile` event. Unknown attributes delegate to the wrapped callable, so
    a wrapped `jax.jit` object keeps its full API (`.lower()`, `.trace()`,
    ... — the HLO-inspection tests and tools rely on it)."""

    def __init__(self, fn, name: str, clock, recompile_budget=None):
        self.__wrapped__ = fn
        self._name = name
        self._clock = clock
        self._done = False
        self.recompile_budget = recompile_budget
        functools.update_wrapper(self, fn, updated=())

    def __call__(self, *args, **kwargs):
        recorder = _ENTRYPOINT_RECORDER
        if recorder is not None:
            # fires BEFORE dispatch: the auditor only needs the abstract
            # argument shapes, never the execution
            recorder.on_call(self._name, self.__wrapped__, args, kwargs)
        if self._done:
            out = self.__wrapped__(*args, **kwargs)
        else:
            self._done = True
            resolver = _AOT_RESOLVER
            if resolver is not None:
                # warm boot: swap in a pre-compiled executable before the
                # first (otherwise compiling) dispatch; the resolver returns
                # None to decline and never raises
                replacement = resolver.before_first_call(
                    self._name, self.__wrapped__, args, kwargs)
                if replacement is not None:
                    self.__wrapped__ = replacement
            t0 = self._clock()
            out = self.__wrapped__(*args, **kwargs)
            record_compile(self._name, self._clock() - t0)
        guard = _RECOMPILE_GUARD
        if guard is not None:
            guard.after_call(self._name, self.__wrapped__,
                             self.recompile_budget)
        return out

    def __getattr__(self, item):
        return getattr(self.__wrapped__, item)


def timed_first_call(fn, name: str, clock=time.perf_counter,
                     recompile_budget=None):
    """Wrap a jitted callable so its FIRST invocation's wall time is
    recorded as a `compile` event (trace + XLA compile happen synchronously
    inside that call; execution dispatch is the tail). Subsequent calls pass
    through untimed. Recording goes to whatever EventLog is active at
    first-call time — none active, nothing recorded.

    `recompile_budget` declares how many traces (shape/dtype buckets) this
    entry point is allowed — its `_cache_size()` upper bound. It is inert
    until the runtime sanitizer installs a recompile guard
    (`--sanitize`; `analysis/sanitize.py`), which then checks the wrapped
    jit's cache growth after every call and fails the run on excess.

    When an entry-point recorder is installed (`set_entrypoint_recorder`;
    the program auditor's capture mode), every wrap is reported through
    `on_wrap(name, fn)` and every call through `on_call(name, fn, args,
    kwargs)` — which is how `python -m dorpatch_tpu.analysis --trace`
    discovers the production jit entry points without observe importing
    the analysis package."""
    recorder = _ENTRYPOINT_RECORDER
    if recorder is not None:
        recorder.on_wrap(name, fn)
        # optional hook (baseline tier's DP303): the declared recompile
        # budget is wrapper metadata, invisible through on_wrap's raw fn
        on_budget = getattr(recorder, "on_budget", None)
        if on_budget is not None:
            on_budget(name, recompile_budget)
    return _FirstCallTimer(fn, name, clock, recompile_budget)
